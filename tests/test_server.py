"""HTTP front door e2e: concurrent clients against a live EngineServer
must each receive the stream the single-request oracle predicts, while
admission interleaves with running decode (continuous batching over
the wire — the native counterpart of the reference's vllm-serve curl
smoke test, /root/reference/README.md:144-156)."""

import http.client
import json
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_k8s_device_plugin.workloads.inference import (
    greedy_generate,
    make_decoder,
)
from tpu_k8s_device_plugin.workloads.server import EngineServer, _Request
from tpu_k8s_device_plugin.workloads.serving import ServingEngine

CFG = dict(vocab=128, d_model=64, n_heads=4, n_layers=2, d_ff=128)


@pytest.fixture(scope="module")
def setup():
    model = make_decoder(**CFG, max_len=64, dtype=jnp.float32)
    rng = jax.random.PRNGKey(0)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    params = model.init(rng, tokens, pos)["params"]
    return model, params


@pytest.fixture()
def server(setup):
    model, params = setup
    eng = ServingEngine(model, params, n_slots=2)
    srv = EngineServer(eng, max_new_tokens=8, window=4)
    srv.start(host="127.0.0.1", port=0)
    yield srv
    srv.stop()


def _solo(model, params, prompt, n_steps):
    out, _ = greedy_generate(
        model, params, jnp.asarray(prompt, jnp.int32)[None, :], n_steps)
    return np.asarray(out)[0].tolist()


def _post(port, payload, timeout=120):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", "/generate", json.dumps(payload),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        events = [json.loads(line) for line in resp if line.strip()]
        return resp.status, events
    finally:
        conn.close()


def _streamed_tokens(events, index=None):
    """Flatten streamed ids from BOTH wire shapes — coalesced window
    frames ({"tokens": [...]}) and legacy per-token events — so every
    oracle assertion covers whichever shape the request selected."""
    out = []
    for e in events:
        if "done" in e or "error" in e:
            continue
        if index is not None and e.get("index", 0) != index:
            continue
        if "tokens" in e:
            out.extend(e["tokens"])
        elif "token" in e:
            out.append(e["token"])
    return out


def test_three_concurrent_clients_oracle_matched(server, setup):
    # 3 clients > 2 slots: the third request queues and is admitted
    # mid-flight when a slot frees — its stream must still match the
    # oracle exactly
    model, params = setup
    prompts = [[3, 14, 15, 92, 65], [2, 71, 82], [9, 9, 8, 7, 1]]
    results = [None] * len(prompts)

    def client(i):
        results[i] = _post(server.port,
                           {"tokens": prompts[i], "max_new_tokens": 8})

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    for i, prompt in enumerate(prompts):
        status, events = results[i]
        assert status == 200
        done = events[-1]
        assert done.get("done") is True
        want = _solo(model, params, prompt, 8)
        assert done["tokens"] == want, f"client {i}"
        # the streamed window frames must agree with the final list
        assert _streamed_tokens(events) == done["tokens"]
    st = server.stats()
    assert st["requests_served"] == 3
    assert st["running_requests"] == 0


def test_non_streaming_mode(server, setup):
    model, params = setup
    prompt = [5, 17, 3, 70]
    status, events = _post(
        server.port,
        {"tokens": prompt, "max_new_tokens": 6, "stream": False})
    assert status == 200
    assert len(events) == 1
    assert events[0]["tokens"] == _solo(model, params, prompt, 6)
    assert events[0]["finish_reason"] == "length"


def test_sampled_request_stays_reproducible(server):
    # same engine rng would be needed for bit-exactness across servers;
    # here we just assert a sampled request completes with the right
    # budget and valid token ids
    status, events = _post(
        server.port,
        {"tokens": [1, 2, 3], "max_new_tokens": 5,
         "temperature": 1.0, "top_k": 8})
    assert status == 200
    done = events[-1]
    assert len(done["tokens"]) == 5
    assert all(0 <= t < CFG["vocab"] for t in done["tokens"])


def test_bad_requests_rejected(server):
    status, events = _post(server.port, {"tokens": []})
    assert status == 400
    status, events = _post(server.port, {"tokens": "abc"})
    assert status == 400
    # admission-time rejection (prompt leaves no room to generate) is
    # a REAL 400 on both paths: the stream handler waits for the first
    # event before sending headers, so status-checking clients see it
    for stream in (True, False):
        status, events = _post(
            server.port,
            {"tokens": list(range(1, 70)), "stream": stream})
        assert status == 400, f"stream={stream}"
        assert "error" in events[0]


def test_stop_drains_inflight_requests(setup):
    # stop() must hand every connected client a terminal event — a
    # hanging client on shutdown is how "graceful" restarts turn into
    # socket-timeout storms
    model, params = setup
    eng = ServingEngine(model, params, n_slots=1)
    srv = EngineServer(eng, max_new_tokens=40, window=2)
    srv.start(host="127.0.0.1", port=0)
    result = {}

    def client():
        result["r"] = _post(srv.port, {"tokens": [1, 2, 3],
                                       "max_new_tokens": 40})

    t = threading.Thread(target=client)
    t.start()
    # let it admit and start streaming, then pull the plug
    deadline = time.monotonic() + 30
    while not srv._running and time.monotonic() < deadline:
        time.sleep(0.01)
    srv.stop()
    t.join(timeout=30)
    assert not t.is_alive(), "client hung after stop()"
    status, events = result["r"]
    assert status == 200          # stream had begun
    assert "error" in events[-1]  # ...and was terminated explicitly


def test_n_completions_over_http(setup):
    # n=3 on a 2-slot engine: copies admit INCREMENTALLY as slots
    # free; the final event carries all three choices and per-token
    # events are index-tagged
    model, params = setup
    eng = ServingEngine(model, params, n_slots=2)
    srv = EngineServer(eng, max_new_tokens=4, window=2)
    srv.start(host="127.0.0.1", port=0)
    try:
        status, events = _post(
            srv.port,
            {"tokens": [5, 9, 3], "max_new_tokens": 4, "n": 3,
             "temperature": 1.0, "top_k": 16})
        assert status == 200
        done = events[-1]
        assert done.get("done") is True
        choices = done["choices"]
        assert [c["index"] for c in choices] == [0, 1, 2]
        for c in choices:
            assert len(c["tokens"]) == 4
            assert c["finish_reason"] == "length"
        for e in events[:-1]:
            assert "index" in e and 0 <= e["index"] < 3
        # streamed frames reassemble into exactly the choices
        for c in choices:
            assert _streamed_tokens(
                events[:-1], index=c["index"]) == c["tokens"]
        # sampled siblings must actually diverge (distinct noise per
        # slot row — the failure mode n>1 exists to avoid is n
        # identical copies); statistically safe at temp 1.0/top-k 16
        assert len({tuple(c["tokens"]) for c in choices}) > 1
        assert srv.stats()["requests_served"] == 1
    finally:
        srv.stop()


def test_n_greedy_copies_identical_and_prefix_cached(setup):
    # greedy copies are deterministic duplicates, and siblings reuse
    # the shared prompt through the automatic prefix cache (prompt
    # longer than the engine chunk so the match clears the grid)
    model, params = setup
    eng = ServingEngine(model, params, n_slots=2)  # chunk=32
    srv = EngineServer(eng, max_new_tokens=3, window=2)
    srv.start(host="127.0.0.1", port=0)
    try:
        prompt = list(range(1, 40))  # 39 tokens > chunk
        status, events = _post(
            srv.port,
            {"tokens": prompt, "max_new_tokens": 3, "n": 2,
             "stream": False})
        assert status == 200
        a, b = events[0]["choices"]
        assert a["tokens"] == b["tokens"]
        assert srv.stats()["prefix_cache_hits"] >= 1
        # invalid n is a clean 400
        status, _ = _post(srv.port, {"tokens": [1, 2], "n": 0,
                                     "stream": False})
        assert status == 400
    finally:
        srv.stop()


def test_logprobs_over_http(setup):
    model, params = setup
    eng = ServingEngine(model, params, n_slots=1, logprobs_k=4)
    srv = EngineServer(eng, max_new_tokens=4, window=2)
    srv.start(host="127.0.0.1", port=0)
    try:
        status, events = _post(
            srv.port,
            {"tokens": [5, 9, 3], "max_new_tokens": 4, "logprobs": 2})
        assert status == 200
        tok_evs = [e for e in events if "token" in e]
        for e in tok_evs:
            assert "logprob" in e and len(e["top_logprobs"]) == 2
            # greedy: the chosen token leads its own top list
            assert e["top_logprobs"][0][0] == e["token"]
        done = events[-1]
        assert len(done["logprobs"]) == len(done["tokens"])
        # over-cap ask is a clean 400
        status, events = _post(
            srv.port, {"tokens": [1, 2], "logprobs": 9,
                       "stream": False})
        assert status == 400
    finally:
        srv.stop()


def test_prompt_logprobs_over_http(setup):
    model, params = setup
    eng = ServingEngine(model, params, n_slots=1, logprobs_k=3)
    srv = EngineServer(eng, max_new_tokens=3, window=2)
    srv.start(host="127.0.0.1", port=0)
    try:
        status, events = _post(
            srv.port,
            {"tokens": [5, 9, 3, 7], "max_new_tokens": 3,
             "prompt_logprobs": 2, "stream": False})
        assert status == 200
        plps = events[0]["prompt_logprobs"]
        assert len(plps) == 4 and plps[0] is None
        for rec in plps[1:]:
            assert "logprob" in rec and len(rec["top_logprobs"]) == 2
        # n>1: only copy 0 computes the (identical) records — the
        # siblings keep APC tail-only prefill and the done event
        # carries prompt_logprobs ONCE, not per choice
        prompt = list(range(1, 40))  # > chunk so APC can match
        status, events = _post(
            srv.port,
            {"tokens": prompt, "max_new_tokens": 2,
             "prompt_logprobs": 1, "n": 2, "stream": False})
        assert status == 200
        done = events[0]
        assert len(done["prompt_logprobs"]) == len(prompt)
        assert all("prompt_logprobs" not in c for c in done["choices"])
        assert srv.stats()["prefix_cache_hits"] >= 1
    finally:
        srv.stop()


def test_stop_tokens_over_http(server, setup):
    model, params = setup
    prompt = [3, 14, 15, 92, 65]
    solo = _solo(model, params, prompt, 8)
    status, events = _post(
        server.port,
        {"tokens": prompt, "max_new_tokens": 8, "stop": [solo[2]]})
    assert status == 200
    done = events[-1]
    assert done["finish_reason"] == "stop"
    assert done["tokens"] == solo[:3]
    status, _ = _post(server.port, {"tokens": [1, 2], "stop": "x"})
    assert status == 400


def test_priority_scheduling_order(setup):
    # higher priority admits first when slots are scarce; FIFO within
    # a level (deterministic: scheduler thread not started, the heap
    # is exercised directly)
    model, params = setup
    eng = ServingEngine(model, params, n_slots=1)
    srv = EngineServer(eng, max_new_tokens=4)
    lo = srv._parse_request({"tokens": [1, 2], "priority": 0})
    hi = srv._parse_request({"tokens": [3, 4], "priority": 7})
    srv._enqueue(lo)
    srv._enqueue(hi)
    srv._admit_pending()
    assert hi.admitted == 1 and lo.admitted == 0
    assert srv.stats()["pending_requests"] == 1
    eng2 = ServingEngine(model, params, n_slots=1)
    srv2 = EngineServer(eng2, max_new_tokens=4)
    a = srv2._parse_request({"tokens": [1, 2]})
    b = srv2._parse_request({"tokens": [3, 4]})
    srv2._enqueue(a)
    srv2._enqueue(b)
    srv2._admit_pending()
    assert a.admitted == 1 and b.admitted == 0


def test_priority_preempts_multi_completion_head(setup):
    # a partially-admitted n>1 request must NOT monopolize freed slots
    # against a strictly higher-priority arrival: its remaining copies
    # go back into the heap and the high-priority request admits first
    model, params = setup
    eng = ServingEngine(model, params, n_slots=1)
    srv = EngineServer(eng, max_new_tokens=2, window=1)
    low = srv._parse_request(
        {"tokens": [1, 2], "max_new_tokens": 2, "n": 3})
    srv._enqueue(low)
    srv._admit_pending()          # copy 0 occupies the one slot
    assert low.admitted == 1 and srv._head is low
    hi = srv._parse_request({"tokens": [3, 4], "priority": 5})
    srv._enqueue(hi)
    # finish the running copy and harvest it (what the scheduler loop
    # does between windows)
    eng.run(5)
    for slot, (req, idx) in list(srv._running.items()):
        srv._emit(slot, req, idx, eng.output(slot))
    srv._admit_pending()
    assert hi.admitted == 1      # preempted the head's copy 1
    assert low.admitted == 1
    assert srv._head is None and len(srv._pending) == 1


def test_seed_over_http(server):
    # per-request seed: same request, same tokens — even after an
    # unseeded sampled request shifts the engine's global stream — and
    # n>1 sibling copies diverge (distinct second-level streams)
    body = {"tokens": [5, 17, 3], "max_new_tokens": 5,
            "temperature": 1.0, "top_k": 16, "seed": 42,
            "stream": False}
    _, events = _post(server.port, dict(body))
    first = events[0]["tokens"]
    _post(server.port, {"tokens": [9, 9], "max_new_tokens": 3,
                        "temperature": 1.3, "stream": False})
    _, events = _post(server.port, dict(body))
    assert events[0]["tokens"] == first
    _, events = _post(server.port, {**body, "max_new_tokens": 4,
                                    "n": 2})
    a, b = events[0]["choices"]
    assert a["tokens"] != b["tokens"]


def test_healthz_and_stats(server):
    conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                      timeout=30)
    conn.request("GET", "/healthz")
    assert conn.getresponse().read() == b"ok\n"
    conn.request("GET", "/stats")
    st = json.loads(conn.getresponse().read())
    assert st["n_slots"] == 2
    assert "requests_served" in st
    conn.close()


def test_engine_wide_budget_rejected(setup):
    model, params = setup
    eng = ServingEngine(model, params, n_slots=1, max_new_tokens=4)
    with pytest.raises(ValueError, match="per-request"):
        EngineServer(eng)


def test_eos_finish_reason(setup):
    model, params = setup
    prompt = [3, 14, 15, 92, 65]
    solo = _solo(model, params, prompt, 6)
    eos = solo[2]  # emitted at step 3
    eng = ServingEngine(model, params, n_slots=1, eos_id=eos)
    srv = EngineServer(eng, max_new_tokens=8, window=4)
    srv.start(host="127.0.0.1", port=0)
    try:
        status, events = _post(srv.port,
                               {"tokens": prompt, "stream": False})
        assert status == 200
        assert events[0]["finish_reason"] == "eos"
        assert events[0]["tokens"] == solo[:3]
    finally:
        srv.stop()


def test_tensor_parallel_server_matches_meshless(setup):
    # the --tp path: an EngineServer over a model=2-sharded engine must
    # stream the same tokens the meshless engine produces (CPU-mesh
    # calibrated; see __graft_entry__ on f32 psum near-ties)
    from tpu_k8s_device_plugin.workloads import llama
    from tpu_k8s_device_plugin.workloads.transformer import make_lm_mesh

    cfg = llama.TINY_LLAMA  # 2 KV heads: shardable over model=2
    model = llama.decoder(cfg, dtype=jnp.float32, max_len=64)
    rng = jax.random.PRNGKey(2)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    params = model.init(rng, tokens, pos)["params"]
    mesh = make_lm_mesh(seq=1, model=2, expert=1)
    plain = ServingEngine(model, params, n_slots=2)
    sp = plain.admit([5, 17, 3, 70])
    plain.run(5)
    srv = EngineServer(
        ServingEngine(model, params, n_slots=2, mesh=mesh),
        max_new_tokens=6, window=3)
    srv.start(host="127.0.0.1", port=0)
    try:
        status, events = _post(
            srv.port, {"tokens": [5, 17, 3, 70], "max_new_tokens": 6,
                       "stream": False})
        assert status == 200
        assert events[0]["tokens"] == plain.output(sp)
    finally:
        srv.stop()


def test_parse_request_defaults():
    eng_default = 64

    class FakeSrv(EngineServer):
        def __init__(self):
            self.default_max_new = eng_default
            self.max_events = 256

    req = FakeSrv()._parse_request({"tokens": [1, 2]})
    assert isinstance(req, _Request)
    assert req.max_new_tokens == eng_default
    assert req.temperature == 0.0 and req.top_p == 1.0


def test_speculative_server_matches_plain(setup):
    """A draft-loaded engine behind the front door serves greedy
    requests through spec rounds — streams identical to the plain
    server's, and the engine must actually have speculated."""
    model, params = setup
    draft = make_decoder(vocab=128, d_model=32, n_heads=2, n_layers=1,
                         d_ff=64, max_len=64, dtype=jnp.float32)
    rng = jax.random.PRNGKey(7)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    dparams = draft.init(rng, tokens, pos)["params"]

    eng = ServingEngine(model, params, n_slots=2,
                        draft=(draft, dparams), gamma=3)
    srv = EngineServer(eng, max_new_tokens=8, window=4)
    srv.start(host="127.0.0.1", port=0)
    try:
        prompt = [5, 17, 3, 70]
        status, events = _post(
            srv.port, {"tokens": prompt, "stream": False})
        assert status == 200
        assert events[0]["tokens"] == _solo(model, params, prompt, 8)
        assert eng.stats()["spec_rounds"] >= 1

        # a SAMPLED request flips the scheduler to run_scan (spec is
        # greedy-only) and still matches its seeded oracle shape
        status, events = _post(
            srv.port, {"tokens": prompt, "temperature": 0.9,
                       "seed": 11, "stream": False})
        assert status == 200
        assert len(events[0]["tokens"]) == 8
    finally:
        srv.stop()


# -- tokenizer surface: prompt strings, stop strings, text streaming ---------

class _ByteTok:
    """1 byte == 1 token (ids < 128 fit the test vocab): the simplest
    lossless tokenizer, so text oracles derive from token oracles."""

    def encode(self, s):
        return list(s.encode("latin-1"))

    def decode(self, ids):
        return bytes(int(t) % 256 for t in ids).decode("latin-1")


@pytest.fixture()
def text_server(setup):
    model, params = setup
    eng = ServingEngine(model, params, n_slots=2, logprobs_k=2)
    srv = EngineServer(eng, max_new_tokens=8, window=3,
                       tokenizer=_ByteTok())
    srv.start(host="127.0.0.1", port=0)
    yield srv, model, params
    srv.stop()


def test_incremental_detok_matches_full_decode():
    """_DetokState commits text token-by-token with BOUNDED decode
    windows; the committed text must equal the full decode once every
    byte of a split UTF-8 char has arrived (the U+FFFD stall case)."""
    from tpu_k8s_device_plugin.workloads.server import _DetokState

    class _Utf8ByteTok:
        # 1 token == 1 raw UTF-8 byte: multi-byte chars span tokens
        def decode(self, ids):
            return bytes(ids).decode("utf-8", errors="replace")

    text = "héllo ✓ wörld"
    ids = list(text.encode("utf-8"))
    tok = _Utf8ByteTok()
    st = _DetokState()
    for n in range(1, len(ids) + 1):
        st.feed(tok, ids, n)
        # committed text is always a prefix of the final text — the
        # unstable tail is withheld, never streamed as U+FFFD
        assert text.startswith(st.text), (n, st.text)
        assert len(st.cum) == n + 1
    assert st.text == text


def test_find_stop_spanning_scan_windows():
    from tpu_k8s_device_plugin.workloads.server import (
        _DetokState, _find_stop,
    )

    st = _DetokState()
    st.text = "abcXYdef"
    st.cum = [0, 1, 2, 3, 4, 5, 6, 7, 8]  # 1 char per token
    # scanned through "abcX" (4 chars): the match completes at "Y" —
    # the overlap window must still see the X that was already scanned
    keep, text = _find_stop(st, ["XY"], 4)
    assert keep == 5 and text == "abc"
    # fully-scanned matches are not re-reported
    keep, _ = _find_stop(st, ["XY"], 8)
    assert keep is None


def test_find_stop_stale_match_does_not_shadow_new():
    """A stop occurrence already inside the scanned region must not
    shadow a LATER genuine occurrence of the same stop string (the
    first-occurrence-only bug): with scanned_from past the first 'AB',
    the second 'AB' is the match."""
    from tpu_k8s_device_plugin.workloads.server import (
        _DetokState, _find_stop,
    )

    st = _DetokState()
    st.text = "xABxyABz"
    st.cum = list(range(len(st.text) + 1))
    # with chars [0, 4) marked scanned, the new AB completing at 7 must
    # still be FOUND (the first-occurrence-only bug returned None
    # because the stale AB at pos 1 shadowed it); the cut lands at the
    # new match — the stale one sits before the overlap window
    keep, text = _find_stop(st, ["AB"], 4)
    assert keep == 7 and text == "xABxy"


def test_prompt_string_roundtrip(text_server):
    srv, model, params = text_server
    tok = _ByteTok()
    prompt = "ab"
    want = _solo(model, params, tok.encode(prompt), 8)
    status, events = _post(
        srv.port, {"prompt": prompt, "stream": False})
    assert status == 200
    assert events[0]["tokens"] == want
    assert events[0]["text"] == tok.decode(want)


def test_stop_string_truncates(text_server):
    srv, model, params = text_server
    tok = _ByteTok()
    prompt_ids = tok.encode("ab")
    full = _solo(model, params, prompt_ids, 8)
    text = tok.decode(full)
    stop = text[3:5]          # 2 chars spanning emit windows
    pos = text.find(stop)     # first occurrence rules the truncation
    status, events = _post(
        srv.port, {"prompt": "ab", "stop": [stop], "stream": False})
    assert status == 200
    assert events[0]["finish_reason"] == "stop"
    assert events[0]["text"] == text[:pos]


def test_stop_string_streaming_holdback(text_server):
    srv, model, params = text_server
    tok = _ByteTok()
    full = _solo(model, params, tok.encode("ab"), 8)
    text = tok.decode(full)
    stop = text[3:5]
    status, events = _post(
        srv.port, {"prompt": "ab", "stop": [stop]})
    assert status == 200
    deltas = "".join(e["text"] for e in events if "text" in e
                     and "done" not in e)
    done = [e for e in events if e.get("done")][0]
    # streamed deltas reassemble exactly to the final truncated text,
    # and no intermediate chunk ever leaked past the stop
    assert deltas == done["text"] == text[:text.find(stop)]
    assert done["finish_reason"] == "stop"


def test_mixed_stop_ids_and_strings(text_server):
    srv, model, params = text_server
    tok = _ByteTok()
    full = _solo(model, params, tok.encode("ab"), 8)
    # both forms in one list; the EARLIEST token boundary wins —
    # computed from the oracle for whichever rule fires first
    # (repetitive random-model output can make either one fire early)
    stop_id = full[2]
    stop_str = tok.decode(full)[5:7]
    keep_id = full.index(stop_id) + 1  # id token is included
    keep_str = next(t for t in range(1, len(full) + 1)
                    if stop_str in tok.decode(full[:t]))
    expect = full[:min(keep_id, keep_str)]
    status, events = _post(
        srv.port, {"prompt": "ab", "stream": False,
                   "stop": [stop_id, stop_str]})
    assert status == 200
    assert events[0]["finish_reason"] == "stop"
    assert events[0]["tokens"] == expect


def test_text_features_require_tokenizer(server):
    status, body = _post_raw(server.port, {"prompt": "hi"})
    assert status == 400 and "tokenizer" in body
    status, body = _post_raw(
        server.port, {"tokens": [1, 2], "stop": ["x"]})
    assert status == 400 and "tokenizer" in body


def _post_raw(port, payload):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    try:
        conn.request("POST", "/generate", json.dumps(payload),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, resp.read().decode()
    finally:
        conn.close()


# -- OpenAI-compatible /v1/completions ---------------------------------------

def _post_openai(port, payload):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    try:
        conn.request("POST", "/v1/completions", json.dumps(payload),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, resp.read().decode()
    finally:
        conn.close()


def test_openai_completions_basic(text_server):
    srv, model, params = text_server
    tok = _ByteTok()
    want = _solo(model, params, tok.encode("ab"), 8)
    status, body = _post_openai(srv.port, {
        "model": "tiny", "prompt": "ab", "temperature": 0,
        "max_tokens": 8})
    assert status == 200
    out = json.loads(body)
    assert out["object"] == "text_completion"
    assert out["model"] == "tiny"
    ch = out["choices"][0]
    assert ch["text"] == tok.decode(want)
    assert ch["finish_reason"] == "length"
    assert out["usage"] == {"prompt_tokens": 2,
                            "completion_tokens": 8,
                            "total_tokens": 10}


def test_openai_completions_token_array_and_stop(text_server):
    srv, model, params = text_server
    tok = _ByteTok()
    ids = tok.encode("ab")
    full = _solo(model, params, ids, 8)
    text = tok.decode(full)
    stop = text[3:5]
    status, body = _post_openai(srv.port, {
        "prompt": ids, "temperature": 0, "max_tokens": 8,
        "stop": stop})
    assert status == 200
    ch = json.loads(body)["choices"][0]
    assert ch["finish_reason"] == "stop"
    assert ch["text"] == text[:text.find(stop)]


def test_openai_completions_sse_stream(text_server):
    srv, model, params = text_server
    tok = _ByteTok()
    want = _solo(model, params, tok.encode("ab"), 8)
    conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                      timeout=120)
    conn.request("POST", "/v1/completions", json.dumps({
        "prompt": "ab", "temperature": 0, "max_tokens": 8,
        "stream": True}), {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    assert resp.getheader("Content-Type") == "text/event-stream"
    raw = resp.read().decode()
    conn.close()
    datas = [line[len("data: "):] for line in raw.splitlines()
             if line.startswith("data: ")]
    assert datas[-1] == "[DONE]"
    chunks = [json.loads(d) for d in datas[:-1]]
    text = "".join(c["choices"][0]["text"] for c in chunks)
    assert text == tok.decode(want)
    finals = [c["choices"][0]["finish_reason"] for c in chunks
              if c["choices"][0]["finish_reason"]]
    assert finals == ["length"]


def test_openai_echo_and_stream_usage(text_server):
    """echo prefixes the prompt text (non-streaming and as the first
    SSE chunk); stream_options.include_usage appends one usage-only
    chunk before [DONE]; stream_options without stream is a 400."""
    srv, model, params = text_server
    tok = _ByteTok()
    want = _solo(model, params, tok.encode("ab"), 8)
    status, body = _post_openai(srv.port, {
        "prompt": "ab", "temperature": 0, "max_tokens": 8,
        "echo": True})
    assert status == 200
    assert json.loads(body)["choices"][0]["text"] == \
        "ab" + tok.decode(want)
    conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                      timeout=120)
    conn.request("POST", "/v1/completions", json.dumps({
        "prompt": "ab", "temperature": 0, "max_tokens": 8,
        "stream": True, "echo": True,
        "stream_options": {"include_usage": True}}),
        {"Content-Type": "application/json"})
    resp = conn.getresponse()
    raw = resp.read().decode()
    conn.close()
    datas = [line[len("data: "):] for line in raw.splitlines()
             if line.startswith("data: ")]
    assert datas[-1] == "[DONE]"
    chunks = [json.loads(d) for d in datas[:-1]]
    # echo chunk first, usage-only chunk last
    assert chunks[0]["choices"][0]["text"] == "ab"
    assert chunks[-1]["choices"] == []
    assert chunks[-1]["usage"] == {"prompt_tokens": 2,
                                   "completion_tokens": 8,
                                   "total_tokens": 10}
    # the include_usage contract: every preceding chunk says usage null
    assert all(c["usage"] is None for c in chunks[:-1])
    text = "".join(c["choices"][0]["text"] for c in chunks[1:-1])
    assert text == tok.decode(want)
    # echo + logprobs: arrays cover prompt + completion, first null
    status, body = _post_openai(srv.port, {
        "prompt": "ab", "temperature": 0, "max_tokens": 4,
        "echo": True, "logprobs": 1})
    assert status == 200
    lp = json.loads(body)["choices"][0]["logprobs"]
    assert len(lp["tokens"]) == 2 + 4
    assert lp["token_logprobs"][0] is None
    assert lp["top_logprobs"][0] is None
    assert all(isinstance(v, float)
               for v in lp["token_logprobs"][1:])
    # stream_options without stream: 400
    status, body = _post_openai(srv.port, {
        "prompt": "ab", "max_tokens": 2,
        "stream_options": {"include_usage": True}})
    assert status == 400
    assert "stream" in json.loads(body)["error"]["message"]


def test_openai_completions_needs_tokenizer(server):
    status, body = _post_openai(server.port, {"prompt": "hi"})
    assert status == 400
    err = json.loads(body)["error"]
    assert err["type"] == "invalid_request_error"
    assert "tokenizer" in err["message"]


def test_openai_logprobs_counts(text_server):
    srv, model, params = text_server
    # logprobs=0: chosen token's logprob, NO alternatives (valid in
    # the OpenAI API; engine-side 0 means off, so the server maps it)
    status, body = _post_openai(srv.port, {
        "prompt": "ab", "temperature": 0, "max_tokens": 4,
        "logprobs": 0})
    assert status == 200
    lp = json.loads(body)["choices"][0]["logprobs"]
    assert len(lp["token_logprobs"]) == 4
    assert all(t == {} for t in lp["top_logprobs"])
    # logprobs=2: two alternatives per position
    status, body = _post_openai(srv.port, {
        "prompt": "ab", "temperature": 0, "max_tokens": 4,
        "logprobs": 2})
    lp = json.loads(body)["choices"][0]["logprobs"]
    assert all(len(t) == 2 for t in lp["top_logprobs"])
    # streamed logprobs are an explicit 400, not silent data loss
    status, body = _post_openai(srv.port, {
        "prompt": "ab", "logprobs": 2, "stream": True})
    assert status == 400
    assert "stream" in json.loads(body)["error"]["message"]


class _ChatTok(_ByteTok):
    """ByteTok plus a minimal chat template (the transformers API
    surface the chat endpoint needs)."""

    def apply_chat_template(self, messages, tokenize=False,
                            add_generation_prompt=True):
        text = "".join(f"<{m['role']}>{m['content']}" for m in messages)
        if add_generation_prompt:
            text += "<assistant>"
        return text


def test_openai_chat_completions(setup):
    model, params = setup
    tok = _ChatTok()
    eng = ServingEngine(model, params, n_slots=2)
    srv = EngineServer(eng, max_new_tokens=6, window=3, tokenizer=tok)
    srv.start(host="127.0.0.1", port=0)
    try:
        msgs = [{"role": "user", "content": "hi"}]
        prompt_ids = tok.encode(tok.apply_chat_template(msgs))
        want = _solo(model, params, prompt_ids, 6)
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=120)
        conn.request("POST", "/v1/chat/completions", json.dumps({
            "model": "tiny", "messages": msgs, "temperature": 0,
            "max_tokens": 6}), {"Content-Type": "application/json"})
        resp = conn.getresponse()
        out = json.loads(resp.read().decode())
        conn.close()
        assert resp.status == 200
        assert out["object"] == "chat.completion"
        msg = out["choices"][0]["message"]
        assert msg["role"] == "assistant"
        assert msg["content"] == tok.decode(want)
        assert out["usage"]["prompt_tokens"] == len(prompt_ids)

        # streamed: chat.completion.chunk deltas reassemble
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=120)
        conn.request("POST", "/v1/chat/completions", json.dumps({
            "messages": msgs, "temperature": 0, "max_tokens": 6,
            "stream": True}), {"Content-Type": "application/json"})
        resp = conn.getresponse()
        raw = resp.read().decode()
        conn.close()
        datas = [l[len("data: "):] for l in raw.splitlines()
                 if l.startswith("data: ")]
        assert datas[-1] == "[DONE]"
        chunks = [json.loads(d) for d in datas[:-1]]
        assert all(c["object"] == "chat.completion.chunk"
                   for c in chunks)
        text = "".join(c["choices"][0]["delta"].get("content", "")
                       for c in chunks)
        assert text == tok.decode(want)
    finally:
        srv.stop()


def test_openai_chat_needs_template(text_server):
    srv, _, _ = text_server  # _ByteTok has no chat template
    conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=60)
    conn.request("POST", "/v1/chat/completions", json.dumps({
        "messages": [{"role": "user", "content": "x"}]}),
        {"Content-Type": "application/json"})
    resp = conn.getresponse()
    body = resp.read().decode()
    conn.close()
    assert resp.status == 400 and "chat template" in body


def test_openai_chat_logprobs_boolean(setup):
    model, params = setup
    tok = _ChatTok()
    eng = ServingEngine(model, params, n_slots=1, logprobs_k=2)
    srv = EngineServer(eng, max_new_tokens=4, window=2, tokenizer=tok)
    srv.start(host="127.0.0.1", port=0)
    try:
        msgs = [{"role": "user", "content": "hi"}]

        def chat(body):
            c = http.client.HTTPConnection("127.0.0.1", srv.port,
                                           timeout=120)
            c.request("POST", "/v1/chat/completions", json.dumps(body),
                      {"Content-Type": "application/json"})
            r = c.getresponse()
            out = r.status, json.loads(r.read().decode())
            c.close()
            return out

        # logprobs: false must NOT enable logprobs (bool, not count)
        status, out = chat({"messages": msgs, "temperature": 0,
                            "max_tokens": 4, "logprobs": False})
        assert status == 200
        assert out["choices"][0]["logprobs"] is None
        assert out["created"] > 0
        # logprobs: true + top_logprobs: 2 -> chat content shape
        status, out = chat({"messages": msgs, "temperature": 0,
                            "max_tokens": 4, "logprobs": True,
                            "top_logprobs": 2})
        assert status == 200
        recs = out["choices"][0]["logprobs"]["content"]
        assert len(recs) == 4
        assert all(len(r["top_logprobs"]) == 2 for r in recs)
        assert all("logprob" in r and "token" in r for r in recs)
    finally:
        srv.stop()


def test_min_tokens_floors_stop_strings(text_server):
    """vLLM semantics: no stop check below the min_tokens floor —
    a stop string completing early must not end the request there."""
    srv, model, params = text_server
    tok = _ByteTok()
    full = _solo(model, params, tok.encode("ab"), 8)
    text = tok.decode(full)
    stop = text[1:3]  # completes at token 3 (< the floor)
    status, events = _post(
        srv.port, {"prompt": "ab", "stop": [stop], "stream": False,
                   "min_tokens": 6})
    assert status == 200
    ev = events[0]
    assert len(ev["tokens"]) >= 6
    # without the floor the same request stops early
    status, events = _post(
        srv.port, {"prompt": "ab", "stop": [stop], "stream": False})
    assert len(events[0]["tokens"]) < 6


def test_window_frames_match_per_token_stream(server, setup):
    """Streaming equivalence (JSON-lines): the default coalesced
    window frames must reassemble token-for-token into exactly what
    the legacy per_token path streams, and both into the final
    tokens array."""
    prompt = [3, 14, 15, 92, 65]
    st1, coal = _post(server.port,
                      {"tokens": prompt, "max_new_tokens": 8})
    st2, per = _post(server.port,
                     {"tokens": prompt, "max_new_tokens": 8,
                      "per_token": True})
    assert st1 == st2 == 200
    assert coal[-1]["tokens"] == per[-1]["tokens"]
    assert (_streamed_tokens(coal) == _streamed_tokens(per)
            == coal[-1]["tokens"])
    # the per-token path really is per-token, the coalesced path
    # really coalesces (window=4 here: >1 token per frame)
    assert all("token" in e for e in per[:-1])
    assert any(len(e.get("tokens", ())) > 1 for e in coal[:-1])


def test_coalesced_text_and_sse_equivalence(text_server):
    """Streaming equivalence (text + SSE): coalesced-window text
    deltas, the per_token path, the unary body, and the OpenAI SSE
    stream all reconstruct the same text for the same prompt."""
    srv, model, params = text_server
    body = {"prompt": "ab", "max_new_tokens": 8}
    s1, coal = _post(srv.port, dict(body))
    s2, per = _post(srv.port, dict(body, per_token=True))
    s3, unary = _post(srv.port, dict(body, stream=False))
    assert s1 == s2 == s3 == 200
    assert (coal[-1]["tokens"] == per[-1]["tokens"]
            == unary[0]["tokens"])
    assert _streamed_tokens(coal) == _streamed_tokens(per)
    joined = "".join(e["text"] for e in coal
                     if "text" in e and "done" not in e)
    joined_per = "".join(e["text"] for e in per
                         if "text" in e and "done" not in e)
    assert joined == joined_per
    assert coal[-1]["text"] == unary[0]["text"]
    assert coal[-1]["text"].startswith(joined)
    # SSE reconstructs the same text
    conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                      timeout=120)
    conn.request("POST", "/v1/completions", json.dumps({
        "prompt": "ab", "temperature": 0, "max_tokens": 8,
        "stream": True}), {"Content-Type": "application/json"})
    resp = conn.getresponse()
    raw = resp.read().decode()
    conn.close()
    chunks = [json.loads(d[len("data: "):])
              for d in raw.splitlines()
              if d.startswith("data: ") and not d.endswith("[DONE]")]
    sse_text = "".join(c["choices"][0]["text"] for c in chunks)
    assert sse_text == unary[0]["text"]


def test_stop_match_ids_agree_with_text(text_server):
    """ADVICE r5: the ids and text surfaces of one stop response must
    agree — tokens truncate at the match-completing token, text at the
    match start, both derived from the SAME match."""
    srv, model, params = text_server
    tok = _ByteTok()
    full = _solo(model, params, tok.encode("ab"), 8)
    text = tok.decode(full)
    stop = text[3:5]  # completes at token 5, starts at char 3
    status, events = _post(
        srv.port, {"prompt": "ab", "stop": [stop], "stream": False,
                   "min_tokens": 2})
    assert status == 200
    ev = events[0]
    assert ev["finish_reason"] == "stop"
    assert len(ev["tokens"]) == 5          # through the completing token
    assert ev["text"] == text[:3]          # cut at the match start
    # the surfaces agree: kept ids detokenize to text + the stop
    assert tok.decode(ev["tokens"]) == ev["text"] + stop
