"""Debug endpoint tests: /healthz, /debug/status, /debug/threads against a
live manager (SURVEY §5's 'optional pprof endpoint' plan item)."""

import json
import os
import urllib.request

import pytest

from tpu_k8s_device_plugin.manager import PluginManager
from tpu_k8s_device_plugin.observability import DebugServer
from tpu_k8s_device_plugin.proto import deviceplugin_pb2 as pluginapi
from tpu_k8s_device_plugin.tpu.device_impl import TpuContainerImpl

from fake_kubelet import FakeKubelet


@pytest.fixture
def served(testdata, tmp_path):
    root = os.path.join(testdata, "v5e-8")
    impl = TpuContainerImpl(
        sysfs_root=os.path.join(root, "sys"),
        dev_root=os.path.join(root, "dev"),
        tpu_env_path=os.path.join(root, "run", "tpu", "tpu-env"),
    )
    kubelet = FakeKubelet(str(tmp_path / "device-plugins")).start()
    manager = PluginManager(impl, kubelet_dir=kubelet.dir,
                            kubelet_watch_interval_s=0.1)
    manager.run(block=False)
    debug = DebugServer(manager, port=0).start()  # ephemeral port
    yield manager, debug, kubelet
    debug.stop()
    manager.stop()
    kubelet.stop()


def get(debug, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{debug.port}{path}", timeout=5
    ) as resp:
        return resp.status, resp.read().decode()


def test_healthz(served):
    _, debug, _ = served
    status, body = get(debug, "/healthz")
    assert status == 200 and body == "ok\n"


def test_status_reports_resources_and_counters(served):
    manager, debug, kubelet = served
    # drive one Allocate through the real gRPC socket so counters move
    assert kubelet.wait_for_registration()
    stub = kubelet.plugin_stub("google.com_tpu")
    stub.Allocate(pluginapi.AllocateRequest(
        container_requests=[pluginapi.ContainerAllocateRequest(
            devices_ids=["0000:00:04.0"]
        )]
    ))
    status, body = get(debug, "/debug/status")
    assert status == 200
    data = json.loads(body)
    res = data["resources"]["tpu"]
    assert res["healthy"] == 8 and res["unhealthy"] == 0
    assert res["rpc_counts"]["allocate"] == 1
    assert res["preferred_allocation_enabled"] is True
    assert data["topology"]["global_mesh"] == "2x4"
    assert data["topology"]["accelerator_type"] == "v5litepod-8"


def test_thread_dump_shows_manager_threads(served):
    _, debug, _ = served
    status, body = get(debug, "/debug/threads")
    assert status == 200
    assert "kubelet-watch" in body
    assert "MainThread" in body


def test_unknown_path_404(served):
    _, debug, _ = served
    import urllib.error
    with pytest.raises(urllib.error.HTTPError) as ei:
        get(debug, "/nope")
    assert ei.value.code == 404
