"""Weight-only int4: packing exactness, serving closeness, engine
composition.  4-bit is the coarse rung of the quantization ladder, so
the oracle is closeness (per-channel scales bound the error), not
bit-equality — but the PACKING itself must be lossless over the whole
[-8, 7] grid."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_k8s_device_plugin.workloads import llama
from tpu_k8s_device_plugin.workloads.inference import (
    greedy_generate,
    init_cache,
    make_decoder,
    pack_int4,
    quantize_lm_params_int4,
    unpack_int4,
)
from tpu_k8s_device_plugin.workloads.serving import ServingEngine

CFG = dict(vocab=96, d_model=64, n_heads=4, n_layers=2, d_ff=128)
DT = jnp.float32


@pytest.fixture(scope="module")
def trained():
    model = make_decoder(**CFG, max_len=64, dtype=DT)
    rng = jax.random.PRNGKey(0)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    return model, model.init(rng, tokens, pos)["params"]


def test_pack_unpack_exact_over_full_grid():
    vals = jnp.asarray(
        np.stack([np.arange(-8, 8, dtype=np.int8)] * 4), jnp.int8)
    assert jnp.array_equal(unpack_int4(pack_int4(vals)), vals)
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.integers(-8, 8, (32, 48), np.int8))
    assert jnp.array_equal(unpack_int4(pack_int4(w)), w)


def test_int4_tree_layout_and_size(trained):
    _, params = trained
    q = quantize_lm_params_int4(params)
    blk = q["block_0"]
    assert blk["qkv"]["kernel_int4"].dtype == jnp.int8
    assert blk["qkv"]["kernel_int4"].shape == (
        CFG["d_model"], params["block_0"]["qkv"]["kernel"].shape[1] // 2)
    # group-wise scales: [D/group, F]
    from tpu_k8s_device_plugin.workloads.inference import _int4_group
    d = CFG["d_model"]
    f = params["block_0"]["qkv"]["kernel"].shape[1]
    assert blk["qkv"]["scale"].shape == (d // _int4_group(d), f)


def test_int4_prefill_close_to_full_precision(trained):
    model, params = trained
    q = quantize_lm_params_int4(params)
    m4 = make_decoder(**CFG, max_len=64, dtype=DT, quantized="int4")
    prompt = jnp.asarray([[5, 17, 3, 70, 2]], jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(5, dtype=jnp.int32), (1, 5))
    ref, _ = model.apply(
        {"params": params, "cache": init_cache(model, 1)},
        prompt, pos, decode=False, mutable=["cache"])
    got, _ = m4.apply(
        {"params": q, "cache": init_cache(m4, 1)},
        prompt, pos, decode=False, mutable=["cache"])
    err = float(jnp.max(jnp.abs(ref - got)))
    scale = float(jnp.max(jnp.abs(ref))) + 1e-6
    assert err / scale < 0.25, err / scale  # 4-bit is coarse


def test_int4_decodes_through_engine_and_loop(trained):
    _, params = trained
    q = quantize_lm_params_int4(params)
    m4 = make_decoder(**CFG, max_len=64, dtype=DT, quantized="int4")
    prompt = [5, 17, 3]
    out, _ = greedy_generate(m4, q, jnp.asarray([prompt]), 5)
    assert out.shape == (1, 5)
    eng = ServingEngine(m4, q, n_slots=2, max_new_tokens=5)
    s = eng.admit(prompt)
    eng.run_scan(4)
    assert eng.finished(s)
    assert eng.output(s) == np.asarray(out)[0].tolist()


def test_int4_llama_gqa_swiglu(trained):
    cfg = llama.TINY_LLAMA
    base = llama.train_model(cfg, dtype=DT)
    rng = jax.random.PRNGKey(2)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    params = base.init(rng, tokens, pos)["params"]
    q = quantize_lm_params_int4(params)
    assert "kernel_int4" in q["block_0"]["mlp_gate"]
    m4 = make_decoder(
        vocab=cfg.vocab, d_model=cfg.d_model, n_heads=cfg.n_heads,
        n_layers=cfg.n_layers, d_ff=cfg.d_ff, max_len=64, dtype=DT,
        quantized="int4", n_kv_heads=cfg.n_kv_heads, ffn="swiglu",
        rope_theta=cfg.rope_theta)
    out, _ = greedy_generate(m4, q, jnp.asarray([[3, 200, 100]]), 4)
    assert out.shape == (1, 4)


def test_int4_moe_rejected(trained):
    moe = make_decoder(**CFG, max_len=64, dtype=DT, quantized="int4",
                       n_experts=4)
    with pytest.raises(NotImplementedError, match="int4"):
        moe.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32),
                 jnp.broadcast_to(jnp.arange(4, dtype=jnp.int32), (1, 4)))
    _, params = trained
    bad = {"block_0": {"moe": {"experts_up": jnp.zeros((2, 4, 8))}}}
    with pytest.raises(NotImplementedError, match="int8"):
        quantize_lm_params_int4(bad)
