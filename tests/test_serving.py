"""Continuous batching engine: every scheduling pattern must produce
exactly what the uniform single-request engine produces.

Oracle = ``greedy_generate`` (itself oracle-tested against full
recompute in test_inference.py), so any banded-mask, per-slot-depth,
splice, or chunk-padding bug shows up as a token mismatch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_k8s_device_plugin.workloads import llama
from tpu_k8s_device_plugin.workloads.inference import (
    greedy_generate,
    init_cache,
    make_decoder,
)
from tpu_k8s_device_plugin.workloads.serving import ServingEngine

CFG = dict(vocab=128, d_model=64, n_heads=4, n_layers=2, d_ff=128)
DT = jnp.float32


@pytest.fixture(scope="module")
def setup():
    model = make_decoder(**CFG, max_len=64, dtype=DT)
    rng = jax.random.PRNGKey(0)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    params = model.init(rng, tokens, pos)["params"]
    return model, params


def _solo(model, params, prompt, n_steps):
    out, _ = greedy_generate(
        model, params, jnp.asarray(prompt, jnp.int32)[None, :], n_steps)
    return np.asarray(out)[0].tolist()


def test_two_requests_different_lengths_match_solo(setup):
    model, params = setup
    pa = [3, 14, 15, 92, 65]
    pb = [2, 71, 82]
    eng = ServingEngine(model, params, n_slots=4)
    sa = eng.admit(pa)
    sb = eng.admit(pb)
    eng.run(7)
    assert eng.output(sa)[:8] == _solo(model, params, pa, 8)
    assert eng.output(sb)[:8] == _solo(model, params, pb, 8)


def test_admit_mid_stream_does_not_disturb_running_requests(setup):
    model, params = setup
    pa = [3, 14, 15, 92, 65]
    pc = [9, 9, 8, 7, 1, 0, 2]
    eng = ServingEngine(model, params, n_slots=4)
    sa = eng.admit(pa)
    eng.step(); eng.step(); eng.step()
    sc = eng.admit(pc)  # lands while sa is mid-generation
    eng.run(5)
    assert eng.output(sa)[:8] == _solo(model, params, pa, 8)
    assert eng.output(sc)[:5] == _solo(model, params, pc, 5)


def test_chunked_prefill_matches_unchunked(setup):
    model, params = setup
    prompt = [5, 9, 3, 3, 7, 1, 0, 44, 91, 12]  # 10 tokens, chunk 4
    plain = ServingEngine(model, params, n_slots=2)
    chunked = ServingEngine(model, params, n_slots=2, chunk=4)
    sp = plain.admit(prompt)
    sc = chunked.admit(prompt)
    plain.run(6)
    chunked.run(6)
    assert plain.output(sp) == chunked.output(sc)
    assert chunked.output(sc)[:6] == _solo(model, params, prompt, 6)


def test_slot_reuse_after_completion(setup):
    model, params = setup
    eng = ServingEngine(model, params, n_slots=1, max_new_tokens=3)
    pa = [3, 14, 15]
    sa = eng.admit(pa)
    eng.run(10)
    assert eng.finished(sa)
    assert eng.output(sa) == _solo(model, params, pa, 3)
    pb = [7, 7, 2, 1]
    sb = eng.admit(pb)  # same slot, recycled
    assert sb == sa
    eng.run(10)
    assert eng.output(sb) == _solo(model, params, pb, 3)


def test_eos_stops_a_slot_and_frees_it(setup):
    model, params = setup
    prompt = [3, 14, 15, 92, 65]
    solo = _solo(model, params, prompt, 6)
    eos = solo[2]  # the token it will emit at step 3
    eng = ServingEngine(model, params, n_slots=2, eos_id=eos)
    s = eng.admit(prompt)
    eng.run(10)
    assert eng.finished(s)
    assert eng.output(s) == solo[:3]
    assert s in [x for x in eng.free_slots()]


def test_engine_full_raises(setup):
    model, params = setup
    eng = ServingEngine(model, params, n_slots=1)
    eng.admit([1, 2, 3])
    with pytest.raises(RuntimeError, match="no free slots"):
        eng.admit([4, 5])


def test_max_len_guard(setup):
    model, params = setup
    eng = ServingEngine(model, params, n_slots=1, max_new_tokens=32)
    with pytest.raises(ValueError, match="max_len"):
        eng.admit(list(range(60)))


def test_extend_two_chunks_equals_one_prefill_block_level(setup):
    # block-level banded-extend check, independent of the engine: the
    # same prompt pushed as two extends must leave identical cache and
    # logits as one prefill
    model, params = setup
    prompt = jnp.asarray([[5, 9, 3, 3, 7, 1]], jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(6, dtype=jnp.int32), (1, 6))
    ref_logits, ref_mut = model.apply(
        {"params": params, "cache": init_cache(model, 1)},
        prompt, pos, decode=False, mutable=["cache"],
    )
    cache = init_cache(model, 1)
    out = []
    for lo, hi in ((0, 3), (3, 6)):
        logits, mut = model.apply(
            {"params": params, "cache": cache},
            prompt[:, lo:hi], pos[:, lo:hi], decode=True,
            mutable=["cache"],
        )
        cache = mut["cache"]
        out.append(logits)
    got_logits = jnp.concatenate(out, axis=1)
    np.testing.assert_allclose(
        np.asarray(ref_logits), np.asarray(got_logits),
        rtol=2e-5, atol=2e-5)
    for layer in ref_mut["cache"]:
        np.testing.assert_allclose(
            np.asarray(ref_mut["cache"][layer]["cached_k"]),
            np.asarray(cache[layer]["cached_k"]), rtol=1e-5, atol=1e-5)
        assert (ref_mut["cache"][layer]["cache_lens"].tolist()
                == cache[layer]["cache_lens"].tolist())


def test_gqa_llama_through_the_engine(setup):
    # the engine composes with the Llama config (GQA compact cache)
    cfg = llama.TINY_LLAMA
    model = llama.decoder(cfg, dtype=DT, max_len=64)
    rng = jax.random.PRNGKey(1)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    params = model.init(rng, tokens, pos)["params"]
    prompt = [3, 14, 15, 92, 65, 21]
    eng = ServingEngine(model, params, n_slots=3, chunk=4)
    s = eng.admit(prompt)
    eng.run(5)
    assert eng.output(s)[:5] == _solo(model, params, prompt, 5)


def test_moe_chunked_prefill_matches_unchunked():
    # T>1 extends pin MoE capacity to T (dropless), so chunked and
    # unchunked admission must emit identical tokens even with a tight
    # training capacity_factor
    model = make_decoder(
        vocab=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        max_len=64, dtype=DT, n_experts=4, moe_k=2,
        moe_capacity_factor=0.5,  # tight: training would drop tokens
    )
    rng = jax.random.PRNGKey(5)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    params = model.init(rng, tokens, pos)["params"]
    prompt = [5, 9, 3, 3, 7, 1, 0, 44, 9, 12, 13, 2]
    plain = ServingEngine(model, params, n_slots=2)
    chunked = ServingEngine(model, params, n_slots=2, chunk=4)
    sp = plain.admit(prompt)
    sc = chunked.admit(prompt)
    plain.run(6)
    chunked.run(6)
    assert plain.output(sp) == chunked.output(sc)


def test_recycled_slot_is_not_finished(setup):
    model, params = setup
    eng = ServingEngine(model, params, n_slots=1, max_new_tokens=2)
    sa = eng.admit([3, 14])
    eng.run(5)
    assert eng.finished(sa)
    sb = eng.admit([7, 7, 2])
    assert sb == sa
    assert not eng.finished(sb)  # stale record must not leak
    eng.run(5)
    assert eng.finished(sb)


def test_tensor_parallel_engine_matches_single_device(setup):
    # TP serving: params Megatron-split, cache sharded on the KV head
    # axis over a model=2 mesh — tokens must match the meshless engine
    from tpu_k8s_device_plugin.workloads.transformer import make_lm_mesh

    cfg = llama.TINY_LLAMA  # 2 KV heads: shardable over model=2
    model = llama.decoder(cfg, dtype=DT, max_len=64)
    rng = jax.random.PRNGKey(2)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    params = model.init(rng, tokens, pos)["params"]
    mesh = make_lm_mesh(seq=1, model=2, expert=1)

    prompts = {"a": [5, 17, 3, 70], "b": [2, 71, 82, 9, 14]}
    plain = ServingEngine(model, params, n_slots=2)
    tp = ServingEngine(model, params, n_slots=2, mesh=mesh, chunk=4)
    slots_p = {k: plain.admit(v) for k, v in prompts.items()}
    slots_t = {k: tp.admit(v) for k, v in prompts.items()}
    plain.run(6)
    tp.run(6)
    for k in prompts:
        assert plain.output(slots_p[k]) == tp.output(slots_t[k]), k


def test_tp_engine_rejects_unshardable_kv_heads(setup):
    from tpu_k8s_device_plugin.workloads.transformer import make_lm_mesh

    model, params = setup  # 4 heads, MHA
    mesh = make_lm_mesh(seq=1, model=8, expert=1)
    with pytest.raises(ValueError, match="model"):
        ServingEngine(model, params, n_slots=2, mesh=mesh)


def test_prefix_cache_matches_full_admit(setup):
    model, params = setup
    system = [7, 7, 7, 12, 90, 3]
    ua, ub = [5, 9, 3], [44, 1]
    ref = ServingEngine(model, params, n_slots=2)
    eng = ServingEngine(model, params, n_slots=2, chunk=4)
    h = eng.register_prefix(system)
    sa = eng.admit(system + ua, prefix=h)
    sb = eng.admit(system + ub, prefix=h)  # prefix reused (copy survives)
    ra = ref.admit(system + ua)
    rb = ref.admit(system + ub)
    eng.run(6)
    ref.run(6)
    assert eng.output(sa) == ref.output(ra)
    assert eng.output(sb) == ref.output(rb)


def test_prefix_exact_prompt_equals_prefix(setup):
    model, params = setup
    system = [7, 7, 12, 90]
    eng = ServingEngine(model, params, n_slots=2)
    ref = ServingEngine(model, params, n_slots=2)
    h = eng.register_prefix(system)
    s = eng.admit(system, prefix=h)  # empty suffix: uses stored logits
    r = ref.admit(system)
    eng.run(4)
    ref.run(4)
    assert eng.output(s) == ref.output(r)


def test_prefix_mismatch_rejected(setup):
    model, params = setup
    eng = ServingEngine(model, params, n_slots=2)
    h = eng.register_prefix([1, 2, 3])
    with pytest.raises(ValueError, match="prefix"):
        eng.admit([1, 9, 3, 4], prefix=h)
    with pytest.raises(ValueError, match="prefix"):
        eng.admit([1, 2], prefix=h)  # shorter than the prefix


def test_rejected_prefix_admit_leaves_state_untouched(setup):
    model, params = setup
    eng = ServingEngine(model, params, n_slots=1, max_new_tokens=2)
    h = eng.register_prefix([1, 2, 3])
    sa = eng.admit([1, 2, 3, 4], prefix=h)
    eng.run(5)
    assert eng.finished(sa)
    with pytest.raises(ValueError, match="prefix"):
        eng.admit([9, 9, 9, 9], prefix=h)  # mismatch
    assert eng.finished(sa)  # the finished record must survive
    with pytest.raises(ValueError, match="unknown prefix"):
        eng.admit([1, 2, 3, 4], prefix=1234)
    eng.release_prefix(h)
    with pytest.raises(ValueError, match="unknown prefix"):
        eng.admit([1, 2, 3, 4], prefix=h)


def test_chunk_overflow_rejected_before_state_mutation(setup):
    model, params = setup  # max_len=64
    eng = ServingEngine(model, params, n_slots=1, chunk=8,
                        max_new_tokens=1)
    h = eng.register_prefix([1, 2, 3])
    sa = eng.admit([1, 2, 3, 4], prefix=h)
    eng.run(3)
    assert eng.finished(sa)
    # t_p=62 passes the budget check (62+1 <= 64) but the padded
    # suffix (3 + ceil(59/8)*8 = 67) overflows — must reject WITHOUT
    # erasing the finished record
    big = [1, 2, 3] + list(range(59))
    with pytest.raises(ValueError, match="padded"):
        eng.admit(big, prefix=h)
    assert eng.finished(sa)


def test_greedy_slot_unaffected_by_sampling_neighbor(setup):
    model, params = setup
    pa = [3, 14, 15, 92, 65]
    eng = ServingEngine(model, params, n_slots=4)
    sg = eng.admit(pa)                                   # greedy
    ss = eng.admit([9, 9, 8], temperature=1.5, top_k=8)  # sampled
    eng.run(6)
    assert eng.output(sg)[:7] == _solo(model, params, pa, 7)
    assert len(eng.output(ss)) == 7


def test_sampling_reproducible_with_seed(setup):
    model, params = setup
    prompt = [5, 17, 3, 70]
    outs = []
    for _ in range(2):
        eng = ServingEngine(model, params, n_slots=2,
                            rng=jax.random.PRNGKey(42))
        s = eng.admit(prompt, temperature=1.0, top_k=16)
        eng.run(6)
        outs.append(eng.output(s))
    assert outs[0] == outs[1]
    other = ServingEngine(model, params, n_slots=2,
                          rng=jax.random.PRNGKey(7))
    s = other.admit(prompt, temperature=1.0, top_k=16)
    other.run(6)
    # different seed should (overwhelmingly) differ at temp 1.0
    assert other.output(s) != outs[0]


def test_top_k_one_equals_greedy(setup):
    model, params = setup
    prompt = [2, 71, 82, 9]
    eng = ServingEngine(model, params, n_slots=1)
    s = eng.admit(prompt, temperature=2.0, top_k=1)
    eng.run(6)
    assert eng.output(s)[:7] == _solo(model, params, prompt, 7)


def test_sampled_tokens_stay_in_top_k(setup):
    model, params = setup
    prompt = [5, 9, 3, 3]
    eng = ServingEngine(model, params, n_slots=1,
                        rng=jax.random.PRNGKey(3))
    s = eng.admit(prompt, temperature=3.0, top_k=2)
    eng.run(8)
    toks = eng.output(s)
    # ONE full-length causal forward recomputes every step's logits
    # (position t-1's row is what the engine sampled token t from) —
    # a regrowing per-token loop would compile len(toks) shapes
    from tpu_k8s_device_plugin.workloads.inference import (
        init_cache as _ic)
    full = jnp.asarray(list(prompt) + toks, jnp.int32)[None, :]
    T = full.shape[1]
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (1, T))
    logits, _ = model.apply(
        {"params": params, "cache": _ic(model, 1)},
        full, pos, decode=False, mutable=["cache"])
    for i, tok in enumerate(toks):
        row = logits[0, len(prompt) - 1 + i]
        top2 = set(np.asarray(jax.lax.top_k(row, 2)[1]).tolist())
        assert tok in top2, f"step {i}"


def test_sampling_params_validated(setup):
    model, params = setup
    eng = ServingEngine(model, params, n_slots=1)
    with pytest.raises(ValueError, match="temperature"):
        eng.admit([1, 2], temperature=-1.0)
    with pytest.raises(ValueError, match="top_k"):
        eng.admit([1, 2], top_k=0)
    # out-of-range prompt ids reject BEFORE any state mutation (a bad
    # id used to flow into clamped gathers; with the repetition
    # histogram it must be a clean error)
    with pytest.raises(ValueError, match="prompt token"):
        eng.admit([1, 999999])
    with pytest.raises(ValueError, match="prompt token"):
        eng.admit([-1, 2])
    assert eng.free_slots() == [0]  # nothing half-admitted


def test_stats_counters(setup):
    model, params = setup
    eng = ServingEngine(model, params, n_slots=3, max_new_tokens=4)
    assert eng.stats()["active_slots"] == 0
    sa = eng.admit([1, 2, 3])
    eng.register_prefix([9, 9])
    st = eng.stats()
    assert st["active_slots"] == 1 and st["free_slots"] == 2
    assert st["registered_prefixes"] == 1
    assert st["tokens_emitted"] == 1  # the admit's first token
    eng.run(10)
    st = eng.stats()
    assert eng.finished(sa)
    assert st["finished_requests"] == 1
    assert st["tokens_emitted"] == 4  # max_new_tokens budget
    assert st["decode_steps"] == 3   # 3 steps after the admit token


def test_finished_requests_counter_is_cumulative(setup):
    model, params = setup
    eng = ServingEngine(model, params, n_slots=1, max_new_tokens=2)
    for prompt in ([1, 2], [3, 4], [5, 6]):
        eng.admit(prompt)
        eng.run(5)
    assert eng.stats()["finished_requests"] == 3


def test_greedy_fast_path_restored_after_sampled_request(setup):
    model, params = setup
    eng = ServingEngine(model, params, n_slots=2, max_new_tokens=3)
    ss = eng.admit([9, 9], temperature=1.0, top_k=8)
    eng.run(5)
    assert eng.finished(ss)
    # freed slot must not leave sampling knobs behind
    assert not eng.temps.any() and not eng.topks.any()
    sg = eng.admit([3, 14, 15])
    eng.run(5)
    assert eng.output(sg) == _solo(model, params, [3, 14, 15], 3)


def test_top_p_tiny_equals_greedy(setup):
    # p below the argmax's own probability keeps only the argmax
    model, params = setup
    prompt = [2, 71, 82]
    eng = ServingEngine(model, params, n_slots=1)
    s = eng.admit(prompt, temperature=2.0, top_p=1e-6)
    eng.run(6)
    assert eng.output(s)[:7] == _solo(model, params, prompt, 7)


def test_top_p_tokens_stay_in_nucleus(setup):
    model, params = setup
    prompt = [5, 9, 3]
    P_NUC = 0.6
    eng = ServingEngine(model, params, n_slots=1,
                        rng=jax.random.PRNGKey(11))
    s = eng.admit(prompt, temperature=1.0, top_p=P_NUC)
    eng.run(6)
    toks = eng.output(s)
    from tpu_k8s_device_plugin.workloads.inference import init_cache as _ic
    # one full-length causal forward gives every step's logits (see
    # test_sampled_tokens_stay_in_top_k)
    full = jnp.asarray(prompt + toks, jnp.int32)[None, :]
    T = full.shape[1]
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (1, T))
    logits, _ = model.apply(
        {"params": params, "cache": _ic(model, 1)},
        full, pos, decode=False, mutable=["cache"])
    for i, tok in enumerate(toks):
        pr = np.asarray(jax.nn.softmax(logits[0, len(prompt) - 1 + i]))
        order = np.argsort(-pr)
        csum = np.cumsum(pr[order])
        nucleus = set(order[:int(np.searchsorted(csum, P_NUC) + 1)]
                      .tolist())
        assert tok in nucleus, f"step {i}"


def test_top_p_validation(setup):
    model, params = setup
    eng = ServingEngine(model, params, n_slots=1)
    with pytest.raises(ValueError, match="top_p"):
        eng.admit([1, 2], top_p=0.0)
    with pytest.raises(ValueError, match="top_p"):
        eng.admit([1, 2], top_p=1.5)


def test_top_p_applies_within_top_k(setup):
    # sequential semantics: with top_k=2 and top_p just above the
    # renormalized top-1 mass, only the argmax survives — even though
    # the FULL-vocab nucleus at that p would span many tokens
    model, params = setup
    prompt = [5, 9, 3]
    from tpu_k8s_device_plugin.workloads.inference import init_cache as _ic
    cur = jnp.asarray(prompt, jnp.int32)[None, :]
    pos = jnp.broadcast_to(jnp.arange(3, dtype=jnp.int32), (1, 3))
    logits, _ = model.apply(
        {"params": params, "cache": _ic(model, 1)},
        cur, pos, decode=False, mutable=["cache"])
    TEMP = 5.0
    top2 = np.asarray(
        jax.lax.top_k(logits[0, -1], 2)[0], np.float64) / TEMP
    p1 = float(np.exp(top2[0]) / np.exp(top2).sum())  # renorm. top-1 mass
    eng = ServingEngine(model, params, n_slots=1,
                        rng=jax.random.PRNGKey(13))
    # keep rule is before < p: the 2nd token's 'before' equals the
    # top-1 renormalized mass, so p just BELOW it keeps only the argmax
    s = eng.admit(prompt, temperature=TEMP, top_k=2,
                  top_p=max(1e-6, p1 * 0.9999))
    # ONLY checking the first token (later steps have other logits);
    # with the nucleus inside top-k it must be the argmax
    assert eng.output(s)[0] == _solo(model, params, prompt, 1)[0]


def test_run_scan_matches_stepwise_greedy(setup):
    model, params = setup
    prompts = {"a": [3, 14, 15, 92], "b": [9, 8]}
    a = ServingEngine(model, params, n_slots=3)
    b = ServingEngine(model, params, n_slots=3)
    sa = {k: a.admit(p) for k, p in prompts.items()}
    sb = {k: b.admit(p) for k, p in prompts.items()}
    for _ in range(6):
        a.step()
    b.run_scan(6)
    for k in prompts:
        assert a.output(sa[k]) == b.output(sb[k]), k
    assert a.stats()["decode_steps"] == b.stats()["decode_steps"]


def test_run_scan_matches_stepwise_sampled(setup):
    model, params = setup
    a = ServingEngine(model, params, n_slots=2,
                      rng=jax.random.PRNGKey(21))
    b = ServingEngine(model, params, n_slots=2,
                      rng=jax.random.PRNGKey(21))
    sa = a.admit([5, 17, 3], temperature=1.0, top_k=16, top_p=0.9)
    sb = b.admit([5, 17, 3], temperature=1.0, top_k=16, top_p=0.9)
    for _ in range(5):
        a.step()
    b.run_scan(5)
    assert a.output(sa) == b.output(sb)


def test_run_scan_retires_on_eos_and_budget(setup):
    model, params = setup
    prompt = [3, 14, 15, 92, 65]
    solo = _solo(model, params, prompt, 6)
    eos = solo[2]
    eng = ServingEngine(model, params, n_slots=2, eos_id=eos)
    s = eng.admit(prompt)
    out = eng.run_scan(6)
    assert eng.finished(s)
    assert eng.output(s) == solo[:3]
    assert out[s] == solo[1:3]  # scan returns post-admit tokens
    # budget retirement through run_scan: discarded post-retirement
    # tokens must not count toward outputs or the budget
    bng = ServingEngine(model, params, n_slots=1, max_new_tokens=4)
    sb = bng.admit(prompt)
    bng.run_scan(6)
    assert bng.finished(sb)
    assert bng.output(sb) == solo[:4]
    assert bng.stats()["tokens_emitted"] == 4


def test_run_scan_headroom_guard(setup):
    model, params = setup  # max_len = 64
    eng = ServingEngine(model, params, n_slots=1)
    eng.admit(list(range(60)))
    with pytest.raises(ValueError, match="cache rows"):
        eng.run_scan(10)


def test_default_chunk_is_compile_safe(setup, monkeypatch):
    # the default engine must admit arbitrary prompt lengths through a
    # bounded set of compiled extend shapes: one chunk-wide prefill
    # shape plus the S-wide decode step
    import tpu_k8s_device_plugin.workloads.serving as serving_mod

    model, params = setup
    shapes = set()
    real = serving_mod.extend_step

    def counting(model_, params_, cache, tokens, positions,
                 adapter_ids=None):
        shapes.add(tuple(tokens.shape))
        return real(model_, params_, cache, tokens, positions,
                    adapter_ids)

    monkeypatch.setattr(serving_mod, "extend_step", counting)
    eng = ServingEngine(model, params, n_slots=8)
    assert eng.chunk == 32  # largest divisor of 64 <= min(128, 32)
    for ln in range(1, 9):  # 8 distinct prompt lengths
        eng.admit(list(range(1, ln + 1)))
    eng.step()
    assert shapes == {(1, 32), (8, 1)}


def test_default_chunk_matches_unchunked_tokens(setup):
    model, params = setup
    prompt = [5, 9, 3, 3, 7, 1, 0, 44, 91, 12]
    auto = ServingEngine(model, params, n_slots=2)          # chunk=32
    plain = ServingEngine(model, params, n_slots=2, chunk=None)
    sa = auto.admit(prompt)
    sp = plain.admit(prompt)
    auto.run(6)
    plain.run(6)
    assert auto.output(sa) == plain.output(sp)
    assert auto.output(sa)[:6] == _solo(model, params, prompt, 6)


def test_chunk_rejects_bad_string(setup):
    model, params = setup
    with pytest.raises(ValueError, match="chunk"):
        ServingEngine(model, params, n_slots=1, chunk="big")


def test_auto_prefix_reuses_resident_slot_prompt(setup):
    # two prompts sharing a 3-chunk prefix: the second admission must
    # prefill only the tail, and its tokens must be bit-identical to
    # cold (APC-off) admission
    model, params = setup
    shared = [7, 3, 9, 12, 5, 8, 1, 2, 44, 6, 91, 30]  # 12 = 3 chunks
    pa = shared + [5, 9, 3]
    pb = shared + [44, 1]
    cold = ServingEngine(model, params, n_slots=2, chunk=4,
                         auto_prefix=False)
    warm = ServingEngine(model, params, n_slots=2, chunk=4)
    ca, cb = cold.admit(pa), cold.admit(pb)
    wa = warm.admit(pa)
    before = warm.stats()["prefill_tokens"]
    wb = warm.admit(pb)
    st = warm.stats()
    # only the 2-token tail prefilled (the last shared chunk is partial
    # against t_p - 1 = 13 -> matched 12 rows reused)
    assert st["prefill_tokens"] - before == len(pb) - 12
    assert st["prefix_cache_hits"] == 1
    assert st["prefix_reused_tokens"] == 12
    cold.run(6)
    warm.run(6)
    assert warm.output(wa) == cold.output(ca)
    assert warm.output(wb) == cold.output(cb)


def test_auto_prefix_matches_registry_partially(setup):
    # a registered system prompt is reusable WITHOUT the handle, and a
    # partial (chunk-floored) match reuses only the shared chunks
    model, params = setup
    system = [7, 7, 7, 12, 90, 3, 1, 2]          # 2 chunks of 4
    prompt = system[:6] + [9, 9, 44]             # shares 6 -> 1 chunk
    ref = ServingEngine(model, params, n_slots=2, chunk=4,
                        auto_prefix=False)
    eng = ServingEngine(model, params, n_slots=2, chunk=4,
                        auto_prefix_min=4)
    eng.register_prefix(system)
    before = eng.stats()["prefill_tokens"]
    s = eng.admit(prompt)
    assert eng.stats()["prefill_tokens"] - before == len(prompt) - 4
    r = ref.admit(prompt)
    eng.run(5)
    ref.run(5)
    assert eng.output(s) == ref.output(r)


def test_auto_prefix_respects_adapter_binding(setup):
    # donors under a different LoRA adapter must not match (the
    # adapter shapes the K/V)
    model = make_decoder(**CFG, max_len=64, dtype=DT, n_adapters=2,
                         lora_rank=4)
    rng = jax.random.PRNGKey(3)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    params = model.init(rng, tokens, pos)["params"]
    shared = list(range(1, 13))
    eng = ServingEngine(model, params, n_slots=2, chunk=4,
                        auto_prefix_min=4)
    eng.admit(shared + [5], adapter=0)
    before = eng.stats()["prefix_cache_hits"]
    eng.admit(shared + [9], adapter=1)  # different adapter: no reuse
    assert eng.stats()["prefix_cache_hits"] == before


def test_unchunked_engine_disables_auto_prefix(setup):
    model, params = setup
    eng = ServingEngine(model, params, n_slots=2, chunk=None)
    assert not eng.auto_prefix
    shared = list(range(1, 13))
    eng.admit(shared + [5])
    eng.admit(shared + [9])
    assert eng.stats()["prefix_cache_hits"] == 0


def test_stop_tokens_finish_request(setup):
    # per-request stop tokens (vLLM stop_token_ids): the slot retires
    # at the first stop token, reason "stop"; other slots unaffected
    model, params = setup
    prompt = [3, 14, 15, 92, 65]
    solo = _solo(model, params, prompt, 6)
    stop_tok = solo[2]  # emitted at step 3
    eng = ServingEngine(model, params, n_slots=2)
    s = eng.admit(prompt, stop=[stop_tok, 999999 % 128])
    other = eng.admit([9, 9, 8])
    eng.run(10)
    assert eng.finished(s)
    assert eng.finish_reason(s) == "stop"
    assert eng.output(s) == solo[:3]  # stop token included, like eos
    assert not eng.finished(other)
    assert eng.finish_reason(other) is None
    # through run_scan too
    bng = ServingEngine(model, params, n_slots=1)
    sb = bng.admit(prompt, stop=[stop_tok])
    bng.run_scan(6)
    assert bng.finished(sb) and bng.finish_reason(sb) == "stop"
    assert bng.output(sb) == solo[:3]


def test_seeded_request_isolated_from_neighbors(setup):
    # vLLM's per-request seed: the SAME seeded request must emit the
    # SAME tokens regardless of engine rng, neighbors, admission
    # order, or scheduling API — the engine-stream guarantee
    # (test_sampling_reproducible_with_seed) can't offer that
    model, params = setup
    prompt = [5, 17, 3, 70]

    def run_one(rng_seed, with_neighbor, scan):
        eng = ServingEngine(model, params, n_slots=3,
                            rng=jax.random.PRNGKey(rng_seed))
        if with_neighbor:  # sampled neighbor shifts the GLOBAL stream
            eng.admit([9, 9, 8], temperature=1.5, top_k=8)
        s = eng.admit(prompt, temperature=1.0, top_k=16, seed=1234)
        if scan:
            eng.run_scan(6)
        else:
            eng.run(6)
        return eng.output(s)[:7]

    ref = run_one(0, False, False)
    assert ref == run_one(7, True, False)   # different rng + neighbor
    assert ref == run_one(3, True, True)    # ...and scan scheduling
    # a different seed diverges (overwhelmingly, at temp 1)
    eng = ServingEngine(model, params, n_slots=1)
    s = eng.admit(prompt, temperature=1.0, top_k=16, seed=99)
    eng.run(6)
    assert eng.output(s)[:7] != ref
    # and the unseeded engine stream is untouched by seeded history:
    # greedy neighbors still bit-match solo
    eng2 = ServingEngine(model, params, n_slots=2)
    g = eng2.admit([3, 14, 15, 92, 65])
    eng2.admit(prompt, temperature=1.0, seed=5)
    eng2.run(6)
    assert eng2.output(g)[:7] == _solo(model, params,
                                       [3, 14, 15, 92, 65], 7)


def test_ignore_eos_decodes_to_budget(setup):
    # vLLM's ignore_eos: the slot decodes past the eos token to the
    # budget (fixed-length benchmarking through the real engine path);
    # per-request stop tokens still apply
    model, params = setup
    prompt = [3, 14, 15, 92, 65]
    solo = _solo(model, params, prompt, 6)
    eos = solo[2]
    eng = ServingEngine(model, params, n_slots=2, eos_id=eos,
                        max_new_tokens=6)
    s = eng.admit(prompt, ignore_eos=True)
    other = eng.admit(prompt)  # respects eos
    eng.run(10)
    assert eng.output(s) == solo  # all 6, eos included mid-stream
    assert eng.finish_reason(s) == "length"
    assert eng.output(other) == solo[:3]
    assert eng.finish_reason(other) == "eos"
    # recycled slot must not inherit the flag
    s2 = eng.admit(prompt)
    eng.run(10)
    assert eng.finish_reason(s2) == "eos"


def test_finish_reasons_eos_and_length(setup):
    model, params = setup
    prompt = [3, 14, 15, 92, 65]
    solo = _solo(model, params, prompt, 6)
    eng = ServingEngine(model, params, n_slots=1, eos_id=solo[2])
    s = eng.admit(prompt)
    eng.run(10)
    assert eng.finish_reason(s) == "eos"
    bng = ServingEngine(model, params, n_slots=1, max_new_tokens=2)
    sb = bng.admit(prompt)
    bng.run(10)
    assert bng.finish_reason(sb) == "length"
    # recycled slot drops the stale reason and stop set
    sc = bng.admit([7, 7])
    assert bng.finish_reason(sc) is None
    bng.run(10)
    assert bng.finish_reason(sc) == "length"


def test_stop_token_validation(setup):
    model, params = setup
    eng = ServingEngine(model, params, n_slots=1, max_new_tokens=2)
    sa = eng.admit([1, 2])
    eng.run(5)
    assert eng.finished(sa)
    with pytest.raises(ValueError, match="stop token"):
        eng.admit([1, 2], stop=[9999])
    assert eng.finished(sa)  # rejected admit left state untouched


def test_min_p_one_equals_greedy(setup):
    # min_p = 1.0 keeps only tokens at least as probable as the argmax
    # -> exactly the argmax, at any temperature
    model, params = setup
    prompt = [2, 71, 82, 9]
    eng = ServingEngine(model, params, n_slots=1)
    s = eng.admit(prompt, temperature=3.0, min_p=1.0)
    eng.run(6)
    assert eng.output(s)[:7] == _solo(model, params, prompt, 7)


def test_min_p_tokens_stay_in_support(setup):
    # every sampled token's candidate probability must be >= min_p
    # times the argmax's (full recompute oracle, one causal forward)
    model, params = setup
    prompt = [5, 9, 3]
    MIN_P = 0.5
    eng = ServingEngine(model, params, n_slots=1,
                        rng=jax.random.PRNGKey(19))
    s = eng.admit(prompt, temperature=1.2, min_p=MIN_P)
    eng.run(6)
    toks = eng.output(s)
    from tpu_k8s_device_plugin.workloads.inference import init_cache as _ic
    full = jnp.asarray(prompt + toks, jnp.int32)[None, :]
    T = full.shape[1]
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (1, T))
    logits, _ = model.apply(
        {"params": params, "cache": _ic(model, 1)},
        full, pos, decode=False, mutable=["cache"])
    for i, tok in enumerate(toks):
        # candidate distribution at temperature 1.2 (min_p thresholds
        # the TEMPERATURE-SCALED probabilities)
        pr = np.asarray(jax.nn.softmax(
            np.asarray(logits[0, len(prompt) - 1 + i], np.float64)
            / 1.2))
        assert pr[tok] >= MIN_P * pr.max() * (1 - 1e-6), f"step {i}"


def test_min_p_scan_matches_stepwise(setup):
    model, params = setup

    def mk():
        return ServingEngine(model, params, n_slots=1,
                             rng=jax.random.PRNGKey(23))

    a, b = mk(), mk()
    sa = a.admit([5, 17, 3], temperature=1.0, min_p=0.3)
    sb = b.admit([5, 17, 3], temperature=1.0, min_p=0.3)
    for _ in range(5):
        a.step()
    b.run_scan(5)
    assert a.output(sa) == b.output(sb)


def test_min_p_validation(setup):
    model, params = setup
    eng = ServingEngine(model, params, n_slots=1)
    with pytest.raises(ValueError, match="min_p"):
        eng.admit([1, 2], min_p=1.5)
    with pytest.raises(ValueError, match="min_p"):
        eng.admit([1, 2], min_p=-0.1)


def test_frequency_penalty_matches_recompute_oracle(setup):
    # greedy + penalties must equal argmax of (logits - pres*seen -
    # freq*count) recomputed from one full causal forward with a
    # host-tracked output histogram — exact, step by step
    model, params = setup
    prompt = [3, 14, 15, 92, 65]
    PRES, FREQ = 0.7, 1.3
    eng = ServingEngine(model, params, n_slots=2)
    s = eng.admit(prompt, presence_penalty=PRES, frequency_penalty=FREQ)
    eng.run(7)
    toks = eng.output(s)
    from tpu_k8s_device_plugin.workloads.inference import init_cache
    full = jnp.asarray(prompt + toks, jnp.int32)[None, :]
    T = full.shape[1]
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (1, T))
    logits, _ = model.apply(
        {"params": params, "cache": init_cache(model, 1)},
        full, pos, decode=False, mutable=["cache"])
    logits = np.asarray(logits, np.float64)[0]
    counts = np.zeros(model.vocab)
    for i, tok in enumerate(toks):
        row = logits[len(prompt) - 1 + i].copy()
        row -= PRES * (counts > 0) + FREQ * counts
        assert tok == int(np.argmax(row)), f"step {i}"
        counts[tok] += 1
    # the penalty must actually bite: unpenalized greedy repeats
    plain = _solo(model, params, prompt, 7)
    assert toks != plain


def test_penalties_scan_matches_stepwise_and_reset(setup):
    model, params = setup

    def mk():
        return ServingEngine(model, params, n_slots=2,
                             max_new_tokens=5)

    a, b = mk(), mk()
    sa = a.admit([5, 17, 3], frequency_penalty=1.0)
    sb = b.admit([5, 17, 3], frequency_penalty=1.0)
    for _ in range(6):
        a.step()
    b.run_scan(6)
    assert a.output(sa) == b.output(sb)
    assert a.finished(sa) and b.finished(sb)
    # knobs reset on finish: a fresh greedy admit into the recycled
    # slot must match plain greedy (stale counts/penalties must not
    # leak)
    sc = b.admit([3, 14, 15])
    b.run(10)
    assert b.output(sc) == _solo(model, params, [3, 14, 15], 5)


def test_penalty_validation(setup):
    model, params = setup
    eng = ServingEngine(model, params, n_slots=1)
    with pytest.raises(ValueError, match="presence_penalty"):
        eng.admit([1, 2], presence_penalty=3.0)
    with pytest.raises(ValueError, match="frequency_penalty"):
        eng.admit([1, 2], frequency_penalty=-2.5)
    with pytest.raises(ValueError, match="repetition_penalty"):
        eng.admit([1, 2], repetition_penalty=0.0)


def test_repetition_penalty_matches_recompute_oracle(setup):
    # greedy + repetition penalty: every step's token equals the argmax
    # of logits with seen (PROMPT + output) tokens scaled by vLLM's
    # divide-positive / multiply-negative rule — including the FIRST
    # token, whose seen set is the prompt alone
    model, params = setup
    prompt = [3, 14, 15, 92, 65, 14, 3]   # repeated prompt tokens
    REP = 1.8
    eng = ServingEngine(model, params, n_slots=2)
    s = eng.admit(prompt, repetition_penalty=REP)
    eng.run(6)
    toks = eng.output(s)
    from tpu_k8s_device_plugin.workloads.inference import init_cache
    full = jnp.asarray(prompt + toks, jnp.int32)[None, :]
    T = full.shape[1]
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (1, T))
    logits, _ = model.apply(
        {"params": params, "cache": init_cache(model, 1)},
        full, pos, decode=False, mutable=["cache"])
    logits = np.asarray(logits, np.float64)[0]
    seen = np.zeros(model.vocab, bool)
    seen[prompt] = True
    for i, tok in enumerate(toks):
        row = logits[len(prompt) - 1 + i].copy()
        row[seen] = np.where(row[seen] > 0, row[seen] / REP,
                             row[seen] * REP)
        assert tok == int(np.argmax(row)), f"step {i}"
        seen[tok] = True
    assert toks != _solo(model, params, prompt, 7)  # it bites


def test_repetition_penalty_scan_matches_stepwise(setup):
    model, params = setup

    def mk():
        return ServingEngine(model, params, n_slots=2)

    a, b = mk(), mk()
    sa = a.admit([5, 17, 3, 17], repetition_penalty=1.5)
    sb = b.admit([5, 17, 3, 17], repetition_penalty=1.5)
    for _ in range(5):
        a.step()
    b.run_scan(5)
    assert a.output(sa) == b.output(sb)
    # recycled slot must not inherit the seen histogram or the knob
    a.release(sa)
    sc = a.admit([3, 14, 15])
    a.run(4)
    assert a.output(sc) == _solo(model, params, [3, 14, 15], 5)[:5]


def test_logprobs_match_full_recompute(setup):
    # per-token logprobs (vLLM's `logprobs` API): chosen + top-n must
    # equal log-softmax of a full causal recompute at every position
    model, params = setup
    prompt = [3, 14, 15, 92]
    eng = ServingEngine(model, params, n_slots=2, logprobs_k=4)
    s = eng.admit(prompt, logprobs=3)
    eng.run(4)
    toks = eng.output(s)
    recs = eng.token_logprobs(s)
    assert len(recs) == len(toks)
    from tpu_k8s_device_plugin.workloads.inference import init_cache
    full = jnp.asarray(prompt + toks, jnp.int32)[None, :]
    T = full.shape[1]
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (1, T))
    logits, _ = model.apply(
        {"params": params, "cache": init_cache(model, 1)},
        full, pos, decode=False, mutable=["cache"])
    lp = np.asarray(jax.nn.log_softmax(
        np.asarray(logits, np.float32), axis=-1))[0]
    for i, (tok, (clp, top)) in enumerate(zip(toks, recs)):
        row = lp[len(prompt) - 1 + i]
        assert len(top) == 3
        np.testing.assert_allclose(clp, row[tok], rtol=1e-4, atol=1e-4)
        want_ids = np.argsort(-row)[:3]
        got_ids = [t for t, _ in top]
        assert got_ids == want_ids.tolist(), f"step {i}"
        for tid, tlp in top:
            np.testing.assert_allclose(tlp, row[tid],
                                       rtol=1e-4, atol=1e-4)
        # greedy: chosen token IS the top-1
        assert tok == got_ids[0]


def test_logprobs_scan_matches_stepwise(setup):
    model, params = setup
    prompt = [5, 17, 3]

    def mk():
        return ServingEngine(model, params, n_slots=2, logprobs_k=2)

    a, b = mk(), mk()
    sa = a.admit(prompt, logprobs=2)
    sb = b.admit(prompt, logprobs=2)
    for _ in range(4):
        a.step()
    b.run_scan(4)
    ra, rb = a.token_logprobs(sa), b.token_logprobs(sb)
    assert len(ra) == len(rb) == 5
    for (ca, ta), (cb, tb) in zip(ra, rb):
        np.testing.assert_allclose(ca, cb, rtol=1e-5, atol=1e-6)
        assert [t for t, _ in ta] == [t for t, _ in tb]


def test_logprobs_validation_and_isolation(setup):
    model, params = setup
    eng = ServingEngine(model, params, n_slots=2, logprobs_k=2)
    with pytest.raises(ValueError, match="logprobs_k"):
        eng.admit([1, 2], logprobs=3)
    s = eng.admit([1, 2])           # no logprobs requested
    t = eng.admit([3, 4], logprobs=1)
    eng.run(3)
    assert eng.token_logprobs(s) == []
    assert len(eng.token_logprobs(t)) == 4
    off = ServingEngine(model, params, n_slots=1)  # default k=0
    with pytest.raises(ValueError, match="logprobs_k"):
        off.admit([1, 2], logprobs=1)


def test_prompt_logprobs_match_full_recompute(setup):
    # vLLM's prompt_logprobs: entry j scores prompt[j] given
    # prompt[:j] (entry 0 is None) — compare chunked-prefill records
    # against log-softmax of one full causal forward, and the chunked
    # records against an unchunked engine's
    model, params = setup
    prompt = [3, 14, 15, 92, 65, 7, 9, 1, 44, 2]  # 10 tokens, chunk 4
    eng = ServingEngine(model, params, n_slots=2, chunk=4,
                        logprobs_k=3)
    s = eng.admit(prompt, prompt_logprobs=2)
    recs = eng.prompt_logprobs(s)
    assert len(recs) == len(prompt) and recs[0] is None
    from tpu_k8s_device_plugin.workloads.inference import init_cache
    full = jnp.asarray(prompt, jnp.int32)[None, :]
    T = full.shape[1]
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (1, T))
    logits, _ = model.apply(
        {"params": params, "cache": init_cache(model, 1)},
        full, pos, decode=False, mutable=["cache"])
    lp = np.asarray(jax.nn.log_softmax(
        np.asarray(logits, np.float32), axis=-1))[0]
    for j in range(1, len(prompt)):
        clp, top = recs[j]
        row = lp[j - 1]
        np.testing.assert_allclose(clp, row[prompt[j]],
                                   rtol=1e-4, atol=1e-4)
        assert len(top) == 2
        assert [t for t, _ in top] == np.argsort(-row)[:2].tolist()
    # unchunked engine produces the same records (to tolerance)
    ung = ServingEngine(model, params, n_slots=1, chunk=None,
                        logprobs_k=3)
    recs2 = ung.prompt_logprobs(ung.admit(prompt, prompt_logprobs=2))
    for a, b in zip(recs[1:], recs2[1:]):
        np.testing.assert_allclose(a[0], b[0], rtol=1e-4, atol=1e-4)


def test_prompt_logprobs_bypass_prefix_cache(setup):
    # every position needs ITS OWN logits, so APC must not skip any
    # prefill for a prompt_logprobs request
    model, params = setup
    shared = list(range(1, 13))
    eng = ServingEngine(model, params, n_slots=2, chunk=4,
                        auto_prefix_min=4, logprobs_k=2)
    eng.admit(shared + [5])
    before = eng.stats()
    s = eng.admit(shared + [9], prompt_logprobs=1)
    st = eng.stats()
    assert st["prefix_cache_hits"] == before["prefix_cache_hits"]
    assert (st["prefill_tokens"] - before["prefill_tokens"]
            == len(shared) + 1)
    assert len(eng.prompt_logprobs(s)) == len(shared) + 1


def test_prompt_logprobs_validation_and_reset(setup):
    model, params = setup
    eng = ServingEngine(model, params, n_slots=1, chunk=4,
                        logprobs_k=2, max_new_tokens=2)
    with pytest.raises(ValueError, match="prompt_logprobs"):
        eng.admit([1, 2], prompt_logprobs=3)
    h = eng.register_prefix([1, 2, 3])
    with pytest.raises(ValueError, match="prefix"):
        eng.admit([1, 2, 3, 4], prefix=h, prompt_logprobs=1)
    s = eng.admit([1, 2, 3], prompt_logprobs=1)
    assert len(eng.prompt_logprobs(s)) == 3
    eng.run(5)
    s2 = eng.admit([4, 5, 6])  # recycled without the ask
    assert eng.prompt_logprobs(s2) == []


def test_draw_stream_mode_independent_after_retirement(setup):
    # a sampled slot retiring mid-window must leave the engine's key
    # stream where step-by-step scheduling would have left it, so later
    # sampled admissions emit identical tokens under either API
    model, params = setup

    def mk():
        return ServingEngine(model, params, n_slots=2,
                             max_new_tokens=3,
                             rng=jax.random.PRNGKey(5))

    a, b = mk(), mk()
    for e in (a, b):
        e.admit([3, 14, 15])                              # greedy
        e.admit([9, 9, 8], temperature=1.0, top_k=8)      # sampled
    for _ in range(6):
        a.step()
    b.run_scan(6)  # both requests retire after step 2 of the window
    sa = a.admit([5, 17, 3], temperature=1.0, top_k=8)
    sb = b.admit([5, 17, 3], temperature=1.0, top_k=8)
    for _ in range(2):
        a.step()
    b.run_scan(2)
    assert a.output(sa) == b.output(sb)


def test_run_scan_fused_matches_unfused(setup):
    # the fused window (on-device eos/stop/budget carry + columnar
    # harvest) against the per-step host harvest, over a window mixing
    # greedy, stop-set, and seeded-sampled slots with budget cuts
    model, params = setup

    def mk(fused):
        e = ServingEngine(model, params, n_slots=3, eos_id=0,
                          max_new_tokens=5, fused_decode=fused,
                          rng=jax.random.PRNGKey(5))
        sl = {}
        sl["g"] = e.admit([3, 14, 15, 92, 65])
        sl["t"] = e.admit([2, 71, 82], stop=[94, 22])
        sl["s"] = e.admit([9, 9, 8], temperature=1.0, top_k=8,
                          seed=17)
        return e, sl

    a, sa = mk(False)
    b, sb = mk(True)
    oa = a.run_scan(7)
    ob = b.run_scan(7)
    assert oa == ob                      # per-window returns
    for k in sa:
        assert a.output(sa[k]) == b.output(sb[k]), k
        assert (a.finish_reason(sa[k]) if a.finished(sa[k]) else None) \
            == (b.finish_reason(sb[k]) if b.finished(sb[k]) else None)
    assert a.stats()["tokens_emitted"] == b.stats()["tokens_emitted"]
    assert b.stats()["fused_windows"] == 1
    # the unfused engine never counts fused windows
    assert a.stats()["fused_windows"] == 0


def test_draw_stream_pinned_across_fused_and_per_step(setup):
    # the sampled-window draw-accounting contract survives fusion: a
    # LATER admission must see the identical key stream whether the
    # earlier window ran fused, per-step harvested, or step-by-step —
    # _draws and the per-slot chains land in the same place
    model, params = setup

    def mk(fused):
        return ServingEngine(model, params, n_slots=2,
                             max_new_tokens=3, fused_decode=fused,
                             rng=jax.random.PRNGKey(5))

    a, b, c = mk(False), mk(True), mk(False)
    for e in (a, b, c):
        e.admit([3, 14, 15])                              # greedy
        e.admit([9, 9, 8], temperature=1.0, top_k=8)      # sampled
    a.run_scan(6)   # per-step harvest (both retire mid-window)
    b.run_scan(6)   # fused harvest of the same window
    for _ in range(6):
        c.step()    # step-by-step baseline
    assert a._draws == b._draws == c._draws
    assert a._slot_draws == b._slot_draws == c._slot_draws
    sa = a.admit([5, 17, 3], temperature=1.0, top_k=8)
    sb = b.admit([5, 17, 3], temperature=1.0, top_k=8)
    sc = c.admit([5, 17, 3], temperature=1.0, top_k=8)
    a.run_scan(2)
    b.run_scan(2)
    c.run_scan(2)
    assert a.output(sa) == b.output(sb) == c.output(sc)
