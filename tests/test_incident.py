"""Alert-triggered incident bundles (PR 19).

The guardrails under test, per the IncidentManager docstring: atomic
writes (a reader listing ``incident-*`` never sees a partial bundle),
one bundle per alert per ``min_interval_s``, newest-``keep`` GC that
spares foreign files, collector failures degrading to per-file error
markers instead of lost bundles, and the schema round-trip through
``obs_query --incident``.
"""

import json
import os
import time

import pytest

from tpu_k8s_device_plugin import obs

pytestmark = pytest.mark.filterwarnings("ignore")

T0 = 1_700_000_000.0


class FakeClock:
    def __init__(self, t=T0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


def _stack(clock):
    """One page rule over a collapsing gauge, with the full obs stack
    behind it — what a serving surface wires up for real."""
    reg = obs.Registry()
    rec = obs.FlightRecorder(registry=reg)
    goodput = reg.gauge("tpu_serve_goodput", "Goodput ratio.")
    goodput.set(1.0)
    tsdb = obs.TSDB(reg, now_fn=clock)
    rule = obs.threshold_rule(
        "goodput_page", "tpu_serve_goodput", "<", 0.5,
        for_s=0.0, severity="page",
        description="goodput collapsed")
    ev = obs.AlertEvaluator(tsdb, [rule], recorder=rec)
    prof = obs.SamplingProfiler(reg, hz=19.0, now_fn=clock,
                                phase_fn=lambda: "dispatch")
    return reg, rec, goodput, tsdb, rule, ev, prof


def _manager(tmp_path, clock, **kw):
    reg, rec, goodput, tsdb, rule, ev, prof = _stack(clock)
    prof.sample_once()
    mgr = obs.IncidentManager(
        str(tmp_path), ev, registry=reg, recorder=rec, tsdb=tsdb,
        profiler=prof,
        collectors={"statz.json": lambda: {"pending": 3}},
        now_fn=clock, **kw)
    return mgr, reg, rec, goodput, tsdb, rule, ev


def _bundles(tmp_path):
    return sorted(p for p in os.listdir(tmp_path)
                  if p.startswith(obs.BUNDLE_PREFIX))


# -- atomic write + round trip ----------------------------------------------

def test_bundle_write_is_atomic_and_complete(tmp_path):
    clock = FakeClock()
    mgr, reg, rec, goodput, tsdb, rule, ev = _manager(tmp_path, clock)
    tsdb.tick()
    path = mgr.write_bundle(rule, clock(), 0.1)
    # no tmp litter, and every listed incident-* dir is complete
    assert not [p for p in os.listdir(tmp_path)
                if p.startswith(".incident-tmp-")]
    for name in _bundles(tmp_path):
        assert os.path.isfile(
            os.path.join(tmp_path, name, "meta.json"))
    bundle = obs.read_bundle(path)
    meta = bundle["meta"]
    assert meta["schema"] == obs.BUNDLE_SCHEMA
    assert meta["alert"] == "goodput_page"
    assert meta["errors"] == {}
    for rel in ("alert.json", "journal.jsonl", "tsdb.json",
                "profile.folded", "profile.json", "statz.json"):
        assert rel in meta["files"], rel
        assert rel in bundle
    assert bundle["tsdb.json"]["schema"] == obs.TSDB_SNAPSHOT_SCHEMA
    assert bundle["profile.json"]["schema"] == obs.PROFILE_SCHEMA
    assert bundle["statz.json"] == {"pending": 3}
    # the tpu_serve_* core set made it into the snapshot
    assert any(s["name"] == "tpu_serve_goodput"
               for s in bundle["tsdb.json"]["series"])
    # accounting: counter child + journal event
    assert 'tpu_incident_bundles_total{alert="goodput_page"} 1' \
        in reg.render()
    events = rec.events(name=obs.INCIDENT_EVENT)
    assert len(events) == 1
    assert events[0]["attrs"]["alert"] == "goodput_page"


def test_read_bundle_rejects_partial_and_foreign(tmp_path):
    incomplete = tmp_path / "incident-x-1"
    incomplete.mkdir()
    with pytest.raises(ValueError, match="no meta.json"):
        obs.read_bundle(str(incomplete))
    (incomplete / "meta.json").write_text(json.dumps({"schema": "nope"}))
    with pytest.raises(ValueError, match="unknown bundle schema"):
        obs.read_bundle(str(incomplete))


def test_schema_round_trips_through_obs_query(tmp_path, capsys):
    from tools import obs_query

    clock = FakeClock()
    mgr, reg, rec, goodput, tsdb, rule, ev = _manager(tmp_path, clock)
    tsdb.tick()
    goodput.set(0.1)
    clock.advance(5.0)
    tsdb.tick()
    ev.evaluate()  # journal the real inactive->pending->firing history
    path = mgr.write_bundle(rule, clock(), 0.1)
    assert obs_query.main(["--incident", path]) == 0
    out = capsys.readouterr().out
    assert "alert=goodput_page severity=page" in out
    assert "pending -> firing" in out
    assert "phase dispatch" in out
    # JSON mode round-trips the whole bundle
    assert obs_query.main(["--incident", path, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["meta"]["alert"] == "goodput_page"
    # a non-bundle dir is a clean failure, not a traceback
    assert obs_query.main(["--incident", str(tmp_path)]) == 2


# -- trigger path -----------------------------------------------------------

def test_firing_transition_triggers_one_bundle(tmp_path):
    """The full chain, no worker thread: collapse the gauge, evaluate,
    drain the queue synchronously, find exactly one bundle."""
    clock = FakeClock()
    mgr, reg, rec, goodput, tsdb, rule, ev = _manager(tmp_path, clock)
    ev.evaluate()  # healthy: nothing enqueued
    assert mgr._queue.empty()
    goodput.set(0.1)
    clock.advance(5.0)
    tsdb.tick()
    ev.evaluate()
    item = mgr._queue.get_nowait()
    assert item is not None and item[0].name == "goodput_page"
    mgr.write_bundle(*item)
    assert len(_bundles(tmp_path)) == 1


def test_worker_thread_writes_bundle(tmp_path):
    clock = FakeClock()
    mgr, reg, rec, goodput, tsdb, rule, ev = _manager(tmp_path, clock)
    mgr.start()
    try:
        goodput.set(0.1)
        clock.advance(5.0)
        tsdb.tick()
        ev.evaluate()
        deadline = time.time() + 5.0
        while time.time() < deadline and not _bundles(tmp_path):
            time.sleep(0.01)
        assert len(_bundles(tmp_path)) == 1
    finally:
        mgr.stop()


def test_rate_limit_is_per_alert(tmp_path):
    clock = FakeClock()
    mgr, reg, rec, goodput, tsdb, rule, ev = _manager(
        tmp_path, clock, min_interval_s=300.0)
    mgr._on_transition(rule, "pending", "firing", clock(), 0.1)
    clock.advance(10.0)  # inside the interval: suppressed
    mgr._on_transition(rule, "pending", "firing", clock(), 0.1)
    assert mgr._queue.qsize() == 1
    clock.advance(300.0)  # past the interval: allowed again
    mgr._on_transition(rule, "pending", "firing", clock(), 0.1)
    assert mgr._queue.qsize() == 2
    # a DIFFERENT page alert is not throttled by this one's window
    other = obs.threshold_rule(
        "other_page", "tpu_serve_goodput", "<", 0.5,
        for_s=0.0, severity="page")
    mgr._on_transition(other, "pending", "firing", clock(), 0.1)
    assert mgr._queue.qsize() == 3


def test_non_page_and_non_firing_transitions_ignored(tmp_path):
    clock = FakeClock()
    mgr, reg, rec, goodput, tsdb, rule, ev = _manager(tmp_path, clock)
    ticket = obs.threshold_rule(
        "just_a_ticket", "tpu_serve_goodput", "<", 0.5,
        for_s=0.0, severity="ticket")
    mgr._on_transition(ticket, "pending", "firing", clock(), 0.1)
    mgr._on_transition(rule, "firing", "resolved", clock(), 0.9)
    assert mgr._queue.empty()


# -- degradation ------------------------------------------------------------

def test_broken_collector_degrades_to_error_marker(tmp_path):
    clock = FakeClock()
    reg, rec, goodput, tsdb, rule, ev, prof = _stack(clock)

    def broken():
        raise RuntimeError("replica unreachable")

    mgr = obs.IncidentManager(
        str(tmp_path), ev, registry=reg, recorder=rec, tsdb=tsdb,
        profiler=prof,
        collectors={"statz.json": lambda: {"ok": 1},
                    "traces.json": broken},
        now_fn=clock)
    path = mgr.write_bundle(rule, clock(), 0.1)
    meta = obs.read_bundle(path)["meta"]
    assert "statz.json" in meta["files"]
    assert "traces.json" not in meta["files"]
    assert "RuntimeError" in meta["errors"]["traces.json"]


def test_extra_files_nest_and_failures_are_contained(tmp_path):
    clock = FakeClock()
    reg, rec, goodput, tsdb, rule, ev, prof = _stack(clock)
    mgr = obs.IncidentManager(
        str(tmp_path), ev, registry=reg, recorder=rec,
        extra_files_fn=lambda: {
            "replicas/rep-0/statz.json": {"pending": 1},
            "replicas/rep-1/statz.json": {"unreachable": True,
                                          "error": "connection refused"},
        },
        now_fn=clock)
    bundle = obs.read_bundle(mgr.write_bundle(rule, clock(), 0.1))
    assert bundle["replicas/rep-0/statz.json"] == {"pending": 1}
    assert bundle["replicas/rep-1/statz.json"]["unreachable"] is True


# -- GC ---------------------------------------------------------------------

def test_gc_keeps_newest_and_spares_foreign_files(tmp_path):
    clock = FakeClock()
    mgr, reg, rec, goodput, tsdb, rule, ev = _manager(
        tmp_path, clock, keep=2, min_interval_s=0.0)
    (tmp_path / "oncall-notes.md").write_text("it was DNS\n")
    foreign = tmp_path / "some-other-dir"
    foreign.mkdir()
    paths = []
    for _ in range(4):
        clock.advance(1.0)
        p = mgr.write_bundle(rule, clock(), 0.1)
        paths.append(p)
        # mtime granularity: make ordering unambiguous for the GC
        stamp = clock()
        os.utime(p, (stamp, stamp))
    kept = _bundles(tmp_path)
    assert len(kept) == 2
    assert os.path.basename(paths[-1]) in kept
    assert os.path.basename(paths[-2]) in kept
    assert (tmp_path / "oncall-notes.md").exists()
    assert foreign.exists()


def test_keep_validation():
    with pytest.raises(ValueError):
        obs.IncidentManager(
            "/tmp/x", obs.AlertEvaluator(
                obs.TSDB(obs.Registry()), []),
            registry=obs.Registry(), keep=0)


# -- metrics ----------------------------------------------------------------

def test_incident_metrics_are_promlint_clean(tmp_path):
    from tools.promlint import lint

    clock = FakeClock()
    mgr, reg, rec, goodput, tsdb, rule, ev = _manager(tmp_path, clock)
    mgr.write_bundle(rule, clock(), 0.1)
    for om in (False, True):
        problems = lint(reg.render(openmetrics=om), openmetrics=om)
        assert problems == [], problems
