"""In-process fake kubelet for plugin/manager tests.

The reference has no test coverage of the gRPC adapter, registration flow,
or Allocate responses (SURVEY.md §4 'Not tested anywhere'); this harness
closes that gap: it serves the kubelet Registration service on kubelet.sock
in a temp device-plugin dir, records registrations, and can drive a
registered plugin exactly as the kubelet would (ListAndWatch stream,
Allocate, GetPreferredAllocation).
"""

from __future__ import annotations

import concurrent.futures
import os
import queue
import threading
from typing import List, Optional

import grpc

from tpu_k8s_device_plugin.proto import (
    deviceplugin_pb2 as pluginapi,
    deviceplugin_pb2_grpc as pluginapi_grpc,
)


class _RegistrationServicer(pluginapi_grpc.RegistrationServicer):
    def __init__(self, kubelet: "FakeKubelet"):
        self._kubelet = kubelet

    def Register(self, request, context):
        self._kubelet.registrations.append(request)
        self._kubelet.register_event.set()
        return pluginapi.Empty()


class FakeKubelet:
    """Owns a device-plugin dir with a kubelet.sock Registration server."""

    def __init__(self, device_plugin_dir: str):
        self.dir = device_plugin_dir
        os.makedirs(self.dir, exist_ok=True)
        self.socket_path = os.path.join(self.dir, "kubelet.sock")
        self.registrations: List[pluginapi.RegisterRequest] = []
        self.register_event = threading.Event()
        self._server: Optional[grpc.Server] = None

    def start(self) -> "FakeKubelet":
        if os.path.exists(self.socket_path):
            os.remove(self.socket_path)
        self._server = grpc.server(
            concurrent.futures.ThreadPoolExecutor(max_workers=4)
        )
        pluginapi_grpc.add_RegistrationServicer_to_server(
            _RegistrationServicer(self), self._server
        )
        self._server.add_insecure_port(f"unix://{self.socket_path}")
        self._server.start()
        return self

    def stop(self, remove_socket: bool = True) -> None:
        if self._server is not None:
            self._server.stop(grace=0).wait()
            self._server = None
        if remove_socket and os.path.exists(self.socket_path):
            os.remove(self.socket_path)

    def restart(self, wipe_dir: bool = False) -> None:
        """Simulate a kubelet restart (socket re-creation).  With
        ``wipe_dir`` the device-plugin dir is cleared first, matching the
        real kubelet's removeContents on startup."""
        self.stop()
        if wipe_dir:
            for name in os.listdir(self.dir):
                try:
                    os.remove(os.path.join(self.dir, name))
                except OSError:
                    pass
        self.start()

    def wait_for_registration(self, timeout: float = 5.0) -> bool:
        ok = self.register_event.wait(timeout)
        self.register_event.clear()
        return ok

    # -- driving a registered plugin the way kubelet does -------------------

    def plugin_channel(self, endpoint: str) -> grpc.Channel:
        return grpc.insecure_channel(
            f"unix://{os.path.join(self.dir, endpoint)}"
        )

    def plugin_stub(self, endpoint: str) -> pluginapi_grpc.DevicePluginStub:
        return pluginapi_grpc.DevicePluginStub(self.plugin_channel(endpoint))


class ListAndWatchConsumer:
    """Background consumer of a plugin's ListAndWatch stream."""

    def __init__(self, stub: pluginapi_grpc.DevicePluginStub):
        self.frames: "queue.Queue[pluginapi.ListAndWatchResponse]" = queue.Queue()
        self._call = stub.ListAndWatch(pluginapi.Empty())
        self._thread = threading.Thread(target=self._consume, daemon=True)
        self._thread.start()

    def _consume(self):
        try:
            for frame in self._call:
                self.frames.put(frame)
        except grpc.RpcError:
            pass

    def next_frame(self, timeout: float = 5.0) -> pluginapi.ListAndWatchResponse:
        return self.frames.get(timeout=timeout)

    def cancel(self):
        self._call.cancel()
