"""SLO/goodput accounting, window-phase breakdown, trace stitching,
profiling hook, and flight-dump GC (PR 12).

Four layers:

1. Pure units (no jax): the --slo grammar, policy verdicts, label
   BOUNDING (free-form class/tenant names collapse to 'other'), the
   rolling window, burn-rate math, the stitch re-linker, and the
   flight-recorder dump GC.
2. tools/obs_query.py against dump FILES: the same span tree the
   router serves live must render from post-mortem dumps alone.
3. Real-engine server e2e (jax, tiny decoder): every terminal request
   lands in tpu_slo_requests_total, the /statz goodput block agrees
   with a hand-computed goodput from the client's own TTFT
   observations, the window-phase families and duty-cycle gauge are
   live, /debug/profile captures a jax.profiler trace, and every new
   family is promlint-clean in BOTH exposition modes.
"""

import http.client
import json
import os
import time

import pytest

from tpu_k8s_device_plugin import obs
from tpu_k8s_device_plugin.obs.slo import (
    DEFAULT_TENANT_LABEL,
    OTHER_LABEL,
    SLOAccountant,
    SLOPolicy,
    default_slo_policies,
    parse_slo_specs,
)

# ---------------------------------------------------------------------------
# layer 1a: the --slo grammar + policy verdicts


def test_parse_slo_specs_grammar():
    out = parse_slo_specs(["interactive=250", "batch=0:60000",
                           "both=100:5000"])
    assert out["interactive"].ttft_ms == 250.0
    assert out["interactive"].deadline_ms is None
    assert out["batch"].ttft_ms is None
    assert out["batch"].deadline_ms == 60000.0
    assert out["both"].ttft_ms == 100.0
    assert out["both"].deadline_ms == 5000.0
    assert parse_slo_specs(None) == {}
    for bad in ("noequals", "=250", "c=", "c=1:2:3", "c=abc",
                "c=0:0"):
        with pytest.raises(ValueError):
            parse_slo_specs([bad])


def test_policy_verdicts():
    ttft = SLOPolicy("i", ttft_ms=100.0)
    assert ttft.met(0.05, 99.0)          # ttft under, total ignored
    assert not ttft.met(0.2, 0.2)        # ttft over
    assert not ttft.met(None, 0.0)       # never streamed a token
    dl = SLOPolicy("b", deadline_ms=1000.0)
    assert dl.met(None, 0.5)             # no ttft target
    assert not dl.met(0.001, 1.5)        # deadline blown
    both = SLOPolicy("x", ttft_ms=100.0, deadline_ms=1000.0)
    assert both.met(0.05, 0.5)
    assert not both.met(0.05, 2.0)
    with pytest.raises(ValueError):
        SLOPolicy("empty")               # needs at least one target
    with pytest.raises(ValueError):
        SLOPolicy("bad", ttft_ms=1.0, objective=1.5)


# ---------------------------------------------------------------------------
# layer 1b: the accountant — bounding, window, burn rate


def _accountant(**kw):
    reg = obs.Registry()
    kw.setdefault("policies", default_slo_policies())
    return reg, SLOAccountant(reg, **kw)


def test_class_and_tenant_label_values_are_bounded():
    """Free-form request-supplied names must NEVER mint a label value:
    unknown classes and tenants collapse to 'other' (the O1 contract
    this module is the runtime half of)."""
    reg, acc = _accountant(tenants=["paid", "free"])
    acc.record("interactive", "paid", ttft_s=0.01, total_s=0.1,
               ok=True)
    acc.record("../../etc/passwd", "mallory-" + "x" * 100,
               ttft_s=0.01, total_s=0.1, ok=True)
    acc.record("", "", ttft_s=0.01, total_s=0.1, ok=True)
    samples = obs.parse_exposition(reg.render())
    seen_classes = {lab["class"] for n, lab, v in samples
                    if n == "tpu_slo_requests_total"}
    seen_tenants = {lab["tenant"] for n, lab, v in samples
                    if n == "tpu_slo_requests_total"}
    assert seen_classes == {"interactive", OTHER_LABEL}
    assert seen_tenants == {"paid", OTHER_LABEL,
                            DEFAULT_TENANT_LABEL}


def test_classless_request_lands_under_fallback():
    reg, acc = _accountant()
    acc.record(None, None, ttft_s=None, total_s=0.1, ok=True,
               fallback="batch")
    samples = obs.parse_exposition(reg.render())
    rows = [(lab["class"], lab["met"]) for n, lab, v in samples
            if n == "tpu_slo_requests_total"]
    assert rows == [("batch", "true")]


def test_non_ok_outcome_never_meets():
    reg, acc = _accountant()
    met = acc.record("interactive", "", ttft_s=0.0001, total_s=0.001,
                     ok=False)
    assert met is False
    assert acc.summary()["classes"]["interactive"]["met"] == 0


def test_goodput_ratio_and_burn_rate():
    reg, acc = _accountant(policies={
        "i": SLOPolicy("i", ttft_ms=100.0, objective=0.9)})
    for _ in range(8):
        acc.record("i", "", ttft_s=0.01, total_s=0.1, ok=True)
    for _ in range(2):
        acc.record("i", "", ttft_s=9.9, total_s=10.0, ok=True)
    row = acc.summary()["classes"]["i"]
    assert row["total"] == 10 and row["met"] == 8
    assert row["goodput_ratio"] == pytest.approx(0.8)
    # miss rate 0.2 over the 0.1 budget = burning 2x
    assert row["burn_rate"] == pytest.approx(2.0)
    # the gauges tell the same story after a scrape
    samples = obs.parse_exposition(reg.render())
    by = {(n, lab.get("class")): v for n, lab, v in samples}
    assert by[("tpu_slo_goodput_ratio", "i")] == pytest.approx(0.8)
    assert by[("tpu_slo_error_budget_burn_rate", "i")] == \
        pytest.approx(2.0)


def test_rolling_window_expires_old_requests():
    reg, acc = _accountant(window_s=0.05)
    acc.record("interactive", "", ttft_s=0.01, total_s=0.1, ok=True)
    assert acc.summary()["classes"]["interactive"]["window_total"] == 1
    time.sleep(0.08)
    row = acc.summary()["classes"]["interactive"]
    assert row["window_total"] == 0
    assert row["goodput_ratio"] == 1.0   # empty window: not burning
    assert row["total"] == 1             # lifetime totals remain


def test_slo_families_promlint_clean_both_modes():
    import tools.promlint as promlint

    reg, acc = _accountant()
    acc.record("interactive", "", ttft_s=0.01, total_s=0.1, ok=True)
    assert promlint.lint(reg.render()) == []
    assert promlint.lint(reg.render(openmetrics=True)) == []


# ---------------------------------------------------------------------------
# layer 1c: stitch re-linker


def _ev(name, trace, t, source=""):
    d = {"name": name, "trace_id": trace.trace_id,
         "span_id": trace.span_id,
         "parent_id": trace.parent_id or "", "t_wall": t,
         "t_mono": t, "attrs": {}}
    if source:
        d["source"] = source
    return d


def test_stitch_links_child_span_under_parent():
    root = obs.new_trace()          # the router's context
    child = root.child()            # the replica continued it
    events = [
        _ev("tpu_serve_admit", child, 2.0, source="r0"),
        _ev("tpu_router_routed", root, 1.0, source="router"),
        _ev("tpu_serve_window", child, 3.0, source="r0"),
        _ev("tpu_router_proxy", root, 4.0, source="router"),
    ]
    tree = obs.stitch(events)
    assert len(tree) == 1
    node = tree[0]
    assert node["source"] == "router"
    assert [e["name"] for e in node["events"]] == [
        "tpu_router_routed", "tpu_router_proxy"]
    assert len(node["children"]) == 1
    kid = node["children"][0]
    assert kid["source"] == "r0"
    assert kid["parent_id"] == root.span_id
    assert [e["name"] for e in kid["events"]] == [
        "tpu_serve_admit", "tpu_serve_window"]
    # depth-first flatten = the causal read order
    flat = [e["name"] for e in obs.flatten(tree)]
    assert flat.index("tpu_router_routed") \
        < flat.index("tpu_serve_admit") \
        < flat.index("tpu_serve_window")


def test_stitch_tolerates_parentless_legacy_events():
    """Events from pre-parent_id dumps still stitch (as roots)."""
    tree = obs.stitch([
        {"name": "old", "trace_id": "t", "span_id": "s1",
         "t_wall": 1.0, "attrs": {}},
        {"name": "older", "trace_id": "t", "span_id": "s2",
         "t_wall": 0.5, "attrs": {}},
    ])
    assert [n["events"][0]["name"] for n in tree] == ["older", "old"]
    text = obs.render_tree(tree)
    assert "old" in text and "older" in text


# ---------------------------------------------------------------------------
# layer 1d: flight-recorder dump GC


def test_dump_gc_keeps_newest_k(tmp_path):
    reg = obs.Registry()
    rec = obs.FlightRecorder(capacity=16, registry=reg, dump_keep=3)
    rec.record("something")
    # 5 pre-existing dumps from prior crashed incarnations
    for i in range(5):
        p = tmp_path / f"flight-100-{1000 + i}.jsonl"
        p.write_text("{}\n")
        os.utime(p, (1000 + i, 1000 + i))
    new_path = rec.dump_to_dir(str(tmp_path))
    assert new_path is not None
    left = sorted(f for f in os.listdir(tmp_path)
                  if f.startswith("flight-"))
    assert len(left) == 3
    # the newest survive: the fresh dump + the two youngest old ones
    assert os.path.basename(new_path) in left
    assert "flight-100-1004.jsonl" in left
    assert "flight-100-1003.jsonl" in left
    assert rec.dump_gc_count == 3
    samples = obs.parse_exposition(reg.render())
    assert ("tpu_flight_dump_gc_total", {}, 3.0) in samples


def test_dump_gc_spares_other_files(tmp_path):
    rec = obs.FlightRecorder(capacity=16, dump_keep=1)
    rec.record("x")
    keepers = ["faulthandler-1.log", "notes.txt"]
    for name in keepers:
        (tmp_path / name).write_text("keep me\n")
    for i in range(3):
        p = tmp_path / f"flight-7-{i}.jsonl"
        p.write_text("{}\n")
        os.utime(p, (100 + i, 100 + i))
    rec.dump_to_dir(str(tmp_path))
    left = set(os.listdir(tmp_path))
    for name in keepers:
        assert name in left
    assert sum(1 for f in left if f.startswith("flight-")) == 1


# ---------------------------------------------------------------------------
# layer 2: obs_query over dump files

import sys  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools import obs_query  # noqa: E402


def test_obs_query_stitches_from_dumps(tmp_path, capsys):
    """The acceptance path: after the processes die, their dump files
    alone must reproduce the stitched tree — router events in one
    dump, replica events in another, re-linked by parent_id."""
    root = obs.new_trace()
    child = root.child()
    router_rec = obs.FlightRecorder(capacity=64)
    router_rec.record("tpu_router_routed", trace=root, replica="r0")
    router_rec.record("tpu_router_proxy", trace=root, outcome="ok")
    replica_rec = obs.FlightRecorder(capacity=64)
    replica_rec.record("tpu_serve_admit", trace=child, slot=0)
    replica_rec.record("tpu_serve_window", trace=child, tokens=4)
    rdir = tmp_path / "router"
    pdir = tmp_path / "replica"
    assert router_rec.dump_to_dir(str(rdir))
    assert replica_rec.dump_to_dir(str(pdir))

    rc = obs_query.main(["--trace-id", root.trace_id,
                         "--dump", str(rdir), "--dump", str(pdir)])
    out = capsys.readouterr().out
    assert rc == 0
    for name in ("tpu_router_routed", "tpu_router_proxy",
                 "tpu_serve_admit", "tpu_serve_window"):
        assert name in out
    # the replica span renders NESTED under the router span
    router_line = next(ln for ln in out.splitlines()
                       if ln.lstrip().startswith(
                           f"span {root.span_id[:16]}"))
    child_line = next(ln for ln in out.splitlines()
                      if ln.lstrip().startswith(
                          f"span {child.span_id[:16]}"))
    indent = len(child_line) - len(child_line.lstrip())
    assert indent > len(router_line) - len(router_line.lstrip())
    # JSON mode round-trips the same tree
    rc = obs_query.main(["--trace-id", root.trace_id,
                         "--dump", str(rdir), "--dump", str(pdir),
                         "--json"])
    payload = json.loads(capsys.readouterr().out)
    assert payload["events"] == 4
    assert payload["tree"][0]["children"][0]["span_id"] == \
        child.span_id


def test_obs_query_time_range_mode(tmp_path, capsys):
    rec = obs.FlightRecorder(capacity=16)
    rec.record("early", note="a")
    rec.record("late", note="b")
    events = rec.events()
    cut = events[0]["t_wall"]
    rec.dump_to_dir(str(tmp_path))
    rc = obs_query.main(["--dump", str(tmp_path),
                         "--since", str(cut)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "late" in out and "early" not in out
    # an empty result exits nonzero (scripts can branch on it)
    rc = obs_query.main(["--dump", str(tmp_path), "--name",
                         "no-such-event"])
    capsys.readouterr()
    assert rc == 1


# ---------------------------------------------------------------------------
# layer 3: real-engine server e2e

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from tpu_k8s_device_plugin.workloads.inference import make_decoder  # noqa: E402
from tpu_k8s_device_plugin.workloads.server import EngineServer  # noqa: E402
from tpu_k8s_device_plugin.workloads.serving import ServingEngine  # noqa: E402

CFG = dict(vocab=128, d_model=64, n_heads=4, n_layers=2, d_ff=128)


@pytest.fixture(scope="module")
def slo_server(tmp_path_factory):
    model = make_decoder(**CFG, max_len=64, dtype=jnp.float32)
    rng = jax.random.PRNGKey(0)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    params = model.init(rng, tokens, pos)["params"]
    eng = ServingEngine(model, params, n_slots=2)
    profile_dir = str(tmp_path_factory.mktemp("profiles"))
    # one generous class (everything meets) and one impossible class
    # (nothing can): borderline-free, so the hand-computed goodput
    # below must agree EXACTLY with the server's accounting
    srv = EngineServer(
        eng, max_new_tokens=8, window=4,
        slo_policies=parse_slo_specs(
            ["lenient=60000:600000", "impossible=0.0001"]),
        profile_dir=profile_dir)
    srv.start(host="127.0.0.1", port=0)
    yield srv
    srv.stop()


def _post(port, payload):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    t0 = time.perf_counter()
    conn.request("POST", "/generate", json.dumps(payload),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    first_line_at = None
    for line in resp:
        if line.strip() and first_line_at is None:
            first_line_at = time.perf_counter() - t0
    conn.close()
    return resp.status, first_line_at


def _get_json(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request("GET", path)
    resp = conn.getresponse()
    out = json.loads(resp.read())
    conn.close()
    return resp.status, out


def test_server_goodput_agrees_with_hand_computed(slo_server):
    srv = slo_server
    client_ttfts = []
    for _ in range(3):
        st, ttft = _post(srv.port, {
            "tokens": [5, 6, 7], "max_new_tokens": 4,
            "slo_class": "lenient"})
        assert st == 200
        client_ttfts.append(ttft)
    for _ in range(2):
        st, _ = _post(srv.port, {
            "tokens": [9, 9], "max_new_tokens": 4,
            "slo_class": "impossible"})
        assert st == 200
    # hand-computed goodput from the client's own recorded TTFTs:
    # every lenient TTFT is under its 60s target, no impossible TTFT
    # can beat 0.0001ms — the server's families must agree exactly
    hand_met = {
        "lenient": sum(1 for t in client_ttfts if t is not None
                       and t * 1000.0 <= 60000.0),
        "impossible": 0,
    }
    assert hand_met["lenient"] == 3
    samples = obs.parse_exposition(srv.render_metrics())
    counts = {}
    for n, lab, v in samples:
        if n == "tpu_slo_requests_total":
            counts[(lab["class"], lab["met"])] = v
    assert counts.get(("lenient", "true"), 0) == hand_met["lenient"]
    assert ("lenient", "false") not in counts
    assert counts.get(("impossible", "false"), 0) == 2
    assert ("impossible", "true") not in counts
    # /statz carries the same truth in its fixed goodput schema
    _, statz = _get_json(srv.port, "/statz")
    g = statz["goodput"]["classes"]
    assert g["lenient"]["met"] == 3
    assert g["lenient"]["goodput_ratio"] == 1.0
    assert g["impossible"]["total"] == 2
    assert g["impossible"]["met"] == 0
    assert g["impossible"]["goodput_ratio"] == 0.0
    assert g["impossible"]["burn_rate"] == pytest.approx(
        1.0 / (1.0 - 0.99))
    # the goodput gauges agree with the summary after a scrape
    by = {(n, lab.get("class")): v
          for n, lab, v in obs.parse_exposition(srv.render_metrics())}
    assert by[("tpu_slo_goodput_ratio", "lenient")] == 1.0
    assert by[("tpu_slo_goodput_ratio", "impossible")] == 0.0


def test_server_bounds_request_supplied_names(slo_server):
    srv = slo_server
    st, _ = _post(srv.port, {
        "tokens": [1, 2, 3], "max_new_tokens": 2,
        "slo_class": "free-form-$$$", "tenant": "mallory"})
    assert st == 200
    samples = obs.parse_exposition(srv.render_metrics())
    labels = [lab for n, lab, v in samples
              if n == "tpu_slo_requests_total"]
    assert all(lab["class"] in ("lenient", "impossible", OTHER_LABEL)
               for lab in labels)
    assert any(lab["class"] == OTHER_LABEL
               and lab["tenant"] == OTHER_LABEL for lab in labels)


def test_window_phase_families_and_duty_cycle(slo_server):
    srv = slo_server
    _post(srv.port, {"tokens": [4, 4, 4], "max_new_tokens": 6})
    samples = obs.parse_exposition(srv.render_metrics())
    phase_counts = {
        lab["phase"]: v for n, lab, v in samples
        if n == "tpu_serve_window_phase_seconds_count"}
    assert set(phase_counts) == {"dispatch", "harvest", "stream",
                                 "idle"}
    for phase in ("dispatch", "harvest", "stream"):
        assert phase_counts[phase] > 0, phase
    duty = [v for n, lab, v in samples
            if n == "tpu_serve_device_duty_cycle"]
    assert len(duty) == 1 and 0.0 <= duty[0] <= 1.0


def test_debug_profile_captures_trace(slo_server):
    srv = slo_server
    st, out = _get_json(srv.port, "/debug/profile?seconds=0.2")
    assert st == 200 and out["ok"] is True
    assert os.listdir(srv.profile_dir)  # the profiler wrote something
    # bad inputs answer 400, not a stack trace
    st, out = _get_json(srv.port, "/debug/profile?seconds=9999")
    assert st == 400
    st, out = _get_json(srv.port, "/debug/profile?seconds=abc")
    assert st == 400
    samples = obs.parse_exposition(srv.render_metrics())
    assert ("tpu_serve_profile_captures_total", {}, 1.0) in samples


def test_debug_profile_requires_profile_dir():
    model = make_decoder(**CFG, max_len=32, dtype=jnp.float32)
    rng = jax.random.PRNGKey(1)
    tokens = jnp.zeros((1, 4), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(4, dtype=jnp.int32), (1, 4))
    params = model.init(rng, tokens, pos)["params"]
    srv = EngineServer(ServingEngine(model, params, n_slots=1),
                       max_new_tokens=4, window=2)
    srv.start(host="127.0.0.1", port=0)
    try:
        st, out = _get_json(srv.port, "/debug/profile?seconds=0.1")
        assert st == 400
        assert "--profile-dir" in out["error"]
    finally:
        srv.stop()


def test_server_metrics_promlint_clean_both_modes(slo_server):
    import tools.promlint as promlint

    srv = slo_server
    assert promlint.lint(srv.render_metrics()) == []
    assert promlint.lint(srv.render_metrics(openmetrics=True)) == []
