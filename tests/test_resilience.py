"""Resilience layer: retry/breaker/watchdog policies, deterministic
fault injection, suppressed-error accounting, and the crash-containment
paths they guard (manager thread joins, probe watchdog demotion, the
serving scheduler supervisor).
"""

import inspect
import os
import sys
import threading
import time

import pytest

from tpu_k8s_device_plugin import obs, resilience
from tpu_k8s_device_plugin.resilience import faults

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools"))
import promlint  # noqa: E402


# -- RetryPolicy -------------------------------------------------------------

def test_retry_backoff_deterministic_per_seed():
    a = resilience.RetryPolicy(jitter=0.3, seed=7)
    b = resilience.RetryPolicy(jitter=0.3, seed=7)
    c = resilience.RetryPolicy(jitter=0.3, seed=8)
    sched_a = [a.backoff_s(i) for i in range(1, 6)]
    sched_b = [b.backoff_s(i) for i in range(1, 6)]
    sched_c = [c.backoff_s(i) for i in range(1, 6)]
    assert sched_a == sched_b
    assert sched_a != sched_c


def test_retry_backoff_exponential_and_capped():
    p = resilience.RetryPolicy(
        initial_backoff_s=1.0, max_backoff_s=4.0, multiplier=2.0,
        jitter=0.0)
    assert [p.backoff_s(i) for i in range(1, 5)] == [1.0, 2.0, 4.0, 4.0]


def test_retry_succeeds_after_transient_failures():
    calls = []

    def fn():
        calls.append(1)
        if len(calls) < 3:
            raise ValueError("transient")
        return "ok"

    p = resilience.RetryPolicy(max_attempts=5, initial_backoff_s=0.001,
                               jitter=0.0)
    reg = obs.Registry()
    m = resilience.ResilienceMetrics(reg)
    assert p.call(fn, op="t", retry_on=(ValueError,), metrics=m) == "ok"
    assert len(calls) == 3
    assert m.retries.labels(op="t").value == 2
    assert m.giveups.labels(op="t").value == 0


def test_retry_exhaustion_raises_last_and_counts_giveup():
    p = resilience.RetryPolicy(max_attempts=3, initial_backoff_s=0.001,
                               jitter=0.0)
    reg = obs.Registry()
    m = resilience.ResilienceMetrics(reg)
    with pytest.raises(ValueError, match="always"):
        p.call(lambda: (_ for _ in ()).throw(ValueError("always")),
               op="t", retry_on=(ValueError,), metrics=m)
    assert m.giveups.labels(op="t").value == 1


def test_retry_non_retryable_propagates_immediately():
    calls = []

    def fn():
        calls.append(1)
        raise KeyError("not transient")

    p = resilience.RetryPolicy(max_attempts=5, initial_backoff_s=0.001)
    with pytest.raises(KeyError):
        p.call(fn, op="t", retry_on=(ValueError,))
    assert len(calls) == 1


def test_retry_deadline_stops_the_loop():
    p = resilience.RetryPolicy(max_attempts=1000,
                               initial_backoff_s=0.02, jitter=0.0,
                               deadline_s=0.1)
    t0 = time.monotonic()
    with pytest.raises(ValueError):
        p.call(lambda: (_ for _ in ()).throw(ValueError("x")), op="t",
               retry_on=(ValueError,))
    assert time.monotonic() - t0 < 2.0


def test_retry_stop_event_aborts_backoff():
    stop = threading.Event()
    calls = []

    def fn():
        calls.append(1)
        stop.set()  # set mid-loop: the backoff wait must abort
        raise ValueError("x")

    p = resilience.RetryPolicy(max_attempts=100,
                               initial_backoff_s=30.0, jitter=0.0)
    t0 = time.monotonic()
    with pytest.raises(ValueError):
        p.call(fn, op="t", retry_on=(ValueError,), stop=stop)
    assert time.monotonic() - t0 < 5.0
    assert len(calls) == 1


# -- CircuitBreaker ----------------------------------------------------------

def test_breaker_opens_after_threshold_and_recovers():
    reg = obs.Registry()
    m = resilience.ResilienceMetrics(reg)
    rec = obs.FlightRecorder(registry=reg)
    br = resilience.CircuitBreaker("op1", failure_threshold=3,
                                   reset_timeout_s=0.05, metrics=m,
                                   recorder=rec)
    boom = lambda: (_ for _ in ()).throw(RuntimeError("down"))  # noqa: E731
    for _ in range(3):
        with pytest.raises(RuntimeError):
            br.call(boom)
    assert br.state == resilience.BREAKER_OPEN
    assert m.breaker_state.labels(op="op1").value == \
        resilience.BREAKER_OPEN
    # open: fail fast without running the callable
    with pytest.raises(resilience.CircuitOpenError):
        br.call(lambda: pytest.fail("must not run while open"))
    # after the reset window ONE probe is admitted and closes it
    time.sleep(0.06)
    assert br.call(lambda: "alive") == "alive"
    assert br.state == resilience.BREAKER_CLOSED
    # transitions journaled for the chaos assertions
    names = {e["attrs"]["to"] for e in
             rec.events(name="tpu_breaker_transition")}
    assert {"open", "half_open", "closed"} <= names


def test_breaker_half_open_failure_reopens():
    br = resilience.CircuitBreaker("op2", failure_threshold=1,
                                   reset_timeout_s=0.02)
    with pytest.raises(RuntimeError):
        br.call(lambda: (_ for _ in ()).throw(RuntimeError("x")))
    assert br.state == resilience.BREAKER_OPEN
    time.sleep(0.03)
    with pytest.raises(RuntimeError):
        br.call(lambda: (_ for _ in ()).throw(RuntimeError("still")))
    assert br.state == resilience.BREAKER_OPEN


def test_breaker_admits_exactly_one_half_open_probe():
    br = resilience.CircuitBreaker("op3", failure_threshold=1,
                                   reset_timeout_s=0.01)
    br.record_failure()
    time.sleep(0.02)
    assert br.allow()        # the probe slot
    assert not br.allow()    # concurrent caller: refused
    br.record_success()
    assert br.allow()        # closed again


# -- Watchdog ----------------------------------------------------------------

def test_watchdog_passes_result_and_exceptions():
    wd = resilience.Watchdog("w", timeout_s=5.0)
    assert wd.call(lambda: 42) == 42
    with pytest.raises(KeyError):
        wd.call(lambda: (_ for _ in ()).throw(KeyError("inner")))


def test_watchdog_abandons_hung_call_and_counts_trip():
    reg = obs.Registry()
    m = resilience.ResilienceMetrics(reg)
    rec = obs.FlightRecorder(registry=reg)
    wd = resilience.Watchdog("w2", timeout_s=0.05, metrics=m,
                             recorder=rec)
    release = threading.Event()
    t0 = time.monotonic()
    with pytest.raises(resilience.WatchdogTimeout):
        wd.call(lambda: release.wait(10.0))
    assert time.monotonic() - t0 < 5.0
    assert m.watchdog_trips.labels(op="w2").value == 1
    assert rec.events(name="tpu_watchdog_trip")
    release.set()  # let the abandoned worker exit


# -- suppressed-error accounting --------------------------------------------

def test_suppressed_counts_by_site_and_renders_clean():
    reg = obs.Registry()
    m = resilience.ResilienceMetrics(reg)
    resilience.suppressed("test.site", ValueError("swallowed"),
                          metrics=m)
    resilience.suppressed("test.site", OSError("again"), metrics=m)
    assert m.suppressed.labels(site="test.site").value == 2
    body = reg.render()
    assert 'tpu_suppressed_errors_total{site="test.site"} 2' in body
    assert promlint.lint(body) == []


def test_resilience_families_promlint_clean():
    """The satellite gate: every new resilience family renders through
    the shared renderer promlint-clean, with populated series."""
    reg = obs.Registry()
    m = resilience.ResilienceMetrics(reg)
    m.retries.labels(op="kubelet.register").inc()
    m.giveups.labels(op="kubelet.register").inc()
    m.breaker_state.labels(op="probe").set(resilience.BREAKER_OPEN)
    m.breaker_transitions.labels(op="probe", to="open").inc()
    m.watchdog_trips.labels(op="probe").inc()
    m.suppressed.labels(site="manager.make_watcher").inc()
    body = reg.render()
    for fam in ("tpu_resilience_retries_total",
                "tpu_resilience_giveups_total",
                "tpu_breaker_state", "tpu_breaker_transitions_total",
                "tpu_watchdog_trips_total",
                "tpu_suppressed_errors_total"):
        assert fam in body, fam
    assert promlint.lint(body) == []


# -- fault spec / injector ---------------------------------------------------

def test_fault_spec_parses_the_documented_grammar():
    spec = faults.FaultSpec.parse(
        "slice.join:error:0.3;probe:hang:5;kubelet.register:drop:0.5")
    assert [(r.op, r.kind) for r in spec.rules] == [
        ("slice.join", "error"), ("probe", "hang"),
        ("kubelet.register", "drop")]
    assert spec.rules[0].prob == 0.3
    assert spec.rules[1].arg == 5.0 and spec.rules[1].prob == 1.0
    # optional hang probability as the 4th field
    spec = faults.FaultSpec.parse("probe:hang:2:0.25")
    assert spec.rules[0].arg == 2.0 and spec.rules[0].prob == 0.25


@pytest.mark.parametrize("bad", [
    "x:boom:1",         # unknown kind
    "x:error:2",        # probability out of range
    "x:hang:-1",        # negative hang
    "x:error",          # missing arg
    ":error:1",         # empty op
    "x:error:0.5:0.5",  # error takes prob as arg, no 4th field
    "x:error:abc",      # non-numeric arg
])
def test_fault_spec_rejects_malformed_rules(bad):
    with pytest.raises(ValueError):
        faults.FaultSpec.parse(bad)


def test_injector_is_deterministic_per_seed():
    spec = faults.FaultSpec.parse("op:error:0.4")

    def run(seed):
        inj = faults.FaultInjector(spec, seed=seed)
        pattern = []
        for _ in range(50):
            try:
                inj.fire("op")
                pattern.append(0)
            except faults.InjectedFault:
                pattern.append(1)
        return pattern

    assert run(3) == run(3)
    assert run(3) != run(4)


def test_injector_counts_and_journals_fires():
    reg = obs.Registry()
    rec = obs.FlightRecorder(registry=reg)
    inj = faults.FaultInjector(faults.FaultSpec.parse("op:drop:1"),
                               seed=0, recorder=rec)
    with pytest.raises(faults.InjectedFault):
        inj.fire("op")
    inj.fire("other.op")  # no rule: no-op
    assert inj.fired == {"op:drop": 1}
    assert inj.fired_count("op") == 1
    [ev] = rec.events(name="tpu_fault_injected")
    assert ev["attrs"]["op"] == "op" and ev["attrs"]["kind"] == "drop"


def test_install_uninstall_and_env(monkeypatch):
    assert faults.install("") is None and faults.ACTIVE is None
    inj = faults.install("op:error:1", seed=5)
    try:
        assert faults.ACTIVE is inj and faults.active() is inj
    finally:
        faults.uninstall()
    assert faults.ACTIVE is None
    monkeypatch.setenv(faults.ENV_FAULTS, "op:hang:1")
    monkeypatch.setenv(faults.ENV_FAULT_SEED, "9")
    inj = faults.install_from_env()
    try:
        assert inj is not None and inj.seed == 9
    finally:
        faults.uninstall()


# -- inert-when-unset: the acceptance-criteria no-op check -------------------

def test_faults_disarmed_by_default():
    assert faults.ACTIVE is None


def test_hot_path_hooks_are_bare_attribute_checks():
    """Every hot-path injection site must be the inline
    ``if faults.ACTIVE is not None`` guard — one module-attribute load
    and an identity test when disarmed, no function call."""
    from tpu_k8s_device_plugin.health import client as health_client
    from tpu_k8s_device_plugin.health import server as health_server
    from tpu_k8s_device_plugin.manager import manager as manager_mod
    from tpu_k8s_device_plugin.slice import client as slice_client
    from tpu_k8s_device_plugin.workloads import scheduler as sched_mod

    guard = "if faults.ACTIVE is not None:"
    for fn in (
        # the serve.step/serve.schedule site moved with the scheduling
        # loop into the iteration scheduler (PR 6)
        sched_mod.IterationScheduler.iterate,
        health_server.probe_chip_states,
        slice_client.SliceClient._join_once,
        slice_client.SliceClient.heartbeat_now,
        manager_mod.PluginManager._register,
        health_client.get_tpu_health,
    ):
        src = inspect.getsource(fn)
        assert guard in src, f"{fn.__qualname__} lost the inline guard"
        # and no unconditional fire() outside the guard
        for line in src.splitlines():
            if ".fire(" in line:
                assert "ACTIVE" in line, fn.__qualname__


# -- manager stop() joins its threads ----------------------------------------

def test_manager_stop_joins_threads(testdata, tmp_path):
    from tpu_k8s_device_plugin.manager import PluginManager
    from tpu_k8s_device_plugin.tpu.device_impl import TpuContainerImpl

    root = os.path.join(testdata, "v5e-8")
    impl = TpuContainerImpl(
        sysfs_root=os.path.join(root, "sys"),
        dev_root=os.path.join(root, "dev"),
        tpu_env_path=os.path.join(root, "run", "tpu", "tpu-env"),
    )
    m = PluginManager(impl, pulse_seconds=1,
                      kubelet_dir=str(tmp_path / "dp"),
                      kubelet_watch_interval_s=0.1)
    os.makedirs(str(tmp_path / "dp"), exist_ok=True)
    m.run(block=False)
    spawned = list(m._threads)
    assert spawned, "manager should have spawned watch + pulse threads"
    m.stop()
    for t in spawned:
        assert not t.is_alive(), f"{t.name} leaked past stop()"
    assert m._threads == []


# -- probe watchdog: hung probe demotes within one call ----------------------

def test_hung_probe_demotes_devices_within_one_pulse(testdata):
    from tpu_k8s_device_plugin.tpu.device_impl import TpuContainerImpl
    from tpu_k8s_device_plugin.types import DevicePluginContext, constants

    release = threading.Event()
    hang = {"on": True}

    def probe():
        if hang["on"]:
            release.wait(10.0)
        return {}

    root = os.path.join(testdata, "v5e-8")
    impl = TpuContainerImpl(
        sysfs_root=os.path.join(root, "sys"),
        dev_root=os.path.join(root, "dev"),
        tpu_env_path=os.path.join(root, "run", "tpu", "tpu-env"),
        health_fn=probe,
        probe_watchdog_s=0.05,
    )
    ctx = DevicePluginContext("tpu")
    impl.start(ctx)
    impl.enumerate(ctx)
    t0 = time.monotonic()
    devs = impl.update_health(ctx)
    assert time.monotonic() - t0 < 5.0, "pulse stalled on a hung probe"
    assert devs and all(d.health == constants.UNHEALTHY for d in devs)
    healthy, reason = impl.local_health()
    assert not healthy and "hung" in reason
    # recovery: the probe answers again -> devices re-promote
    hang["on"] = False
    release.set()
    devs = impl.update_health(ctx)
    assert all(d.health == constants.HEALTHY for d in devs)


# -- serving scheduler crash containment -------------------------------------

CFG = dict(vocab=128, d_model=64, n_heads=4, n_layers=2, d_ff=128)


@pytest.fixture(scope="module")
def engine_setup():
    import jax
    import jax.numpy as jnp

    from tpu_k8s_device_plugin.workloads.inference import make_decoder

    model = make_decoder(**CFG, max_len=64, dtype=jnp.float32)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    params = model.init(jax.random.PRNGKey(0), tokens, pos)["params"]
    return model, params


def _post(port, payload, timeout=120):
    import http.client
    import json

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", "/generate", json.dumps(payload),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _get(port, path):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def test_scheduler_crash_503s_then_supervisor_restarts(engine_setup):
    from tpu_k8s_device_plugin.workloads.server import EngineServer
    from tpu_k8s_device_plugin.workloads.serving import ServingEngine

    model, params = engine_setup
    eng = ServingEngine(model, params, n_slots=2)
    srv = EngineServer(eng, max_new_tokens=8, window=4)
    srv.start(host="127.0.0.1", port=0)
    try:
        status, _ = _post(srv.port, {"tokens": [1, 2, 3],
                                     "max_new_tokens": 4,
                                     "stream": False})
        assert status == 200
        faults.install("serve.step:error:1", seed=0,
                       recorder=srv.recorder)
        try:
            status, body = _post(srv.port, {"tokens": [4, 5, 6],
                                            "max_new_tokens": 4,
                                            "stream": False})
            assert status == 503, body
        finally:
            faults.uninstall()
        assert srv.recorder.events(name="tpu_serve_scheduler_crash")
        deadline = time.time() + 10.0
        while (time.time() < deadline
               and srv._m_sched_restarts.value < 1):
            time.sleep(0.02)
        assert srv._m_sched_restarts.value >= 1
        assert srv.healthy()
        status, _ = _get(srv.port, "/healthz")
        assert status == 200
        status, body = _post(srv.port, {"tokens": [7, 8, 9],
                                        "max_new_tokens": 4,
                                        "stream": False})
        assert status == 200, body
        # the crash is on /metrics too
        body = srv.render_metrics()
        assert "tpu_serve_scheduler_crashes_total 1" in body
        assert promlint.lint(body) == []
    finally:
        faults.uninstall()
        srv.stop()


def test_scheduler_permanent_death_fails_healthz(engine_setup):
    """Past the restart budget the server stops pretending: /healthz
    503s and new requests answer an immediate 503."""
    from tpu_k8s_device_plugin.workloads import server as serve_mod
    from tpu_k8s_device_plugin.workloads.server import EngineServer
    from tpu_k8s_device_plugin.workloads.serving import ServingEngine

    model, params = engine_setup
    eng = ServingEngine(model, params, n_slots=2)
    srv = EngineServer(eng, max_new_tokens=8, window=4)
    old = serve_mod._SCHED_MAX_RESTARTS
    serve_mod._SCHED_MAX_RESTARTS = 2
    srv.start(host="127.0.0.1", port=0)
    try:
        faults.install("serve.step:error:1", seed=0)
        # each request crashes the loop once; the budget is 2
        for _ in range(3):
            status, _ = _post(srv.port, {"tokens": [1, 2],
                                         "max_new_tokens": 4,
                                         "stream": False})
            assert status == 503
            if srv._sched_dead:
                break
        deadline = time.time() + 10.0
        while time.time() < deadline and not srv._sched_dead:
            status, _ = _post(srv.port, {"tokens": [1, 2],
                                         "max_new_tokens": 2,
                                         "stream": False})
            time.sleep(0.05)
        assert srv._sched_dead
        assert not srv.healthy()
        status, body = _get(srv.port, "/healthz")
        assert status == 503
        status, body = _post(srv.port, {"tokens": [3],
                                        "max_new_tokens": 2,
                                        "stream": False})
        assert status == 503
        assert srv.recorder.events(name="tpu_serve_scheduler_dead")
    finally:
        faults.uninstall()
        serve_mod._SCHED_MAX_RESTARTS = old
        srv.stop()
