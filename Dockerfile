# Multi-target image build (≈ the reference's Alpine multi-stage
# Dockerfile + labeller.Dockerfile, collapsed so the builder stage exists
# once):
#
#   docker build -t k8s-tpu-device-plugin .                  # plugin (default)
#   docker build --target labeller -t k8s-tpu-node-labeller .
#
# GIT_DESCRIBE stamps the version the CLI banner prints, mirroring the
# reference's -ldflags -X main.gitDescribe.
FROM python:3.11-slim AS builder
ARG GIT_DESCRIBE=unknown
RUN apt-get update && apt-get install -y --no-install-recommends g++ make \
    && rm -rf /var/lib/apt/lists/*
WORKDIR /src
COPY pyproject.toml README.md ./
COPY tpu_k8s_device_plugin/ tpu_k8s_device_plugin/
COPY native/ native/
RUN make -C native/tpuprobe \
    && pip install --no-cache-dir --prefix=/install . \
    && cp tpu_k8s_device_plugin/hostinfo/libtpuprobe.so \
         /install/lib/python3.11/site-packages/tpu_k8s_device_plugin/hostinfo/ \
    && echo "${GIT_DESCRIBE}" > /install/git-describe

FROM python:3.11-slim AS labeller
COPY --from=builder /install /usr/local
ENTRYPOINT ["k8s-tpu-node-labeller"]

# plugin image last so it is the default target
FROM python:3.11-slim AS dp
COPY --from=builder /install /usr/local
ENTRYPOINT ["k8s-tpu-device-plugin"]
CMD ["--pulse=0"]
