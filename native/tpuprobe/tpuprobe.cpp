// tpuprobe: native host-interface shim for the TPU device plugin.
//
// The reference's native surface is two cgo->C boundaries: libdrm ioctls
// for device probing (/root/reference/internal/pkg/amdgpu/amdgpu.go:21-27,
// 646-736) and hwloc for NUMA lookup
// (/root/reference/internal/pkg/hwloc/hwloc.go:21-97), plus fsnotify for
// the kubelet-socket watch in the vendored dpm
// (vendor/.../dpm/manager.go:52-55).  This shim provides the TPU-native
// equivalents behind a flat C ABI consumed from Python via ctypes:
//
//   - inotify directory watcher (kubelet socket create/remove detection
//     without polling)
//   - device-node probe (open/stat the chardev as the kernel sees it --
//     an access(2) check can lie under capability-based permissions)
//   - NUMA node lookup for a PCI function (sysfs read, the hwloc subset
//     the plugin actually needs)
//
// Built as libtpuprobe.so with no dependencies beyond libc/libstdc++.

#include <cerrno>
#include <cstdio>
#include <cstring>

#include <fcntl.h>
#include <poll.h>
#include <sys/inotify.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

#define TP_API extern "C" __attribute__((visibility("default")))

static const char kVersion[] = "tpuprobe 1.1.0";

TP_API const char* tp_version(void) { return kVersion; }

// ---------------------------------------------------------------------------
// inotify directory watcher
// ---------------------------------------------------------------------------

struct tp_watch {
  int ifd;
  int wd;
};

// Returns a watcher handle for create/delete/move events in `dir`, or
// nullptr (errno left set) when inotify is unavailable.
TP_API tp_watch* tp_watch_create(const char* dir) {
  int ifd = inotify_init1(IN_NONBLOCK | IN_CLOEXEC);
  if (ifd < 0) return nullptr;
  int wd = inotify_add_watch(
      ifd, dir, IN_CREATE | IN_DELETE | IN_MOVED_TO | IN_MOVED_FROM);
  if (wd < 0) {
    int saved = errno;
    close(ifd);
    errno = saved;
    return nullptr;
  }
  tp_watch* w = new tp_watch{ifd, wd};
  return w;
}

// Blocks up to timeout_ms for a filesystem event in the watched dir.
// Returns 1 if at least one event arrived, 0 on timeout, -errno on error.
// A deleted-and-recreated watch directory delivers IN_IGNORED /
// IN_DELETE_SELF and then goes silent forever; surface that as -ESTALE so
// the caller re-creates the watch (or falls back to polling) instead of
// believing it still has an event-driven watch.
TP_API int tp_watch_wait(tp_watch* w, int timeout_ms) {
  if (!w) return -EINVAL;
  struct pollfd pfd = {w->ifd, POLLIN, 0};
  int rc = poll(&pfd, 1, timeout_ms);
  if (rc < 0) return -errno;
  if (rc == 0) return 0;
  // drain the queue, scanning for watch-death events; the caller re-stats
  // the socket regardless, so individual event payloads are not returned
  char buf[4096] __attribute__((aligned(8)));
  bool stale = false;
  ssize_t n;
  while ((n = read(w->ifd, buf, sizeof buf)) > 0) {
    for (ssize_t off = 0; off + (ssize_t)sizeof(inotify_event) <= n;) {
      const inotify_event* ev =
          reinterpret_cast<const inotify_event*>(buf + off);
      if (ev->mask & (IN_IGNORED | IN_DELETE_SELF | IN_MOVE_SELF | IN_UNMOUNT))
        stale = true;
      off += sizeof(inotify_event) + ev->len;
    }
  }
  return stale ? -ESTALE : 1;
}

TP_API void tp_watch_destroy(tp_watch* w) {
  if (!w) return;
  inotify_rm_watch(w->ifd, w->wd);
  close(w->ifd);
  delete w;
}

// ---------------------------------------------------------------------------
// device-node probe
// ---------------------------------------------------------------------------

// Probes that a TPU device node exists as a character device.  Returns 0
// when present, -errno on stat failure, -ENOTSUP when the path exists but
// is not a chardev (reserved so callers can tell fixture trees — regular
// files — apart from real errors).
//
// Deliberately stat-only, no open(2): the TPU accel driver enforces a
// single-open policy, so an open-based probe (a) reports -EBUSY for every
// chip a workload is actively using — health flapping on each pulse — and
// (b) can itself win the race against a launching workload's open and make
// the *workload* fail with EBUSY.  Granular wedged-chip state comes from
// the driver's sysfs attributes (chip_state / uncorrectable_errors, read
// by health/server.py) instead, which sees strictly more than an open
// probe could (SURVEY.md section 7, "health without privileged /dev/kfd").
TP_API int tp_probe_device(const char* path) {
  struct stat st;
  if (stat(path, &st) != 0) return -errno;
  if (!S_ISCHR(st.st_mode)) return -ENOTSUP;
  return 0;
}

// ---------------------------------------------------------------------------
// NUMA lookup (the hwloc subset the plugin needs)
// ---------------------------------------------------------------------------

// NUMA node of a PCI function from its sysfs directory.  Returns the node
// id (>= 0), 0 when the kernel reports -1 (unknown), or -errno.
TP_API int tp_numa_node(const char* pci_sysfs_dir) {
  char path[4096];
  int n = snprintf(path, sizeof path, "%s/numa_node", pci_sysfs_dir);
  if (n < 0 || static_cast<size_t>(n) >= sizeof path) return -ENAMETOOLONG;
  FILE* f = fopen(path, "re");
  if (!f) return -errno;
  int node = -1;
  int rc = fscanf(f, "%d", &node);
  fclose(f);
  if (rc != 1) return -EINVAL;
  return node < 0 ? 0 : node;
}
