# Tiered test entry points (VERDICT r4 #6): plugin-side work should
# not pay the workload tier's JAX compile tax on every local run.
#
#   make test-plugin     fast tier — discovery/allocator/plugin/manager/
#                        labeller/health/proto/observability/C++ probe
#   make test-workloads  compile-heavy tier — models, kernels, serving
#   make test            everything (what CI runs, there with -n auto)

PYTEST ?= python -m pytest
PYTEST_ARGS ?= -q

PLUGIN_TESTS := \
    tests/test_allocator.py \
    tests/test_cmd_device_plugin.py \
    tests/test_device_impl.py \
    tests/test_discovery.py \
    tests/test_hardware.py \
    tests/test_health.py \
    tests/test_labeller.py \
    tests/test_metrics.py \
    tests/test_observability.py \
    tests/test_plugin_manager.py \
    tests/test_proto.py \
    tests/test_tpuprobe.py

WORKLOAD_TESTS := $(filter-out $(PLUGIN_TESTS), $(wildcard tests/test_*.py))

.PHONY: test test-plugin test-workloads

test:
	$(PYTEST) tests/ -x $(PYTEST_ARGS)

test-plugin:
	$(PYTEST) $(PLUGIN_TESTS) -x $(PYTEST_ARGS)

test-workloads:
	$(PYTEST) $(WORKLOAD_TESTS) -x $(PYTEST_ARGS)
