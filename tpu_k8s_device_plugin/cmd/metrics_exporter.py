"""tpu-metrics-exporter entrypoint: the standalone health probe daemon.

The AMD analog is a separate project the reference only consumes
(docs/user-guide/installation.md); this build ships it so the health DS
variant works out of the box.
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys

from tpu_k8s_device_plugin import __version__
from tpu_k8s_device_plugin.health import MetricsHTTPServer, TpuHealthServer
from tpu_k8s_device_plugin.types import constants


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tpu-metrics-exporter")
    p.add_argument(
        "--socket", default=constants.METRICS_EXPORTER_SOCKET,
        help="unix socket to serve the TpuHealthService on",
    )
    p.add_argument(
        "--metrics-port", type=int, default=constants.METRICS_HTTP_PORT,
        help="TCP port for the Prometheus /metrics endpoint (0 disables)",
    )
    p.add_argument("--sysfs-root", default="/sys", help=argparse.SUPPRESS)
    p.add_argument("--dev-root", default="/dev", help=argparse.SUPPRESS)
    p.add_argument("--version", action="version", version=__version__)
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    # chaos runs arm the exporter's probe hook via TPU_DP_FAULTS (the
    # daemon has no flag surface worth growing for this); unset env
    # leaves the hook a no-op attribute check
    from tpu_k8s_device_plugin.resilience import faults
    faults.install_from_env()
    # pod shutdown sends SIGTERM; exit through the finally so the socket is
    # removed rather than left stale for the next incarnation (skipped when
    # main() is driven from a worker thread, where signal.signal raises)
    import threading
    if threading.current_thread() is threading.main_thread():
        signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))
    server = TpuHealthServer(
        socket_path=args.socket,
        sysfs_root=args.sysfs_root,
        dev_root=args.dev_root,
    ).start()
    metrics = None
    try:
        # inside the try: a bind failure (port taken by a restart race)
        # must tear the gRPC server down and exit non-zero so the pod
        # restarts, not leave a live process with no /metrics listener
        if args.metrics_port:
            metrics = MetricsHTTPServer(
                port=args.metrics_port,
                sysfs_root=args.sysfs_root,
                dev_root=args.dev_root,
            ).start()
        server.wait()
    except KeyboardInterrupt:
        pass
    except OSError as e:
        logging.error("metrics listener failed: %s", e)
        return 1
    finally:
        if metrics is not None:
            metrics.stop()
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
