"""k8s-tpu-node-labeller entrypoint.

≈ /root/reference/cmd/k8s-node-labeller/main.go:507-590: driver-type flag,
one boolean flag per label (all default on here — the reference defaults
off, which in practice means every deployment enables them by hand), node
name from the downward API, then the reconcile controller.
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys

from tpu_k8s_device_plugin import __version__
from tpu_k8s_device_plugin.labeller import (
    LabelContext,
    NodeClient,
    NodeLabelController,
    generate_labels,
)
from tpu_k8s_device_plugin.types import constants

log = logging.getLogger("k8s-tpu-node-labeller")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="k8s-tpu-node-labeller",
        description="Publishes TPU properties as Kubernetes node labels",
    )
    p.add_argument(
        "--driver_type", "--driver-type", dest="driver_type",
        choices=[constants.CONTAINER, constants.VF_PASSTHROUGH,
                 constants.PF_PASSTHROUGH],
        default=constants.CONTAINER,
    )
    for label in constants.SUPPORTED_LABELS:
        p.add_argument(
            f"--{label}",
            dest=f"label_{label.replace('-', '_')}",
            action=argparse.BooleanOptionalAction,
            default=True,
            help=f"emit the {constants.LABEL_PREFIX}.{label} label",
        )
    p.add_argument(
        "--node-name", default=None,
        help="node to label (default: $DS_NODE_NAME from the downward API)",
    )
    p.add_argument(
        "--interval", type=float, default=60.0,
        help="reconcile/watch interval seconds (default 60)",
    )
    p.add_argument(
        "--kube-api", default=None,
        help="API server base URL override (default: in-cluster config)",
    )
    p.add_argument("--sysfs-root", default="/sys", help=argparse.SUPPRESS)
    p.add_argument("--dev-root", default="/dev", help=argparse.SUPPRESS)
    p.add_argument(
        "--tpu-env", default=constants.TPU_ENV_FILE, help=argparse.SUPPRESS
    )
    p.add_argument(
        "--slice-state-file", default=constants.SLICE_STATE_FILE,
        help=argparse.SUPPRESS,
    )
    p.add_argument("--oneshot", action="store_true",
                   help="reconcile once and exit (for jobs/tests)")
    p.add_argument("--version", action="version", version=__version__)
    return p


def main(argv=None) -> int:
    import os

    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s",
    )
    log.info("k8s-tpu-node-labeller %s starting", __version__)

    node_name = args.node_name or os.environ.get("DS_NODE_NAME")
    if not node_name:
        log.error("no node name: set --node-name or DS_NODE_NAME")
        return 2

    enabled = [
        label for label in constants.SUPPORTED_LABELS
        if getattr(args, f"label_{label.replace('-', '_')}")
    ]
    log.info("node=%s labels=%s", node_name, enabled)

    def compute():
        ctx = LabelContext.collect(
            driver_type=args.driver_type,
            sysfs_root=args.sysfs_root,
            dev_root=args.dev_root,
            tpu_env_path=args.tpu_env,
            slice_state_path=args.slice_state_file,
        )
        return generate_labels(ctx, enabled)

    controller = NodeLabelController(
        NodeClient(base_url=args.kube_api),
        node_name,
        compute,
        interval_s=args.interval,
    )
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))
    try:
        if args.oneshot:
            delta = controller.reconcile()
            log.info("oneshot delta: %s", delta)
        else:
            controller.run()
    except KeyboardInterrupt:
        pass
    finally:
        controller.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
