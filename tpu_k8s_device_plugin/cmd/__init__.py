"""CLI entrypoints (≈ reference cmd/)."""
