"""k8s-tpu-device-plugin entrypoint.

TPU-native analog of /root/reference/cmd/k8s-device-plugin/main.go:34-120:
flag parsing/validation, device-impl selection (explicit driver type or the
container→vf→pf fallback chain), then the plugin manager lifecycle.
"""

from __future__ import annotations

import argparse
import functools
import logging
import os
import signal
import socket
import sys

from tpu_k8s_device_plugin import __version__
from tpu_k8s_device_plugin.health import get_tpu_health
from tpu_k8s_device_plugin.manager import PluginManager
from tpu_k8s_device_plugin.tpu.device_impl import TpuContainerImpl
from tpu_k8s_device_plugin.tpu.device_impl_vfio import TpuPfImpl, TpuVfImpl
from tpu_k8s_device_plugin.types import constants

log = logging.getLogger("k8s-tpu-device-plugin")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="k8s-tpu-device-plugin",
        description="Kubernetes device plugin for Google Cloud TPUs",
    )
    p.add_argument(
        "--pulse", type=int, default=0, metavar="SECONDS",
        help="time between health check polling; 0 disables (default 0)",
    )
    p.add_argument(
        "--driver_type", "--driver-type", dest="driver_type",
        choices=[constants.CONTAINER, constants.VF_PASSTHROUGH,
                 constants.PF_PASSTHROUGH],
        default=None,
        help="device driver mode; omit to autodetect "
             "(container, then vf-passthrough, then pf-passthrough)",
    )
    p.add_argument(
        "--resource_naming_strategy", "--resource-naming-strategy",
        dest="naming_strategy",
        choices=[constants.RESOURCE_NAMING_STRATEGY_SINGLE,
                 constants.RESOURCE_NAMING_STRATEGY_MIXED],
        default=constants.RESOURCE_NAMING_STRATEGY_SINGLE,
        help="single: everything under google.com/tpu; "
             "mixed: partition-typed resource names",
    )
    p.add_argument(
        "--kubelet-dir", default=constants.DEVICE_PLUGIN_PATH,
        help="kubelet device-plugin directory",
    )
    p.add_argument("--sysfs-root", default="/sys", help=argparse.SUPPRESS)
    p.add_argument("--dev-root", default="/dev", help=argparse.SUPPRESS)
    p.add_argument(
        "--tpu-env", default=constants.TPU_ENV_FILE, help=argparse.SUPPRESS
    )
    p.add_argument(
        "--exporter-socket", default=constants.METRICS_EXPORTER_SOCKET,
        help="tpu-metrics-exporter unix socket for granular health",
    )
    p.add_argument(
        "--slice-rendezvous", "--slice_rendezvous", dest="slice_rendezvous",
        default=os.environ.get(constants.ENV_SLICE_RENDEZVOUS, ""),
        metavar="HOST:PORT",
        help="multi-host slice rendezvous address; every member of the "
             "slice passes the same value, and the plugin whose hostname "
             "matches HOST also serves the coordinator.  Empty (the "
             "default) disables slice coordination entirely — single-host "
             "behavior is unchanged.  Env override: "
             f"{constants.ENV_SLICE_RENDEZVOUS}",
    )
    p.add_argument(
        "--slice-workers", "--slice_workers", dest="slice_workers",
        type=int, metavar="N",
        default=os.environ.get(constants.ENV_SLICE_WORKERS, "0"),
        help="hosts in the slice (e.g. 2 for v5e-16); required with "
             "--slice-rendezvous.  Env override: "
             f"{constants.ENV_SLICE_WORKERS}",
    )
    p.add_argument(
        "--slice-reshape-grace", "--slice_reshape_grace",
        dest="slice_reshape_grace", type=float, metavar="SECONDS",
        default=float(
            os.environ.get(constants.ENV_SLICE_RESHAPE_GRACE, "0") or 0),
        help="degraded-mode reshape grace window in seconds.  0 (the "
             "default) keeps demote-all semantics: an unhealthy member "
             "demotes every member's devices until it recovers.  > 0: "
             "members still unhealthy/absent when the window expires are "
             "evicted and the survivors re-form into a smaller slice "
             "under the next generation (workloads checkpoint-restart "
             "under the new identity).  Only meaningful on the "
             "rendezvous host; pass it to every member anyway (identical "
             "flags).  Env override: "
             f"{constants.ENV_SLICE_RESHAPE_GRACE}",
    )
    p.add_argument(
        "--slice-state-file", default=constants.SLICE_STATE_FILE,
        help=argparse.SUPPRESS,
    )
    p.add_argument(
        "--debug-port", type=int, default=0, metavar="PORT",
        help="serve /healthz, /debug/status, /debug/threads, /metrics "
             "on --debug-host at PORT; 0 disables (default)",
    )
    p.add_argument(
        "--flight-record-dir", dest="flight_record_dir",
        default=os.environ.get(constants.ENV_FLIGHT_RECORD_DIR, ""),
        metavar="DIR",
        help="dump the flight-recorder event journal (Allocate spans, "
             "device demotions/recoveries, slice transitions) as JSON "
             "lines to DIR on exit/SIGTERM — mount a hostPath here in "
             "the DaemonSet so post-mortems survive the pod.  Empty "
             "disables the dump (the in-memory ring and /debug/traces "
             f"stay on).  Env override: {constants.ENV_FLIGHT_RECORD_DIR}",
    )
    p.add_argument(
        "--incident-dir", dest="incident_dir",
        default=os.environ.get(constants.ENV_INCIDENT_DIR, ""),
        metavar="DIR",
        help="write alert-triggered incident bundles (alert history, "
             "event journal, TSDB snapshot, continuous-profile slice) "
             "under DIR when a page-severity alert starts firing on "
             "the debug surface — mount a hostPath next to the "
             "flight-record dir.  Empty disables (default).  Requires "
             f"--debug-port.  Env override: {constants.ENV_INCIDENT_DIR}",
    )
    p.add_argument(
        "--fault-spec", dest="fault_spec",
        default=os.environ.get("TPU_DP_FAULTS", ""), metavar="SPEC",
        help="arm deterministic fault injection (chaos testing ONLY): "
             "op:kind:arg[;...] — e.g. 'kubelet.register:drop:0.5;"
             "probe:hang:5'.  Empty (the default) leaves every hook a "
             "no-op attribute check.  Env override: TPU_DP_FAULTS",
    )
    p.add_argument(
        "--fault-seed", dest="fault_seed", type=int,
        default=int(os.environ.get("TPU_DP_FAULT_SEED", "0") or 0),
        metavar="N",
        help="RNG seed for --fault-spec probabilities: the same seed "
             "replays the same injection sequence.  Env override: "
             "TPU_DP_FAULT_SEED (default 0)",
    )
    p.add_argument(
        "--debug-host", default="127.0.0.1", metavar="ADDR",
        help="bind address for --debug-port (default loopback; set "
             "0.0.0.0 so Prometheus can scrape /metrics from the pod "
             "IP — the debug surface has no auth, so only widen it on "
             "a trusted pod network)",
    )
    p.add_argument("-v", "--verbose", action="count", default=0)
    p.add_argument("--version", action="version", version=__version__)
    return p


def select_device_impl(args):
    """Explicit driver type, or the fallback chain
    (≈ main.go:85-115: container → vf → pf)."""
    health_fn = functools.partial(get_tpu_health, args.exporter_socket)
    builders = {
        constants.CONTAINER: lambda: TpuContainerImpl(
            resource_naming_strategy=args.naming_strategy,
            sysfs_root=args.sysfs_root,
            dev_root=args.dev_root,
            tpu_env_path=args.tpu_env,
            health_fn=health_fn,
        ),
        constants.VF_PASSTHROUGH: lambda: TpuVfImpl(
            resource_naming_strategy=args.naming_strategy,
            sysfs_root=args.sysfs_root,
            dev_root=args.dev_root,
            health_fn=health_fn,
        ),
        constants.PF_PASSTHROUGH: lambda: TpuPfImpl(
            resource_naming_strategy=args.naming_strategy,
            sysfs_root=args.sysfs_root,
            dev_root=args.dev_root,
            health_fn=health_fn,
        ),
    }
    if args.driver_type:
        return builders[args.driver_type](), args.driver_type
    last_err = None
    for driver_type in (constants.CONTAINER, constants.VF_PASSTHROUGH,
                        constants.PF_PASSTHROUGH):
        try:
            impl = builders[driver_type]()
            log.info("autodetected driver type: %s", driver_type)
            return impl, driver_type
        except Exception as e:
            log.info("driver type %s not usable: %s", driver_type, e)
            last_err = e
    raise SystemExit(f"no usable TPU driver mode found: {last_err}")


def _metadata_coords(topo):
    """This host's ICI coordinate for rendezvous rank sorting, but only
    when the tpu-env metadata actually stated one — a derived/default
    worker id must not masquerade as physical wiring."""
    if topo is None:
        return ()
    stated = ("WORKER_ID", constants.ENV_TPU_WORKER_ID, "AGENT_WORKER_NUMBER")
    if any(k in topo.raw_env for k in stated):
        return (topo.worker_id,)
    return ()


def setup_slice(args, impl, driver_type, registry=None, recorder=None):
    """Wire slice coordination when --slice-rendezvous is set: serve the
    coordinator if this is the named host, attach a client to the impl,
    start its background join+heartbeat loop.  *registry* (the node's
    obs.Registry) turns the slice metrics set on — the plugin debug
    /metrics scrape then carries join/heartbeat/membership series.
    *recorder* (the node's FlightRecorder) journals membership
    transitions and, on the rendezvous host, every member's
    join/heartbeat with its trace-id.
    Returns (coordinator|None, client|None)."""
    from tpu_k8s_device_plugin.slice import SliceClient, SliceCoordinator

    address = args.slice_rendezvous
    host, _, port_s = address.rpartition(":")
    if not host or not port_s.isdigit():
        raise SystemExit(
            f"--slice-rendezvous must be HOST:PORT, got {address!r}"
        )
    if args.slice_workers < 2:
        raise SystemExit(
            "--slice-workers must be >= 2 with --slice-rendezvous "
            f"(got {args.slice_workers})"
        )
    if driver_type != constants.CONTAINER:
        raise SystemExit(
            "slice coordination requires the container driver type "
            f"(got {driver_type}): passthrough VMs run their own runtime"
        )
    hostname = socket.gethostname()
    coordinator = None
    # EXACT hostname match only: every member runs identical flags, and
    # exactly one of them may serve the rendezvous.  A loopback-alias
    # match would make every host self-elect its own empty coordinator
    # and the slice would never form.
    if host == hostname:
        coordinator = SliceCoordinator(
            expected_workers=args.slice_workers,
            bind_address=f"[::]:{port_s}",
            state_path=args.slice_state_file,
            registry=registry,
            recorder=recorder,
            reshape_grace_s=args.slice_reshape_grace,
        ).start()
        log.info("this host (%s) serves the slice rendezvous", hostname)
    client = SliceClient(
        rendezvous_address=address,
        hostname=hostname,
        coords=_metadata_coords(impl.topology),
        chip_count=len(impl.chips),
        state_path=args.slice_state_file,
        local_health_fn=impl.local_health,
        registry=registry,
        recorder=recorder,
    )
    impl.set_slice_client(client)
    client.start()
    return coordinator, client


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s",
    )
    log.info("k8s-tpu-device-plugin %s starting", __version__)
    # native shim banner (≈ the hwloc version banner, main.go:40)
    try:
        from tpu_k8s_device_plugin.hostinfo import tpuprobe
        log.info("native shim: %s", tpuprobe.version())
    except Exception as e:
        log.warning("native shim unavailable (%s); using portable paths", e)
    if args.pulse < 0:
        log.error("invalid pulse %d; must be >= 0", args.pulse)
        return 2

    if args.slice_workers and not args.slice_rendezvous:
        log.error("--slice-workers without --slice-rendezvous has no effect")
        return 2
    if args.slice_reshape_grace and not args.slice_rendezvous:
        log.error("--slice-reshape-grace without --slice-rendezvous "
                  "has no effect")
        return 2
    if args.slice_reshape_grace < 0:
        log.error("invalid --slice-reshape-grace %.1f; must be >= 0",
                  args.slice_reshape_grace)
        return 2

    impl, driver_type = select_device_impl(args)
    resources = impl.get_resource_names()
    log.info("driver=%s resources=%s", driver_type,
             [f"{constants.RESOURCE_NAMESPACE}/{r}" for r in resources])

    # the node's ONE metrics registry + flight recorder: plugin
    # histograms, slice metrics, the debug /metrics surface, and the
    # event journal behind /debug/traces all hang off this pair
    from tpu_k8s_device_plugin import obs, resilience
    registry = obs.Registry()
    recorder = obs.FlightRecorder(registry=registry)
    # resilience wiring (PR 5): swallowed-fault accounting renders on
    # this node's /metrics, and --fault-spec arms the injection hooks
    # (they stay bare attribute checks when unset)
    resilience.set_suppressed_metrics(
        resilience.ResilienceMetrics(registry))
    if args.fault_spec:
        resilience.install(args.fault_spec, seed=args.fault_seed,
                           recorder=recorder)

    coordinator = client = None
    if args.slice_rendezvous:
        coordinator, client = setup_slice(args, impl, driver_type,
                                          registry=registry,
                                          recorder=recorder)

    manager = PluginManager(
        impl,
        pulse_seconds=args.pulse,
        kubelet_dir=args.kubelet_dir,
        slice_client=client,
        registry=registry,
        recorder=recorder,
    )
    debug_server = None
    if args.debug_port:
        from tpu_k8s_device_plugin.observability import DebugServer
        debug_server = DebugServer(
            manager, args.debug_port, host=args.debug_host,
            incident_dir=args.incident_dir or None).start()
    # k8s sends SIGTERM on pod shutdown; route it through the same cleanup
    # path as Ctrl-C so streams get the stop signal and the endpoint socket
    # is unlinked (≈ main.go signal handling)
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))
    if args.flight_record_dir:
        # AFTER the sys.exit handler: the recorder's chaining SIGTERM
        # handler dumps the journal first, then delegates to it (and
        # atexit covers every orderly exit path)
        recorder.install_dump_handlers(args.flight_record_dir)
    try:
        manager.run(block=True)
    finally:
        manager.stop()
        if client is not None:
            client.stop()
        if coordinator is not None:
            coordinator.stop()
        if debug_server is not None:
            debug_server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
