"""Opt-in debug/observability HTTP endpoint.

The SURVEY §5 plan item the reference never had (its only observability is
glog verbosity): a flag-gated localhost HTTP server exposing the pprof-style
introspection a Go binary would get for free —

  GET /healthz        liveness (200 "ok")
  GET /debug/status   JSON: served resources, per-device health, RPC
                      counters, topology summary
  GET /debug/threads  all-thread stack dump (the goroutine-dump analog)
  GET /debug/traces   flight-recorder timelines (?trace_id=… for one
                      trace, index of recent traces without it)
  GET /debug/events   the raw event journal (?since=<unix seconds>)
  GET /metrics        the same counters in Prometheus exposition format
                      (per-resource RPC counters, device health rollups,
                      degraded-allocation count); the OpenMetrics Accept
                      type adds trace-id exemplars

Disabled unless --debug-port is set; binds loopback only (it exposes
internal state and has no auth — same posture as Go's default pprof
guidance).
"""

from __future__ import annotations

import json
import logging
import sys
import threading
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, TYPE_CHECKING
from urllib.parse import parse_qs, urlparse

from tpu_k8s_device_plugin import __version__, obs
from tpu_k8s_device_plugin.resilience import suppressed

if TYPE_CHECKING:
    from tpu_k8s_device_plugin.manager import PluginManager

log = logging.getLogger(__name__)


def thread_dump() -> str:
    """Stack traces of every live thread (≈ a Go goroutine dump)."""
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in frames.items():
        out.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
        out.extend(line.rstrip() for line in traceback.format_stack(frame))
    return "\n".join(out) + "\n"


def manager_status(manager: "PluginManager") -> dict:
    """Snapshot of what the manager is serving, for /debug/status.  All
    plugin/lock discipline lives behind PluginManager.status_snapshot()."""
    status: dict = {
        "version": __version__,
        "pulse_seconds": manager.pulse,
        "kubelet_dir": manager.kubelet_dir,
        "resources": manager.status_snapshot(),
    }
    # impl-level counters are node-wide, not per-resource (e.g. how many
    # Allocates degraded to linear bounds under fragmentation)
    impl_counters = getattr(manager.impl, "counters", None)
    if callable(impl_counters):
        status["impl_counters"] = impl_counters()
    topo = getattr(manager.impl, "topology", None)
    if topo is not None:
        status["topology"] = {
            "accelerator_type": topo.accelerator_type,
            "global_mesh": topo.topology_str,
            "worker_id": topo.worker_id,
            "num_workers": topo.num_workers,
        }
    client = getattr(manager, "slice_client", None)
    if client is not None:
        m = client.membership
        overlay = client.health_overlay()
        status["slice"] = {
            "formed": m is not None,
            "slice_id": m.slice_id if m else "",
            "rank": client.rank,
            "hostnames": list(m.hostnames) if m else [],
            "coordinator_address": m.coordinator_address if m else "",
            # reshape state: which generation this host serves, whether
            # it runs below the configured size, and the lineage of
            # slice ids it was re-formed from
            "generation": m.generation if m else 0,
            "degraded": m.degraded if m else False,
            "reshaped_from": list(m.reshaped_from) if m else [],
            # null until the first heartbeat verdict arrives
            "healthy": None if overlay is None else overlay[0],
            "unhealthy_hostnames": [] if overlay is None else overlay[1],
        }
    return status


def update_plugin_metrics(manager: "PluginManager",
                          registry: "obs.Registry") -> None:
    """Refresh the snapshot-style plugin families (kubelet RPC
    counters, device health rollups, impl counters) from the manager's
    status.  The persistent instruments — Allocate latency, frame
    build, pulse round, slice metrics — live on the same registry and
    need no refreshing; this only bridges the state that predates it.

    Renames (PR 3, promlint): impl counters gain the ``_total`` suffix
    the exposition format requires of counters —
    ``tpu_plugin_degraded_bounds_allocations`` is now
    ``tpu_plugin_degraded_bounds_allocations_total``."""
    status = manager_status(manager)
    rpc = registry.counter(
        "tpu_plugin_rpc_total", "Kubelet device-plugin RPCs served.",
        ("resource", "rpc"))
    healthy = registry.gauge(
        "tpu_plugin_devices_healthy", "Devices advertised Healthy.",
        ("resource",))
    unhealthy = registry.gauge(
        "tpu_plugin_devices_unhealthy", "Devices advertised Unhealthy.",
        ("resource",))
    for fam in (rpc, healthy, unhealthy):
        fam.clear()  # a dropped resource must not leave stale series
    for resource, st in sorted(status["resources"].items()):
        if "error" in st:
            continue
        for rpc_name, n in sorted(st.get("rpc_counts", {}).items()):
            rpc.labels(resource=resource, rpc=rpc_name)._set(n)
        healthy.labels(resource=resource).set(st.get("healthy", 0))
        unhealthy.labels(resource=resource).set(st.get("unhealthy", 0))
    for name, value in status.get("impl_counters", {}).items():
        cname = f"tpu_plugin_{name}"
        if not cname.endswith("_total"):
            cname += "_total"
        registry.counter(
            cname, f"Device-impl counter {name} (node-wide).")._set(value)


def render_plugin_metrics(manager: "PluginManager",
                          openmetrics: bool = False) -> str:
    """The plugin debug /metrics body: the manager's obs.Registry
    (Allocate/frame/pulse histograms, slice metrics) plus the bridged
    status snapshot, through the one shared renderer.  *openmetrics*
    adds trace-id exemplars + ``# EOF`` (serve only under the
    OpenMetrics content type)."""
    registry = getattr(manager, "registry", None)
    if registry is None:  # bare managers in tests / external embedders
        registry = obs.Registry()
    update_plugin_metrics(manager, registry)
    return registry.render(openmetrics=openmetrics)


class DebugServer:
    """Loopback HTTP server for the debug surface."""

    def __init__(self, manager: "PluginManager", port: int,
                 host: str = "127.0.0.1",
                 alert_rules: Optional[list] = None,
                 tick_interval_s: float = 15.0,
                 incident_dir: Optional[str] = None,
                 profiler_hz: float = 19.0):
        self._manager = manager
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._host = host
        self._port = port
        self._tick_interval_s = tick_interval_s
        # the manager's registry when it has one (shared with the
        # Allocate/pulse instruments), a private one otherwise — the
        # PR-18 retention layer needs a stable registry either way
        registry = getattr(manager, "registry", None)
        self.registry: obs.Registry = (
            registry if registry is not None else obs.Registry())
        # bridged snapshot families refresh at render time, so the
        # TSDB's sampling tick sees fresh RPC counts — same collect
        # hook discipline as the health exporter
        self.registry.on_collect(self._refresh)
        self.scrape_meta = obs.ScrapeMeta(self.registry)
        self.tsdb = obs.TSDB(self.registry)
        self.alerts = obs.AlertEvaluator(
            self.tsdb, list(alert_rules or ()),
            recorder=getattr(manager, "recorder", None))
        # continuous sampling profiler + alert-triggered incident
        # bundles (PR 19) — the plugin's flight data recorder
        self.profiler = obs.SamplingProfiler(
            self.registry, hz=profiler_hz)
        self._incidents: Optional[obs.IncidentManager] = None
        if incident_dir:
            self._incidents = obs.IncidentManager(
                incident_dir, self.alerts,
                registry=self.registry,
                recorder=getattr(manager, "recorder", None),
                tsdb=self.tsdb,
                profiler=self.profiler,
                metric_prefixes=("tpu_plugin_", "tpu_slice_"),
                collectors={
                    "statz.json": lambda: manager_status(self._manager),
                })

    def _refresh(self) -> None:
        try:
            update_plugin_metrics(self._manager, self.registry)
        except Exception as e:
            # a broken status snapshot degrades one render's
            # freshness, never the render (or the TSDB tick) itself
            suppressed("debug.metrics_refresh", e, logger=log,
                       metrics=getattr(self._manager, "resilience",
                                       None))

    @property
    def port(self) -> int:
        """Actual bound port (differs from the requested one for port 0)."""
        return self._httpd.server_address[1] if self._httpd else self._port

    def start(self) -> "DebugServer":
        manager = self._manager
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                url = urlparse(self.path)
                if url.path == "/healthz":
                    self._send(200, "text/plain", "ok\n")
                elif url.path == "/alerts":
                    self._send(200, "application/json",
                               outer.alerts.status_json() + "\n")
                elif url.path == "/debug/query":
                    params = {k: v[0] for k, v
                              in parse_qs(url.query).items()}
                    try:
                        body = outer.tsdb.handle_query_json(params)
                    except ValueError as e:
                        self._send(400, "application/json", json.dumps(
                            {"error": str(e)}) + "\n")
                        return
                    self._send(200, "application/json", body + "\n")
                elif url.path == "/debug/status":
                    try:
                        body = json.dumps(manager_status(manager), indent=2)
                        self._send(200, "application/json", body + "\n")
                    except Exception as e:
                        # full traceback to the LOG, generic body to the
                        # CLIENT: raw exception text can leak paths and
                        # internal state, and without the traceback the
                        # operator had nothing to debug with; the
                        # suppressed counter makes repeated failures
                        # visible on /metrics
                        log.exception("/debug/status failed")
                        suppressed("debug.status", e, logger=log,
                                   metrics=getattr(manager, "resilience",
                                                   None))
                        self._send(500, "text/plain",
                                   "internal error; see plugin logs\n")
                elif url.path == "/debug/threads":
                    self._send(200, "text/plain", thread_dump())
                elif url.path == "/debug/pprof":
                    try:
                        ctype, body = outer.profiler.handle_pprof(
                            parse_qs(url.query))
                    except ValueError as e:
                        self._send(400, "application/json", json.dumps(
                            {"error": str(e)}) + "\n")
                        return
                    self._send(200, ctype, body)
                elif url.path in ("/debug/traces", "/debug/events"):
                    recorder = getattr(manager, "recorder", None)
                    if recorder is None:
                        self._send(404, "application/json", json.dumps(
                            {"error": "no flight recorder on this "
                                      "manager"}) + "\n")
                        return
                    q = parse_qs(url.query)
                    if url.path == "/debug/traces":
                        tid = q.get("trace_id", [None])[0]
                        if tid:
                            body = {"trace_id": tid,
                                    "events": recorder.events(
                                        trace_id=tid)}
                        else:
                            body = {"traces": recorder.trace_ids()}
                    else:
                        try:
                            since = float(q.get("since", ["0"])[0])
                        except ValueError:
                            self._send(400, "application/json",
                                       json.dumps({
                                           "error": "'since' must be "
                                           "a unix timestamp"}) + "\n")
                            return
                        body = {"since": since,
                                "dropped": recorder.dropped,
                                "events": recorder.events(since=since)}
                    self._send(200, "application/json",
                               json.dumps(body, indent=2) + "\n")
                elif url.path == "/metrics":
                    om = obs.negotiate_openmetrics(
                        self.headers.get("Accept"))
                    try:
                        # bridged families refresh via the registry
                        # collect hook; ScrapeMeta accounts the
                        # exposition itself (tpu_scrape_*)
                        self._send(
                            200,
                            obs.OPENMETRICS_CONTENT_TYPE if om
                            else obs.TEXT_CONTENT_TYPE,
                            outer.scrape_meta.render(openmetrics=om),
                        )
                    except Exception as e:
                        log.exception("/metrics render failed")
                        suppressed("debug.metrics_render", e,
                                   logger=log,
                                   metrics=getattr(manager, "resilience",
                                                   None))
                        self._send(500, "text/plain",
                                   "internal error; see plugin logs\n")
                else:
                    self._send(404, "text/plain", "not found\n")

            def _send(self, code, ctype, body: str):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, fmt, *args):
                log.debug("debug-http: " + fmt, *args)

        self._httpd = ThreadingHTTPServer((self._host, self._port), Handler)
        t = threading.Thread(
            target=self._httpd.serve_forever, name="debug-http", daemon=True
        )
        t.start()
        self.tsdb.start(self._tick_interval_s)
        self.profiler.start()
        if self._incidents is not None:
            self._incidents.start()
        log.info("debug endpoint on http://%s:%d", self._host, self.port)
        return self

    def stop(self) -> None:
        self.tsdb.stop()
        self.profiler.stop()
        if self._incidents is not None:
            self._incidents.stop()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
