# tpulint: deterministic-path -- the engine equivalence suites replay this file's decisions from seeds; D1 bans bare random/time.time() here
"""Rendezvous state machine + crash-safe membership persistence.

Pure logic layer: no gRPC, no wall clock (callers inject ``now``), so the
CI fuzz sweep can drive random join/leave/restart orderings directly and
assert the invariants that matter:

- ranks are a pure function of the member set (sorted by ICI coordinates,
  then hostname), never of join order;
- a restarted coordinator or worker recovers the formed membership from
  the state file without re-forming the slice (same ranks, same
  generation);
- slice health is the conjunction of every member's reported health and
  heartbeat freshness.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import tempfile
from dataclasses import dataclass, field
from typing import (
    TYPE_CHECKING,
    Any,
    Dict,
    List,
    Optional,
    Set,
    Tuple,
)

if TYPE_CHECKING:  # typing only: this layer stays pure/metrics-free
    from .metrics import SliceMetrics

log = logging.getLogger(__name__)

_STATE_VERSION = 1


@dataclass(frozen=True)
class Membership:
    """The agreed slice: hostnames indexed by rank + coordinator address.

    ``reshaped_from`` is the degraded-mode lineage: the slice_ids of the
    generations this one was reshaped (or re-grown) from, oldest first —
    empty for a first formation.  ``degraded`` is true while the slice
    runs below its configured worker count."""

    slice_id: str
    generation: int
    hostnames: Tuple[str, ...]
    coordinator_address: str
    reshaped_from: Tuple[str, ...] = ()
    degraded: bool = False

    @property
    def num_workers(self) -> int:
        return len(self.hostnames)

    def rank_of(self, hostname: str) -> Optional[int]:
        try:
            return self.hostnames.index(hostname)
        except ValueError:
            return None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "version": _STATE_VERSION,
            "slice_id": self.slice_id,
            "generation": self.generation,
            "hostnames": list(self.hostnames),
            "coordinator_address": self.coordinator_address,
            "reshaped_from": list(self.reshaped_from),
            "degraded": self.degraded,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Membership":
        return cls(
            slice_id=str(d["slice_id"]),
            generation=int(d["generation"]),
            hostnames=tuple(str(h) for h in d["hostnames"]),
            coordinator_address=str(d.get("coordinator_address", "")),
            # absent in pre-reshape state files: loads as a first formation
            reshaped_from=tuple(
                str(s) for s in d.get("reshaped_from", ())),
            degraded=bool(d.get("degraded", False)),
        )


def save_membership(
    path: str,
    membership: Membership,
    member_coords: Optional[Dict[str, Tuple[int, ...]]] = None,
    evicted: Optional[Set[str]] = None,
) -> None:
    """Atomic write (tmp + rename in the target dir): a crash mid-write
    must leave either the old file or the new one, never a torn JSON —
    the whole point of the state file is surviving exactly such crashes.

    *member_coords* (coordinator only) additionally persists each
    member's ICI coordinate so a re-form AFTER a coordinator crash still
    ranks by physical mesh order instead of falling back to hostname
    sort; *evicted* (coordinator only) persists the reshape-evicted set
    so a revived coordinator still recognizes returnees.  Callers that
    omit them (clients) PRESERVE whatever the file already holds — on
    the rendezvous host the coordinator and the local client share one
    state file, and a client-side save must not clobber the
    coordinator's crash-recovery keys."""
    payload = membership.to_dict()
    prior: Optional[Dict[str, Any]] = None
    if member_coords is None or evicted is None:
        try:
            with open(path, "r", encoding="utf-8") as f:
                loaded = json.load(f)
            prior = loaded if isinstance(loaded, dict) else None
        except (OSError, ValueError):
            prior = None
    if member_coords is not None:
        payload["member_coords"] = {
            h: list(c) for h, c in sorted(member_coords.items())}
    elif prior is not None and "member_coords" in prior:
        payload["member_coords"] = prior["member_coords"]
    if evicted is not None:
        payload["evicted"] = sorted(str(h) for h in evicted)
    elif prior is not None and "evicted" in prior:
        payload["evicted"] = prior["evicted"]
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".membership-")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def load_member_coords(path: str) -> Dict[str, Tuple[int, ...]]:
    """The persisted per-member ICI coordinates ({} when absent or
    unreadable) — the coordinator's crash-recovery complement to
    :func:`load_membership`."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            d = json.load(f)
        raw = d.get("member_coords", {})
        return {str(h): tuple(int(x) for x in c)
                for h, c in raw.items()}
    except (OSError, ValueError, TypeError, AttributeError):
        return {}


def load_evicted(path: str) -> Set[str]:
    """The persisted reshape-evicted hostnames (empty when absent or
    unreadable) — lets a revived coordinator keep recognizing returnees
    instead of treating them as strangers."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            d = json.load(f)
        return {str(h) for h in d.get("evicted", ())}
    except (OSError, ValueError, TypeError, AttributeError):
        return set()


def load_membership(path: str) -> Optional[Membership]:
    """Load a persisted membership; None when absent or unreadable (a
    corrupt file means re-forming, not crashing the plugin)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            d = json.load(f)
    except OSError:
        return None
    except ValueError as e:
        log.warning("corrupt slice state file %s (%s); ignoring", path, e)
        return None
    try:
        if int(d.get("version", 0)) != _STATE_VERSION:
            log.warning("slice state file %s has unknown version %r",
                        path, d.get("version"))
            return None
        return Membership.from_dict(d)
    except (KeyError, TypeError, ValueError) as e:
        log.warning("malformed slice state file %s (%s); ignoring", path, e)
        return None


@dataclass
class _Member:
    hostname: str
    coords: Tuple[int, ...] = ()
    chip_count: int = 0
    session: str = ""
    healthy: bool = True
    reason: str = ""
    # None = not heard from since this coordinator incarnation started;
    # freshness is then measured from the incarnation epoch, so a restart
    # doesn't instantly declare every member stale.
    last_seen: Optional[float] = None
    departed: bool = False


def _slice_id(hostnames: List[str]) -> str:
    h = hashlib.sha256("\n".join(hostnames).encode("utf-8"))
    return h.hexdigest()[:12]


@dataclass
class JoinResult:
    formed: bool
    rank: int = -1
    joined: int = 0
    expected: int = 0
    membership: Optional[Membership] = None
    error: str = ""


@dataclass
class HealthView:
    slice_healthy: bool = True
    unhealthy_hostnames: List[str] = field(default_factory=list)
    membership: Optional[Membership] = None


class SliceState:
    """Rendezvous + health bookkeeping for one slice.

    Not thread-safe by itself — the gRPC servicer wraps calls in a lock;
    the fuzz harness drives it single-threaded.
    """

    def __init__(
        self,
        expected_workers: int,
        jax_port: int,
        state_path: Optional[str] = None,
        heartbeat_timeout_s: float = 0.0,
        epoch: float = 0.0,
        metrics: Optional["SliceMetrics"] = None,
        reshape_grace_s: float = 0.0,
    ) -> None:
        if expected_workers < 1:
            raise ValueError(f"expected_workers must be >= 1, got "
                             f"{expected_workers}")
        self.expected = expected_workers
        self.jax_port = jax_port
        self.state_path = state_path
        # 0 disables staleness demotion (tests drive heartbeats manually)
        self.heartbeat_timeout_s = heartbeat_timeout_s
        # 0 disables degraded-mode reshaping: the slice stays demoted
        # until every member recovers (the pre-reshape contract).  > 0:
        # a member's first unhealthy observation opens its own reshape
        # window; members still unhealthy at their window's expiry are
        # evicted and the survivors re-form under the next generation.
        self.reshape_grace_s = reshape_grace_s
        # per-member window clocks: hostname -> first time it was seen
        # unhealthy in the current incident.  Per-member (not one global
        # window) so a member that blips just before another member's
        # window expires still gets its full grace period.
        self._unhealthy_since: Dict[str, float] = {}
        # hosts evicted by a reshape: a returning one is re-admitted
        # into the NEXT generation (never resurrects the old one)
        self._evicted: Set[str] = set()
        self._epoch = epoch
        self._members: Dict[str, _Member] = {}
        self._membership: Optional[Membership] = None
        self._generation = 0
        # optional SliceMetrics (slice.metrics): transition counters,
        # demotion-propagation histogram, heartbeat-age refresh.  None
        # keeps this layer importable/pure on bare-grpc installs and in
        # the fuzz harness.
        self._metrics = metrics
        # propagation tracking: when the slice verdict flips unhealthy
        # at time T, each member's NEXT heartbeat response delivers the
        # demotion — observing (delivery - T) per member is the window
        # in which that member still advertised Healthy devices
        self._last_verdict: Optional[bool] = None
        self._demoted_at: float = 0.0
        self._awaiting_delivery: Set[str] = set()
        if state_path:
            prior = load_membership(state_path)
            if prior is not None:
                # Crash recovery: adopt the persisted slice as-is.  Members
                # exist from the start (ranks already assigned); they
                # refresh their sessions as they heartbeat/rejoin.  Their
                # persisted ICI coordinates come back too, so a LATER
                # re-form (reshape/regrow) still ranks by physical mesh
                # order.
                prior_coords = load_member_coords(state_path)
                self._membership = prior
                self._generation = prior.generation
                # the evicted set survives the crash too: returnees are
                # recognized as such, not treated as strangers
                self._evicted = (load_evicted(state_path)
                                 - set(prior.hostnames))
                for hostname in prior.hostnames:
                    self._members[hostname] = _Member(
                        hostname=hostname,
                        coords=prior_coords.get(hostname, ()))
                log.info(
                    "recovered slice %s gen %d (%d workers) from %s",
                    prior.slice_id, prior.generation,
                    prior.num_workers, state_path,
                )

    # -- rendezvous ---------------------------------------------------------

    def join(
        self,
        hostname: str,
        coords: Tuple[int, ...] = (),
        chip_count: int = 0,
        session: str = "",
        now: float = 0.0,
    ) -> JoinResult:
        """Idempotent join/poll.  Workers call this until ``formed``."""
        if not hostname:
            return JoinResult(formed=False, error="empty hostname")
        member = self._members.get(hostname)
        if member is None:
            if self._membership is not None:
                # Readmission requires an OPEN SEAT: re-forming past
                # expected_workers would hand out more ranks than the
                # physical topology holds (JAX_NUM_PROCESSES > hosts),
                # and a full healthy slice must never be generation-
                # bumped (checkpoint-restarting every workload) by a
                # returnee whose seat was already refilled.
                if (self.reshape_grace_s > 0
                        and len(self._members) < self.expected
                        and (
                            hostname in self._evicted
                            # a coordinator revived from a pre-eviction-
                            # persistence state file (or whose persist
                            # failed) forgets who it evicted: while the
                            # slice runs degraded below its configured
                            # size, an unknown joiner is treated as a
                            # returning member (repair), never on a
                            # full healthy slice
                            or self._membership.degraded
                        )):
                    # A member evicted by a reshape is returning: it joins
                    # the NEXT generation — survivors + returnee re-form
                    # immediately (rank contract changes, workloads
                    # checkpoint-restart) — never the generation it was
                    # evicted from.
                    return self._readmit(
                        hostname, coords=coords, chip_count=chip_count,
                        session=session, now=now)
                # Formed slice, unknown host: ranks are already handed to
                # running containers — admitting a stranger would silently
                # change the contract under them.
                return JoinResult(
                    formed=True,
                    membership=self._membership,
                    joined=len(self._members),
                    expected=self.expected,
                    error=(
                        f"slice {self._membership.slice_id} is formed and "
                        f"{hostname!r} is not a member"
                    ),
                )
            if len(self._members) >= self.expected:
                return JoinResult(
                    formed=False,
                    joined=len(self._members),
                    expected=self.expected,
                    error=f"slice already has {self.expected} joiners",
                )
            member = _Member(hostname=hostname)
            self._members[hostname] = member
        elif member.session and session and member.session != session:
            log.info("worker %s restarted (session %s -> %s)",
                     hostname, member.session[:8], session[:8])
        member.coords = tuple(coords)
        member.chip_count = chip_count
        member.session = session
        member.departed = False
        member.last_seen = now
        if self._membership is None and len(self._members) == self.expected:
            self._form()
        m = self._membership
        rank = m.rank_of(hostname) if m is not None else -1
        return JoinResult(
            formed=m is not None,
            rank=rank if rank is not None else -1,
            joined=len(self._members),
            expected=self.expected,
            membership=m,
        )

    def _form(self, lineage: Tuple[str, ...] = ()) -> None:
        """Assign deterministic ranks: members WITH ICI coordinates sort
        first by coordinate (rank order then matches the physical mesh,
        which is what TPU_WORKER_ID means to libtpu), coordinate-less
        members after them by hostname.  Join order never matters.
        *lineage* carries the reshape ancestry into the new generation."""
        ordered = sorted(
            self._members.values(),
            key=lambda mb: (0, mb.coords, mb.hostname) if mb.coords
            else (1, (), mb.hostname),
        )
        counts = {mb.chip_count for mb in ordered if mb.chip_count}
        if len(counts) > 1:
            log.warning(
                "heterogeneous chip counts across slice members: %s",
                {mb.hostname: mb.chip_count for mb in ordered},
            )
        hostnames = [mb.hostname for mb in ordered]
        self._generation += 1
        self._membership = Membership(
            slice_id=_slice_id(hostnames),
            generation=self._generation,
            hostnames=tuple(hostnames),
            coordinator_address=f"{hostnames[0]}:{self.jax_port}",
            reshaped_from=lineage,
            degraded=len(hostnames) < self.expected,
        )
        log.info("slice %s formed (gen %d%s): ranks %s, coordinator %s",
                 self._membership.slice_id, self._generation,
                 ", degraded" if self._membership.degraded else "",
                 hostnames, self._membership.coordinator_address)
        if self._metrics is not None:
            self._metrics.transition("formed")
        if self.state_path:
            try:
                save_membership(
                    self.state_path, self._membership,
                    member_coords={mb.hostname: mb.coords
                                   for mb in ordered},
                    evicted=set(self._evicted))
            except OSError as e:
                # Keep serving: persistence failing degrades crash
                # recovery, not the live slice.
                log.error("cannot persist slice state to %s: %s",
                          self.state_path, e)

    def _readmit(
        self,
        hostname: str,
        coords: Tuple[int, ...],
        chip_count: int,
        session: str,
        now: float,
    ) -> JoinResult:
        """Re-admit a reshape-evicted host: survivors + returnee re-form
        into the next generation (lineage extended with the generation
        being left behind)."""
        old = self._membership
        assert old is not None
        self._evicted.discard(hostname)
        self._members[hostname] = _Member(
            hostname=hostname, coords=tuple(coords),
            chip_count=chip_count, session=session, last_seen=now,
        )
        log.info("evicted member %s returned; re-forming slice %s into "
                 "the next generation", hostname, old.slice_id)
        self._form(lineage=old.reshaped_from + (old.slice_id,))
        if self._metrics is not None:
            self._metrics.reshape_outcome("grown")
        m = self._membership
        assert m is not None
        rank = m.rank_of(hostname)
        return JoinResult(
            formed=True,
            rank=rank if rank is not None else -1,
            joined=len(self._members),
            expected=self.expected,
            membership=m,
        )

    def leave(self, hostname: str) -> None:
        """Explicit departure.  Before formation the seat frees up; after,
        the member set (and every rank) is immutable — the host is marked
        departed, which drags slice health down until it rejoins."""
        member = self._members.get(hostname)
        if member is None:
            return
        if self._membership is None:
            del self._members[hostname]
        else:
            member.departed = True
            member.session = ""

    # -- health -------------------------------------------------------------

    def heartbeat(
        self,
        hostname: str,
        healthy: bool,
        reason: str = "",
        now: float = 0.0,
    ) -> HealthView:
        member = self._members.get(hostname)
        if member is not None:
            was = (member.healthy, member.departed)
            member.healthy = healthy
            member.reason = reason
            member.last_seen = now
            member.departed = False
            if (healthy, False) != was:
                log.info("slice member %s -> %s%s", hostname,
                         "healthy" if healthy else "UNHEALTHY",
                         f" ({reason})" if reason else "")
                if self._metrics is not None:
                    self._metrics.transition(
                        "member_recovered" if healthy
                        else "member_unhealthy")
        if self._metrics is not None:
            self._metrics.heartbeats.inc()
        view = self.health(now)
        if (self._metrics is not None and not view.slice_healthy
                and hostname in self._awaiting_delivery):
            # this response carries the demoted verdict to *hostname*
            # for the first time since the flip: the propagation window
            # for this member closes here
            self._awaiting_delivery.discard(hostname)
            self._metrics.demotion_propagation.observe(
                max(0.0, now - self._demoted_at))
        return view

    def _unhealthy(self, now: float) -> List[str]:
        """Members currently dragging the verdict down: reported
        unhealthy, departed, or (when a timeout is configured) silent."""
        unhealthy: List[str] = []
        for mb in self._members.values():
            if not mb.healthy or mb.departed:
                unhealthy.append(mb.hostname)
                continue
            if self.heartbeat_timeout_s > 0:
                seen = mb.last_seen if mb.last_seen is not None else self._epoch
                if now - seen > self.heartbeat_timeout_s:
                    unhealthy.append(mb.hostname)
        return unhealthy

    def _reshape_tick(self, unhealthy: List[str], now: float) -> List[str]:
        """Degraded-mode reshape windows (reshape_grace_s > 0, formed
        slice).  Each member's FIRST unhealthy observation opens that
        member's own grace window (a single global window would evict a
        member that blips just before another member's expiry with
        near-zero individual grace); recovery inside the window cancels
        that member's clock (the original generation holds, demote-all
        semantics meanwhile); a member still unhealthy when its own
        window expires is evicted and the survivors — including members
        whose windows are still running — re-form into a smaller slice
        under the next generation.  Returns the (possibly recomputed)
        unhealthy set."""
        current = set(unhealthy)
        recovered = [h for h in self._unhealthy_since if h not in current]
        for h in recovered:
            # recovered inside its window: this member's clock cancels
            del self._unhealthy_since[h]
        if not current:
            if recovered:
                # every member recovered inside its grace window: no
                # reshape, the original generation holds
                log.info("reshape window cancelled: all members of slice "
                         "%s recovered within the grace period",
                         self._membership.slice_id
                         if self._membership else "?")
                if self._metrics is not None:
                    self._metrics.reshape_outcome("cancelled")
            return unhealthy
        fresh = sorted(h for h in current
                       if h not in self._unhealthy_since)
        for h in fresh:
            self._unhealthy_since[h] = now
        if fresh:
            log.warning(
                "reshape window opened for members %s; evicting in "
                "%.1fs unless they recover", fresh, self.reshape_grace_s)
        evict = {h for h in current
                 if now - self._unhealthy_since[h] >= self.reshape_grace_s}
        if not evict:
            return unhealthy
        survivors = [h for h in self._members if h not in evict]
        if not survivors:
            # no valid smaller topology to re-form onto; stay demoted
            # and keep watching (fresh windows restart the clocks)
            self._unhealthy_since.clear()
            if self._metrics is not None:
                self._metrics.reshape_outcome("no_survivors")
            return unhealthy
        old = self._membership
        assert old is not None
        # incident duration: from the oldest evicted member's window
        incident_started = min(self._unhealthy_since[h] for h in evict)
        for h in sorted(evict):
            self._members.pop(h, None)
            self._evicted.add(h)
            del self._unhealthy_since[h]
        log.warning(
            "reshaping slice %s: evicted %s after %.1fs grace; "
            "re-forming over survivors %s", old.slice_id, sorted(evict),
            now - incident_started, sorted(survivors))
        self._form(lineage=old.reshaped_from + (old.slice_id,))
        if self._metrics is not None:
            self._metrics.reshape_outcome("reshaped")
            self._metrics.reshape_seconds.observe(
                max(0.0, now - incident_started))
        # evicted members owe no verdict deliveries anymore
        self._awaiting_delivery -= evict
        return self._unhealthy(now)

    def health(self, now: float = 0.0) -> HealthView:
        """Slice-wide verdict: every member healthy, present, and (when a
        timeout is configured) recently heard from.  With a reshape grace
        configured, a persistently-unhealthy member set is evicted here
        (see :meth:`_reshape_tick`) instead of demoting forever."""
        unhealthy = self._unhealthy(now)
        if self._membership is not None and self.reshape_grace_s > 0:
            unhealthy = self._reshape_tick(unhealthy, now)
        formed = self._membership is not None
        verdict = formed and not unhealthy
        if formed and verdict != self._last_verdict:
            if self._last_verdict is not None or not verdict:
                if self._metrics is not None:
                    self._metrics.transition(
                        "slice_recovered" if verdict
                        else "slice_demoted")
            if not verdict:
                # start the propagation clock: every member owes a
                # delivery of this verdict on its next heartbeat
                self._demoted_at = now
                self._awaiting_delivery = set(self._members)
            else:
                self._awaiting_delivery = set()
            self._last_verdict = verdict
        return HealthView(
            slice_healthy=verdict,
            unhealthy_hostnames=sorted(unhealthy),
            membership=self._membership,
        )

    # -- introspection ------------------------------------------------------

    def refresh_ages(self, now: float) -> None:
        """Refresh the per-member heartbeat-age gauge (scrape-time
        collector: ages are derived, not stored).  Members never heard
        from this incarnation age from the coordinator epoch, matching
        the staleness rule in :meth:`health`."""
        if self._metrics is None:
            return
        gauge = self._metrics.heartbeat_age
        gauge.clear()
        for mb in self._members.values():
            seen = mb.last_seen if mb.last_seen is not None else self._epoch
            gauge.labels(hostname=mb.hostname).set(max(0.0, now - seen))

    @property
    def membership(self) -> Optional[Membership]:
        return self._membership

    @property
    def joined(self) -> int:
        return len(self._members)
