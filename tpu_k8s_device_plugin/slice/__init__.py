"""Multi-host slice coordination: rendezvous, rank assignment, health.

The ROCm reference has no analog — a GPU node is self-contained — but a
TPU slice spans hosts over ICI: every worker must agree on ranks,
hostnames and a coordinator address before JAX can initialize
(``slice.proto``), and one wedged chip poisons collectives slice-wide, so
health must propagate to every member's kubelet, not just the faulty
host's.

Three layers:

- :mod:`.state` — the pure rendezvous state machine (deterministic ranks,
  crash-safe membership file, degraded-mode reshape: a bounded grace
  window instead of demote-all, with ``reshaped_from`` lineage across
  generations), fuzzable without gRPC or a clock;
- :mod:`.server` — the coordinator, serving ``SliceRendezvous`` for the
  whole slice from one member;
- :mod:`.client` — per-host join (retries + exponential backoff),
  heartbeat, eviction/rejoin across reshapes, and the env contract
  Allocate injects into containers.
"""

from .client import SliceClient
from .metrics import SliceMetrics
from .server import SliceCoordinator
from .state import (
    Membership,
    SliceState,
    load_membership,
    save_membership,
)

__all__ = [
    "Membership",
    "SliceClient",
    "SliceCoordinator",
    "SliceMetrics",
    "SliceState",
    "load_membership",
    "save_membership",
]
