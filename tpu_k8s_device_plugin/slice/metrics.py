"""Slice observability: the metrics set for multi-host coordination.

PR 1 shipped rendezvous, heartbeats, and slice-wide health with zero
instrumentation — a slice that formed slowly, a member whose heartbeats
aged out, or a demotion that took three pulses to reach the last member
all looked identical from the outside.  This module is the metric set
both halves (coordinator and per-host client) record into; on the
rendezvous host the two share the plugin manager's registry, so the one
debug ``/metrics`` scrape answers all of it.

Series (full reference: docs/user-guide/observability.md):

- ``tpu_slice_join_seconds`` — histogram, client-side: first Join poll
  to adopted membership (how long formation kept this host waiting).
- ``tpu_slice_heartbeat_age_seconds{hostname}`` — gauge, refreshed at
  scrape time: seconds since each member was last heard from
  (coordinator view) / since this host's last successful heartbeat
  (client view).  The staleness a timeout demotion would act on.
- ``tpu_slice_membership_transitions_total{kind}`` — counter:
  ``formed``, ``member_unhealthy``, ``member_recovered``,
  ``slice_demoted``, ``slice_recovered`` (coordinator) and
  ``verdict_demoted`` / ``verdict_recovered`` (client's learned view).
- ``tpu_slice_demotion_propagation_seconds`` — histogram,
  coordinator-side: slice verdict flipping unhealthy → each member's
  next heartbeat DELIVERING that verdict.  The window in which a
  member still advertises devices Healthy against a wedged peer.
- ``tpu_slice_heartbeats_total`` — heartbeats the coordinator served.
- ``tpu_slice_reshape_total{outcome}`` — counter, coordinator-side:
  degraded-mode reshape window outcomes — ``reshaped`` (members evicted,
  survivors re-formed smaller), ``cancelled`` (every member recovered
  inside the grace window), ``grown`` (an evicted member returned and a
  bigger next generation formed), ``no_survivors`` (window expired with
  nothing left to re-form onto).  The client counts ``reshape_adopted``
  under ``tpu_slice_membership_transitions_total`` when it learns a new
  generation.
- ``tpu_slice_reshape_seconds`` — histogram, coordinator-side: reshape
  window opening (unhealthy verdict) → reshaped membership formed.

Both halves accept ``metrics=None`` and stay zero-cost when unmetered
(the fuzz harness and bare-grpc installs never touch obs state).
"""

from __future__ import annotations

from typing import Optional

from tpu_k8s_device_plugin import obs


class SliceMetrics:
    """The slice instrument set on one registry (see module docstring)."""

    def __init__(self, registry: Optional[obs.Registry] = None) -> None:
        reg = registry if registry is not None else obs.Registry()
        self.registry = reg
        self.join_seconds = reg.histogram(
            "tpu_slice_join_seconds",
            "Time from this host's first Join poll to adopted "
            "membership.", buckets=obs.SLOW_BUCKETS_S)
        self.heartbeat_age = reg.gauge(
            "tpu_slice_heartbeat_age_seconds",
            "Seconds since each slice member was last heard from "
            "(refreshed at scrape time).", ("hostname",))
        self.transitions = reg.counter(
            "tpu_slice_membership_transitions_total",
            "Slice membership and health transitions, by kind.",
            ("kind",))
        self.demotion_propagation = reg.histogram(
            "tpu_slice_demotion_propagation_seconds",
            "Unhealthy slice verdict -> delivery to each member's "
            "next heartbeat.", buckets=obs.LATENCY_BUCKETS_S)
        self.heartbeats = reg.counter(
            "tpu_slice_heartbeats_total",
            "Heartbeats the coordinator has served.")
        self.reshapes = reg.counter(
            "tpu_slice_reshape_total",
            "Degraded-mode reshape window outcomes, by kind.",
            ("outcome",))
        self.reshape_seconds = reg.histogram(
            "tpu_slice_reshape_seconds",
            "Reshape window opening (unhealthy verdict) -> reshaped "
            "membership formed.", buckets=obs.SLOW_BUCKETS_S)

    def transition(self, kind: str) -> None:
        self.transitions.labels(kind=kind).inc()

    def reshape_outcome(self, outcome: str) -> None:
        self.reshapes.labels(outcome=outcome).inc()
