"""Per-host slice client: join with backoff, heartbeat, env contract.

Every member of the slice (the coordinator's own host included) runs one
of these inside its device plugin.  The client owns three things:

- **join**: polls the rendezvous service until the slice forms, with
  exponential backoff, and persists the learned membership to a local
  crash-safe state file so a restarted plugin knows its rank immediately
  (and the node labeller can emit slice labels without talking gRPC);
- **heartbeat**: reports local chip health each pulse and learns the
  slice-wide verdict from the response — the channel through which one
  host's wedged chip flips every member's devices Unhealthy;
- **env contract**: the consistent ``TPU_WORKER_ID`` /
  ``TPU_WORKER_HOSTNAMES`` / JAX coordinator triple Allocate injects into
  every container of the slice, replacing per-host guesses.
"""

from __future__ import annotations

import logging
import socket
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

import grpc

from tpu_k8s_device_plugin import obs, resilience
from tpu_k8s_device_plugin.proto import (
    slice_pb2 as slicepb,
    slice_pb2_grpc as slicepb_grpc,
)
from tpu_k8s_device_plugin.resilience import faults
from tpu_k8s_device_plugin.types import constants
from .metrics import SliceMetrics
from .state import Membership, load_membership, save_membership

log = logging.getLogger(__name__)

# (healthy, reason) probe of this host's own chips; injected by the device
# impl so the client carries fresh local state in every heartbeat.
LocalHealthFn = Callable[[], Tuple[bool, str]]

_JOIN_BACKOFF_INITIAL_S = 0.5
_JOIN_BACKOFF_MAX_S = 15.0
_RPC_TIMEOUT_S = 10.0
# heartbeat circuit breaker: after this many consecutive failed
# heartbeats the client stops burning a full RPC timeout per pulse and
# fails fast until the reset window admits one probe heartbeat
_HB_BREAKER_THRESHOLD = 3
_HB_BREAKER_RESET_S = 30.0
# the RPC faults the retry/breaker machinery treats as transient
_TRANSIENT = (grpc.RpcError, faults.InjectedFault)


def _trace_metadata(trace: Optional[obs.TraceContext]
                    ) -> Tuple[Tuple[str, str], ...]:
    """gRPC metadata carrying the W3C traceparent (the HTTP header's
    metadata analog), or () when the caller runs untraced."""
    if trace is None:
        return ()
    return (("traceparent", trace.to_traceparent()),)


def _rpc_status_code(e: BaseException) -> Optional[Any]:
    """The grpc status code of an RpcError, or None for non-RPC faults
    (an InjectedFault carries no code)."""
    code = getattr(e, "code", None)
    return code() if callable(code) else None


def _membership_from_msg(m: Any) -> Optional[Membership]:
    if not m.hostnames:
        return None
    return Membership(
        slice_id=m.slice_id,
        generation=m.generation,
        hostnames=tuple(m.hostnames),
        coordinator_address=m.coordinator_address,
        reshaped_from=tuple(m.reshaped_from),
        degraded=m.degraded,
    )


class SliceClient:
    """One host's view of the slice."""

    def __init__(
        self,
        rendezvous_address: str,
        hostname: Optional[str] = None,
        coords: Tuple[int, ...] = (),
        chip_count: int = 0,
        state_path: Optional[str] = constants.SLICE_STATE_FILE,
        local_health_fn: Optional[LocalHealthFn] = None,
        registry: Optional[obs.Registry] = None,
        recorder: Optional[obs.FlightRecorder] = None,
        join_backoff_initial_s: float = _JOIN_BACKOFF_INITIAL_S,
        join_backoff_max_s: float = _JOIN_BACKOFF_MAX_S,
        rpc_timeout_s: float = _RPC_TIMEOUT_S,
        breaker_reset_s: float = _HB_BREAKER_RESET_S,
        seed: int = 0,
    ) -> None:
        self._address = rendezvous_address
        self.hostname = hostname or socket.gethostname()
        self._rpc_timeout_s = rpc_timeout_s
        # jittered-backoff schedule shared with every other boundary
        # in the repo (resilience.RetryPolicy); seeded so a chaos run
        # replays the same join timing
        self._join_policy = resilience.RetryPolicy(
            max_attempts=1 << 30,
            initial_backoff_s=join_backoff_initial_s,
            max_backoff_s=join_backoff_max_s,
            seed=seed,
        )
        # flight recorder (PR 4): membership transitions and learned
        # verdicts journal here with the trace that delivered them
        self._recorder = recorder
        # slice metrics (PR 3): join duration, learned-verdict
        # transitions, and this host's own heartbeat age (refreshed at
        # scrape time).  On the rendezvous host the coordinator shares
        # the registry, so instrument families dedupe onto one set.
        self.metrics: Optional[SliceMetrics] = None
        self._last_beat: Optional[float] = None
        self._join_started: Optional[float] = None
        self._res_metrics: Optional[resilience.ResilienceMetrics] = None
        if registry is not None:
            self.metrics = SliceMetrics(registry)
            self._res_metrics = resilience.ResilienceMetrics(registry)
            registry.on_collect(self._refresh_age)
        # a dead coordinator must not cost every pulse a full RPC
        # timeout: the breaker fails heartbeats fast once it opens and
        # admits one probe per reset window.  Verdict semantics are
        # unchanged — a failed (or skipped) heartbeat keeps the last
        # learned verdict, exactly like an unreachable coordinator.
        self._hb_breaker = resilience.CircuitBreaker(
            "slice.heartbeat",
            failure_threshold=_HB_BREAKER_THRESHOLD,
            reset_timeout_s=breaker_reset_s,
            metrics=self._res_metrics,
            recorder=recorder,
            logger=log,
        )
        self._coords = tuple(coords)
        self._chip_count = chip_count
        self._state_path = state_path
        self._local_health_fn = local_health_fn
        # fresh per process start: lets the coordinator tell a worker
        # restart apart from a duplicate hostname
        self._session = uuid.uuid4().hex
        # rising-edge guard so an eviction journals/counts once, not
        # once per pulse while we wait to rejoin
        self._evicted_flag = False
        self._lock = threading.Lock()
        self._membership: Optional[Membership] = None
        # reshape hook: called (old_membership, new_membership) whenever a
        # NEW generation is adopted over a previous one — the workload
        # layer (ReshapeSignal) and tests hang checkpoint triggers here.
        # Exceptions are suppressed-but-accounted: a broken callback must
        # not break heartbeats.
        self._on_reshape: Optional[
            Callable[[Optional[Membership], Membership], None]] = None
        # None until the first heartbeat answer: "no verdict yet" must not
        # flip devices Unhealthy while the slice is still forming
        self._slice_healthy: Optional[bool] = None
        self._unhealthy_hosts: List[str] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # ONE channel for the client's lifetime (gRPC reconnects a
        # broken channel itself); the old fresh-channel-per-attempt
        # pattern leaked a socket + connect handshake per backoff poll
        self._ch: Optional[grpc.Channel] = None
        self._ch_lock = threading.Lock()
        if state_path:
            prior = load_membership(state_path)
            if prior is not None and prior.rank_of(self.hostname) is not None:
                # restarted worker: rank recovered without re-forming
                self._membership = prior
                log.info(
                    "recovered slice %s rank %d from %s",
                    prior.slice_id, prior.rank_of(self.hostname), state_path,
                )

    # -- join ---------------------------------------------------------------

    def _channel(self) -> grpc.Channel:
        """The client's one long-lived channel (created on first use,
        closed by stop()); stopped clients get a fresh one so a
        restarted client keeps working."""
        with self._ch_lock:
            if self._ch is None:
                self._ch = grpc.insecure_channel(self._address)
            return self._ch

    def _close_channel(self) -> None:
        with self._ch_lock:
            ch, self._ch = self._ch, None
        if ch is not None:
            try:
                ch.close()
            except Exception as e:
                resilience.suppressed("slice.channel_close", e,
                                      logger=log,
                                      metrics=self._res_metrics)

    def _join_once(self, trace: Optional[obs.TraceContext] = None
                   ) -> Optional[Membership]:
        """One Join poll; returns the membership when formed.  *trace*
        rides the gRPC metadata as a ``traceparent`` entry so the
        coordinator's join span shares this member's trace."""
        if faults.ACTIVE is not None:
            faults.ACTIVE.fire("slice.join")
        stub = slicepb_grpc.SliceRendezvousStub(self._channel())
        resp = stub.Join(
            slicepb.JoinRequest(
                hostname=self.hostname,
                coords=list(self._coords),
                chip_count=self._chip_count,
                session=self._session,
            ),
            timeout=self._rpc_timeout_s,
            metadata=_trace_metadata(trace),
        )
        if not resp.formed:
            log.info(
                "slice forming: %d/%d workers joined",
                resp.joined, resp.expected,
            )
            return None
        return _membership_from_msg(resp.membership)

    def join(self, timeout_s: float = 0.0) -> Membership:
        """Poll Join until the slice forms (exponential backoff, capped).
        ``timeout_s`` 0 means wait forever; on expiry raises TimeoutError.
        Safe to call again after a restart: the coordinator hands back the
        existing rank without re-forming."""
        deadline = time.monotonic() + timeout_s if timeout_s > 0 else None
        attempt = 0
        if self._join_started is None:
            self._join_started = time.monotonic()
        # one root trace covers the whole join (every poll carries it),
        # so the coordinator's view of this host's formation is one
        # /debug/traces query on the rendezvous node
        join_trace = obs.new_trace()
        while not self._stop.is_set():
            try:
                membership = self._join_once(trace=join_trace)
            except _TRANSIENT as e:
                code = _rpc_status_code(e)
                if code == grpc.StatusCode.FAILED_PRECONDITION:
                    # mis-sized slice or hostname drift: retrying cannot
                    # fix it, surface the coordinator's explanation
                    details = getattr(e, "details", None)
                    raise RuntimeError(
                        "slice join rejected: "
                        f"{details() if callable(details) else e}"
                    ) from e
                log.info("rendezvous %s unreachable (%s); retrying",
                         self._address, code if code is not None else e)
                if self._res_metrics is not None:
                    self._res_metrics.retries.labels(
                        op="slice.join").inc()
                membership = None
            if membership is not None:
                self._adopt(membership, trace=join_trace)
                return membership
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(
                    f"slice did not form within {timeout_s:.0f}s "
                    f"(rendezvous {self._address})"
                )
            attempt += 1
            if self._stop.wait(self._join_policy.backoff_s(attempt)):
                break
        raise RuntimeError("slice client stopped before the slice formed")

    def set_reshape_callback(
        self,
        fn: Optional[Callable[[Optional[Membership], Membership], None]],
    ) -> None:
        """Wire the workload-side reshape hook (e.g.
        ``workloads.checkpoint.ReshapeSignal.fire``): invoked with
        (old_membership, new_membership) when a new generation is
        adopted."""
        self._on_reshape = fn

    def _adopt(self, membership: Membership,
               trace: Optional[obs.TraceContext] = None) -> None:
        with self._lock:
            prior = self._membership
            self._membership = membership
        if prior is None and self.metrics is not None \
                and self._join_started is not None:
            # formation latency as THIS host experienced it (first
            # join attempt to adopted membership)
            self.metrics.join_seconds.observe(
                time.monotonic() - self._join_started)
        if prior is None or prior.generation != membership.generation:
            rank = membership.rank_of(self.hostname)
            if self._recorder is not None:
                self._recorder.record(
                    "tpu_slice_membership_adopted", trace=trace,
                    slice_id=membership.slice_id,
                    generation=membership.generation,
                    rank=rank, workers=membership.num_workers,
                    degraded=membership.degraded,
                    reshaped_from=",".join(membership.reshaped_from)
                    or "-")
            log.info(
                "slice %s gen %d%s: rank %s of %d, coordinator %s",
                membership.slice_id, membership.generation,
                " (degraded)" if membership.degraded else "", rank,
                membership.num_workers, membership.coordinator_address,
            )
            if self._state_path:
                try:
                    save_membership(self._state_path, membership)
                except OSError as e:
                    log.error("cannot persist slice membership to %s: %s",
                              self._state_path, e)
            if prior is not None:
                # the identity contract just CHANGED under this host — a
                # reshape (or regrow) — which is what workloads key
                # checkpoint-restarts off
                if self.metrics is not None:
                    self.metrics.transition("reshape_adopted")
                if self._on_reshape is not None:
                    try:
                        self._on_reshape(prior, membership)
                    except Exception as e:
                        resilience.suppressed(
                            "slice.reshape_callback", e, logger=log,
                            metrics=self._res_metrics)

    # -- heartbeat ----------------------------------------------------------

    def heartbeat_now(self, trace: Optional[obs.TraceContext] = None
                      ) -> None:
        """One synchronous heartbeat: probe local health, report it, learn
        the slice verdict.  Joins first if the slice hasn't formed yet (a
        single non-blocking attempt).  Called from the manager's pulse
        loop (which passes its pulse-round trace, so the coordinator's
        heartbeat span shares it) and from the background thread; errors
        degrade to 'no verdict change', never raise."""
        ctx = trace if trace is not None else obs.new_trace()
        if not self._hb_breaker.allow():
            # circuit open: a dead coordinator already ate
            # failure_threshold RPC timeouts — skip this pulse's
            # heartbeat entirely (same verdict semantics as a failed
            # one) and let the breaker's reset window admit the probe
            log.debug("slice heartbeat skipped: breaker open for %s",
                      self._address)
            return
        try:
            current = self.membership
            if current is None:
                joined = self._join_once(trace=ctx)
                if joined is None:
                    return
                self._adopt(joined, trace=ctx)
                current = joined
            healthy, reason = True, ""
            if self._local_health_fn is not None:
                try:
                    healthy, reason = self._local_health_fn()
                except Exception as e:
                    # a broken probe is a fault report, not a crash: the
                    # peers must still learn this host can't vouch for
                    # its chips
                    log.warning("local health probe failed: %s", e)
                    healthy, reason = False, f"local probe error: {e}"
            if faults.ACTIVE is not None:
                faults.ACTIVE.fire("slice.heartbeat")
            stub = slicepb_grpc.SliceRendezvousStub(self._channel())
            resp = stub.Heartbeat(
                slicepb.HeartbeatRequest(
                    hostname=self.hostname,
                    healthy=healthy,
                    reason=reason,
                    generation=current.generation,
                ),
                timeout=self._rpc_timeout_s,
                metadata=_trace_metadata(ctx),
            )
        except _TRANSIENT as e:
            # An unreachable coordinator is NOT a slice-wide Unhealthy
            # verdict by itself (that would let one crashed pod demote
            # every node's devices); keep the last verdict and let the
            # coordinator's own staleness tracking judge us.
            self._hb_breaker.record_failure()
            code = _rpc_status_code(e)
            log.warning("slice heartbeat to %s failed: %s",
                        self._address,
                        code if code is not None else e)
            return
        self._hb_breaker.record_success()
        fresh = _membership_from_msg(resp.membership)
        if fresh is not None:
            self._adopt(fresh, trace=ctx)
            if fresh.rank_of(self.hostname) is None:
                # the slice reshaped WITHOUT us: this host was evicted
                # (grace window expired while it was wedged/silent).
                # Rejoin into the next generation once local chips are
                # healthy; the learned verdict below belongs to a slice
                # we are no longer part of, so skip it.
                self._last_beat = time.monotonic()
                self._handle_eviction(healthy, ctx)
                return
        self._evicted_flag = False
        self._last_beat = time.monotonic()
        with self._lock:
            prior = self._slice_healthy
            self._slice_healthy = resp.slice_healthy
            self._unhealthy_hosts = list(resp.unhealthy_hostnames)
        if prior is not None and prior != resp.slice_healthy:
            if self.metrics is not None:
                # the verdict as THIS host learned it (the coordinator
                # counts slice_demoted/slice_recovered at the source)
                self.metrics.transition(
                    "verdict_recovered" if resp.slice_healthy
                    else "verdict_demoted")
            if self._recorder is not None:
                # the learned-verdict flip IS the demotion/recovery
                # moment on this host — journal it with the heartbeat's
                # trace so the post-mortem links it to the pulse round
                self._recorder.record(
                    "tpu_slice_verdict_recovered" if resp.slice_healthy
                    else "tpu_slice_verdict_demoted",
                    trace=ctx,
                    slice_id=(self.membership.slice_id
                              if self.membership else ""),
                    unhealthy=",".join(resp.unhealthy_hostnames) or "-")
            log.warning(
                "slice %s -> %s%s",
                self.membership.slice_id if self.membership else "?",
                "healthy" if resp.slice_healthy else "UNHEALTHY",
                f" (members: {list(resp.unhealthy_hostnames)})"
                if not resp.slice_healthy else "",
            )

    def _handle_eviction(self, healthy: bool,
                         trace: Optional[obs.TraceContext]) -> None:
        """This host learned it is no longer a member (evicted by a
        reshape).  Journal it once, then — as soon as local chips are
        healthy — rejoin so the coordinator re-forms the NEXT generation
        around survivors + us.  While evicted, health_overlay() answers
        None: the devices advertise standalone (local) health only."""
        if not self._evicted_flag:
            self._evicted_flag = True
            m = self.membership
            log.warning(
                "evicted from slice %s (gen %d reshape); will rejoin the "
                "next generation when locally healthy",
                m.slice_id if m else "?",
                m.generation if m else -1)
            if self.metrics is not None:
                self.metrics.transition("evicted")
            if self._recorder is not None:
                self._recorder.record(
                    "tpu_slice_evicted", trace=trace,
                    slice_id=m.slice_id if m else "",
                    generation=m.generation if m else -1,
                    hostname=self.hostname)
        if not healthy:
            return
        try:
            rejoined = self._join_once(trace=trace)
        except _TRANSIENT as e:
            code = _rpc_status_code(e)
            log.warning("rejoin after eviction failed: %s",
                        code if code is not None else e)
            return
        if rejoined is not None \
                and rejoined.rank_of(self.hostname) is not None:
            self._evicted_flag = False
            self._adopt(rejoined, trace=trace)

    def start(
        self, period_s: float = constants.SLICE_HEARTBEAT_PERIOD_S
    ) -> "SliceClient":
        """Background join-then-heartbeat loop.  The manager's pulse also
        calls heartbeat_now() directly; both paths are lock-safe."""
        if self._thread is not None:
            return self

        def loop() -> None:
            while not self._stop.is_set():
                self.heartbeat_now()
                if self._stop.wait(period_s):
                    return

        self._thread = threading.Thread(
            target=loop, name="slice-heartbeat", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self._close_channel()

    def _refresh_age(self) -> None:
        """Scrape-time collector: this host's own heartbeat age (how
        stale our view of the slice verdict is)."""
        if self.metrics is None or self._last_beat is None:
            return
        self.metrics.heartbeat_age.labels(hostname=self.hostname).set(
            max(0.0, time.monotonic() - self._last_beat))

    # -- the contract consumed by Allocate / update_health ------------------

    @property
    def membership(self) -> Optional[Membership]:
        with self._lock:
            return self._membership

    @property
    def rank(self) -> Optional[int]:
        m = self.membership
        return m.rank_of(self.hostname) if m is not None else None

    def slice_env(self) -> Dict[str, str]:
        """Env every container of a full-host grant receives — identical
        on all members modulo TPU_WORKER_ID.  Empty before formation (the
        impl then falls back to the per-host metadata view)."""
        m = self.membership
        if m is None:
            return {}
        rank = m.rank_of(self.hostname)
        if rank is None:
            return {}
        return {
            constants.ENV_TPU_WORKER_ID: str(rank),
            constants.ENV_TPU_WORKER_HOSTNAMES: ",".join(m.hostnames),
            constants.ENV_JAX_COORDINATOR_ADDRESS: m.coordinator_address,
            constants.ENV_JAX_NUM_PROCESSES: str(m.num_workers),
            constants.ENV_JAX_PROCESS_ID: str(rank),
            # generation stamp: workloads compare it against the live
            # membership file (ReshapeSignal) to detect that the slice
            # reshaped under them
            constants.ENV_TPU_SLICE_GENERATION: str(m.generation),
        }

    def health_overlay(self) -> Optional[Tuple[bool, List[str]]]:
        """(slice_healthy, unhealthy hostnames), or None while no verdict
        has arrived yet — ListAndWatch must not flap devices Unhealthy
        just because the slice is still forming.  Also None while this
        host is evicted from a reshaped slice: its devices advertise
        standalone (local) health, not a verdict about a slice it no
        longer belongs to."""
        with self._lock:
            if self._slice_healthy is None:
                return None
            m = self._membership
            if m is not None and m.rank_of(self.hostname) is None:
                return None
            return self._slice_healthy, list(self._unhealthy_hosts)
