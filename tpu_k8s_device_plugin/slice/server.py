"""Slice coordinator: serves SliceRendezvous for every member of one slice.

Runs inside the device plugin of the host named by ``--slice-rendezvous``
(the plugin compares that hostname against its own and serves only when
they match — every member runs identical flags, one of them self-elects).
The state machine itself lives in :mod:`.state`; this layer adds the gRPC
surface, locking, and the wall clock.
"""

from __future__ import annotations

import concurrent.futures
import logging
import threading
import time
from typing import Any, Optional

import grpc

from tpu_k8s_device_plugin import obs, resilience
from tpu_k8s_device_plugin.proto import (
    slice_pb2 as slicepb,
    slice_pb2_grpc as slicepb_grpc,
)
from tpu_k8s_device_plugin.types import constants
from .state import Membership, SliceState

log = logging.getLogger(__name__)


def _trace_from_context(context: Any) -> obs.TraceContext:
    """Continue the member's trace from the RPC metadata (the client
    sends a ``traceparent`` entry — the gRPC analog of the HTTP
    header), or open a fresh root for untraced callers."""
    header = None
    try:
        for key, value in context.invocation_metadata():
            if key == "traceparent":
                header = value
                break
    except Exception as e:
        # metadata access is best-effort, never fatal — but the
        # swallow is accounted (tpu_suppressed_errors_total) so a
        # flood of malformed metadata stays visible
        resilience.suppressed("slice.trace_metadata", e, logger=log)
    return obs.trace_from_header(header)


def _membership_msg(m: Optional[Membership]) -> Any:
    if m is None:
        return slicepb.Membership()
    return slicepb.Membership(
        slice_id=m.slice_id,
        generation=m.generation,
        num_workers=m.num_workers,
        hostnames=list(m.hostnames),
        coordinator_address=m.coordinator_address,
        reshaped_from=list(m.reshaped_from),
        degraded=m.degraded,
    )


class _Servicer(slicepb_grpc.SliceRendezvousServicer):
    def __init__(self, state: SliceState, lock: threading.Lock,
                 recorder: Optional[obs.FlightRecorder] = None) -> None:
        self._state = state
        self._lock = lock
        self._recorder = recorder

    def _record_generation_change(
        self,
        before: Optional[Membership],
        after: Optional[Membership],
        trace: obs.TraceContext,
    ) -> None:
        """Journal a reshape/regrow: the locked call just made a NEW
        generation (grace-window eviction or an evicted member
        returning) — the journal entry is the slice-wide evidence the
        chaos episodes assert on.  *before*/*after* are captured inside
        the state lock so concurrent RPCs journal their own transition,
        not each other's."""
        if self._recorder is None:
            return
        if after is None or before is None \
                or after.generation == before.generation:
            return
        self._recorder.record(
            "tpu_slice_reshaped", trace=trace,
            slice_id=after.slice_id,
            generation=after.generation,
            workers=after.num_workers,
            degraded=after.degraded,
            reshaped_from=",".join(after.reshaped_from) or "-",
            previous=before.slice_id)

    def Join(self, request: Any, context: Any) -> Any:
        # the member's trace rides the RPC metadata: the coordinator's
        # join record shares it, so one id greps across both hosts
        trace = _trace_from_context(context)
        with self._lock:
            before = self._state.membership
            res = self._state.join(
                hostname=request.hostname,
                coords=tuple(request.coords),
                chip_count=request.chip_count,
                session=request.session,
                now=time.monotonic(),
            )
            after = self._state.membership
        self._record_generation_change(before, after, trace)
        if self._recorder is not None:
            self._recorder.record(
                "tpu_slice_join", trace=trace,
                hostname=request.hostname, formed=res.formed,
                joined=res.joined, expected=res.expected,
                error=res.error or "")
        log.debug("span=tpu_slice_join trace_id=%s hostname=%s "
                  "formed=%s joined=%d/%d", trace.trace_id,
                  request.hostname, res.formed, res.joined,
                  res.expected)
        if res.error and res.membership is None:
            # a non-member knocking on a full-but-unformed slice, or a
            # malformed request: refuse loudly so the operator sees a
            # mis-sized --slice-workers instead of a hung formation
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, res.error)
        if res.error:
            # formed slice, unknown host: same refusal, but the membership
            # in the details log helps diagnose a hostname drift
            context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                f"{res.error} (members: {list(res.membership.hostnames)})",
            )
        return slicepb.JoinResponse(
            formed=res.formed,
            rank=res.rank,
            joined=res.joined,
            expected=res.expected,
            membership=_membership_msg(res.membership),
        )

    def Heartbeat(self, request: Any, context: Any) -> Any:
        trace = _trace_from_context(context)
        with self._lock:
            before = self._state.membership
            view = self._state.heartbeat(
                hostname=request.hostname,
                healthy=request.healthy,
                reason=request.reason,
                now=time.monotonic(),
            )
            after = self._state.membership
        self._record_generation_change(before, after, trace)
        if self._recorder is not None:
            self._recorder.record(
                "tpu_slice_heartbeat", trace=trace,
                hostname=request.hostname, healthy=request.healthy,
                reason=request.reason or "",
                slice_healthy=view.slice_healthy)
        log.debug("span=tpu_slice_heartbeat trace_id=%s hostname=%s "
                  "healthy=%s slice_healthy=%s", trace.trace_id,
                  request.hostname, request.healthy,
                  view.slice_healthy)
        return slicepb.HeartbeatResponse(
            slice_healthy=view.slice_healthy,
            unhealthy_hostnames=view.unhealthy_hostnames,
            membership=_membership_msg(view.membership),
        )


class SliceCoordinator:
    """Owns the rendezvous gRPC server + the slice state machine."""

    def __init__(
        self,
        expected_workers: int,
        bind_address: str = f"[::]:{constants.SLICE_RENDEZVOUS_PORT}",
        jax_port: int = constants.SLICE_JAX_COORDINATOR_PORT,
        state_path: Optional[str] = constants.SLICE_STATE_FILE,
        heartbeat_timeout_s: float = constants.SLICE_HEARTBEAT_TIMEOUT_S,
        registry: Optional[obs.Registry] = None,
        recorder: Optional[obs.FlightRecorder] = None,
        reshape_grace_s: float = constants.SLICE_RESHAPE_GRACE_S,
    ) -> None:
        self._lock = threading.Lock()
        # flight recorder (PR 4): join/heartbeat events land here with
        # each MEMBER'S trace-id from the RPC metadata — the
        # coordinator's journal is the slice-wide timeline
        self.recorder = recorder
        # slice metrics (PR 3): formation/transition counters, the
        # demotion-propagation histogram, and a scrape-time collector
        # refreshing per-member heartbeat ages.  The CLI passes the
        # plugin manager's registry so the debug /metrics scrape on the
        # rendezvous host carries the whole slice's coordination state.
        from .metrics import SliceMetrics

        self.metrics: Optional[SliceMetrics] = None
        if registry is not None:
            self.metrics = SliceMetrics(registry)
        self.state = SliceState(
            expected_workers=expected_workers,
            jax_port=jax_port,
            state_path=state_path,
            heartbeat_timeout_s=heartbeat_timeout_s,
            epoch=time.monotonic(),
            metrics=self.metrics,
            reshape_grace_s=reshape_grace_s,
        )
        if registry is not None:
            def _refresh() -> None:
                with self._lock:
                    self.state.refresh_ages(time.monotonic())

            registry.on_collect(_refresh)
        self._bind_address = bind_address
        self._server: Optional[grpc.Server] = None
        self.port: int = 0

    def start(self) -> "SliceCoordinator":
        self._server = grpc.server(
            concurrent.futures.ThreadPoolExecutor(max_workers=8)
        )
        slicepb_grpc.add_SliceRendezvousServicer_to_server(
            _Servicer(self.state, self._lock, recorder=self.recorder),
            self._server
        )
        self.port = self._server.add_insecure_port(self._bind_address)
        if self.port == 0:
            raise RuntimeError(
                f"cannot bind slice rendezvous on {self._bind_address}"
            )
        self._server.start()
        log.info(
            "slice rendezvous serving on %s (expecting %d workers)",
            self._bind_address, self.state.expected,
        )
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=1.0).wait()
            self._server = None
