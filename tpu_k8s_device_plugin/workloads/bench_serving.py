"""Pod-runnable serving benchmark: tokens/sec of the native decode
engine (the counterpart of bench_main.py for BASELINE config #5).

Runs the KV-cache decode loop on whatever chips the plugin granted and
prints tokens/sec — e.g. Llama-3-8B weight-only int8 on a single v5e
(the model family the reference's vLLM example deploys, served by the
native engine instead of an opaque image):

    python -m tpu_k8s_device_plugin.workloads.bench_serving \
        --config llama3-8b --quantized --batch 1 --steps 64

Weights are random (throughput moves bytes, not meanings) and are
constructed DIRECTLY in the quantized layout so the 8B config fits on
one 16 GB chip (see llama.random_quantized_params).
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp

from . import llama
from .inference import decode_throughput, quantize_lm_params

CONFIGS = {
    "llama3-8b": llama.LLAMA3_8B,
    "llama2-7b": llama.LLAMA2_7B,
    "tiny": llama.TINY_LLAMA,
}


def build_model_and_params(config: str, max_len: int, quantized,
                           mesh=None):
    """Decode model + benchmark-posture params (random weights built
    DIRECTLY in the serving layout) for a named config.  The ONE
    construction recipe shared by this benchmark and the HTTP server
    (workloads/server.py) — a real deployment swaps the random params
    for a checkpoint restored via workloads.checkpoint.

    With *mesh*, every leaf is materialized ALREADY SHARDED onto its
    tensor-parallel placement (jit with out_shardings from an abstract
    tree) — build-then-reshard would peak at the full tree on one
    device, which is exactly what --tp exists to avoid at 8B scale."""
    cfg = CONFIGS[config]
    model = llama.decoder(cfg, max_len=max_len, quantized=quantized)

    def build():
        if quantized == "int4":
            return llama.random_quantized_params(cfg, bits=4)
        if quantized:
            return llama.random_quantized_params(cfg)
        # small configs only: materializes the bf16 tree
        train = llama.train_model(cfg)
        tokens = jnp.zeros((1, 8), jnp.int32)
        pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
        return train.init(jax.random.PRNGKey(0), tokens, pos)["params"]

    if mesh is None:
        return cfg, model, build()
    from .transformer import lm_tree_shardings

    shardings = lm_tree_shardings(mesh, jax.eval_shape(build))
    params = jax.jit(build, out_shardings=shardings)()
    return cfg, model, params


def run(config: str, quantized, batch: int, steps: int,
        prompt_len: int, max_len: int, engine: bool = False):
    # fail fast for library callers too, not just the CLI: engine mode
    # consumes (warmup + rounds) run_scan windows of cache headroom,
    # and a mid-benchmark ValueError from run_scan is a worse place to
    # learn that than here
    scans = (_ENGINE_WARMUP + _ENGINE_ROUNDS) if engine else 1
    if prompt_len + steps * scans > max_len:
        raise ValueError(
            f"prompt_len {prompt_len} + {scans} decode windows of "
            f"{steps} steps exceed max_len {max_len}")
    cfg, model, params = build_model_and_params(
        config, max_len, quantized)
    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (batch, prompt_len), 0, cfg.vocab)
    if engine:
        stats = _engine_throughput(model, params, prompt, steps)
    else:
        stats = decode_throughput(model, params, prompt, steps)
    stats["config"] = config
    stats["quantized"] = quantized
    return stats


# scans the engine benchmark actually runs: 1 warmup + the timed rounds
# (run()'s and main()'s headroom guards derive from these — in sync)
_ENGINE_WARMUP = 1
_ENGINE_ROUNDS = 3


def _engine_throughput(model, params, prompt, steps,
                       rounds: int = _ENGINE_ROUNDS):
    """tokens/sec through the continuous-batching engine: *batch*
    requests occupy slots, decode runs as run_scan windows (one
    compiled scan — no per-token host round-trip).  Prefill/admission
    excluded from the timed region, like decode_throughput."""
    import time

    import numpy as np

    from .serving import ServingEngine

    batch, _ = prompt.shape
    eng = ServingEngine(model, params, n_slots=batch)
    prompt_host = np.asarray(prompt)  # ONE transfer, not one per token
    for b in range(batch):
        eng.admit(prompt_host[b].tolist())
    eng.run_scan(steps)  # warm/compile
    best = None
    for _ in range(rounds):
        # fresh depth each round is irrelevant for timing (static
        # shapes); just keep scanning
        t0 = time.perf_counter()
        eng.run_scan(steps)
        dt = time.perf_counter() - t0
        best = dt if best is None or dt < best else best
    return {
        "tokens_per_sec": batch * steps / best,
        "tokens_per_sec_per_seq": steps / best,
        "batch": float(batch),
        "steps": float(steps),
        "engine": True,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tpu-serving-bench")
    p.add_argument("--config", choices=sorted(CONFIGS), default="tiny")
    p.add_argument("--quantized", action="store_true",
                   help="weight-only int8")
    p.add_argument("--int4", action="store_true",
                   help="weight-only int4 (packed; dense configs only)")
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--steps", type=int, default=64)
    p.add_argument("--prompt-len", type=int, default=128)
    p.add_argument("--max-len", type=int, default=512)
    p.add_argument("--engine", action="store_true",
                   help="measure through the continuous-batching "
                        "engine (run_scan) instead of the uniform loop")
    args = p.parse_args(argv)
    scans = (_ENGINE_WARMUP + _ENGINE_ROUNDS) if args.engine else 1
    if args.prompt_len + args.steps * scans > args.max_len:
        p.error("--prompt-len + decode budget must fit in --max-len")

    devs = jax.devices()
    print(f"devices: {len(devs)} x {devs[0].platform}", flush=True)
    if args.int4 and args.quantized:
        p.error("--quantized and --int4 are mutually exclusive")
    quantized = "int4" if args.int4 else args.quantized
    stats = run(args.config, quantized, args.batch, args.steps,
                args.prompt_len, args.max_len, engine=args.engine)
    for k, v in stats.items():
        print(f"{k}: {v}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
