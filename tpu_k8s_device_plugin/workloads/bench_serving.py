"""Pod-runnable serving benchmark: tokens/sec of the native decode
engine (the counterpart of bench_main.py for BASELINE config #5).

Runs the KV-cache decode loop on whatever chips the plugin granted and
prints tokens/sec — e.g. Llama-3-8B weight-only int8 on a single v5e
(the model family the reference's vLLM example deploys, served by the
native engine instead of an opaque image):

    python -m tpu_k8s_device_plugin.workloads.bench_serving \
        --config llama3-8b --quantized --batch 1 --steps 64

Weights are random (throughput moves bytes, not meanings) and are
constructed DIRECTLY in the quantized layout so the 8B config fits on
one 16 GB chip (see llama.random_quantized_params).
"""
# tpulint: disable-file=R1 -- benchmark CLIENT: its raw HTTP calls MEASURE the serving stack (429s/drops are data points); a retry/breaker wrapper here would hide the regressions the bench exists to catch

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp

from . import llama
from .inference import decode_throughput, quantize_lm_params

CONFIGS = {
    "llama3-8b": llama.LLAMA3_8B,
    "llama3-1b": llama.LLAMA32_1B,
    "llama2-7b": llama.LLAMA2_7B,
    "tiny": llama.TINY_LLAMA,
    "tiny-draft": llama.TINY_DRAFT,
}

# the standard draft pairing for --spec (same vocab/tokenizer family)
DRAFT_FOR = {
    "llama3-8b": "llama3-1b",
    "tiny": "tiny-draft",
}


def _train_init(cfg):
    """The ONE train-layout init recipe (shape template and build
    share it, so the restore template can never silently diverge from
    the build path's layout)."""
    train = llama.train_model(cfg)

    def init():
        tokens = jnp.zeros((1, 8), jnp.int32)
        pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
        return train.init(jax.random.PRNGKey(0), tokens, pos)["params"]

    return init


def build_model_and_params(config: str, max_len: int, quantized,
                           mesh=None):
    """Decode model + benchmark-posture params (random weights built
    DIRECTLY in the serving layout) for a named config.  The ONE
    construction recipe shared by this benchmark and the HTTP server
    (workloads/server.py) — a real deployment swaps the random params
    for a checkpoint restored via workloads.checkpoint.

    With *mesh*, every leaf is materialized ALREADY SHARDED onto its
    tensor-parallel placement (jit with out_shardings from an abstract
    tree) — build-then-reshard would peak at the full tree on one
    device, which is exactly what --tp exists to avoid at 8B scale."""
    cfg = CONFIGS[config]
    model = llama.decoder(cfg, max_len=max_len, quantized=quantized)

    def build():
        if quantized == "int4":
            return llama.random_quantized_params(cfg, bits=4)
        if quantized:
            return llama.random_quantized_params(cfg)
        # small configs only: materializes the bf16 tree
        return _train_init(cfg)()

    if mesh is None:
        return cfg, model, build()
    from .transformer import lm_tree_shardings

    shardings = lm_tree_shardings(mesh, jax.eval_shape(build))
    params = jax.jit(build, out_shardings=shardings)()
    return cfg, model, params


def load_checkpoint_params(config: str, max_len: int, quantized,
                           checkpoint_dir: str, step=None, mesh=None):
    """Decode model + REAL params restored from an orbax checkpoint
    (``workloads.checkpoint`` layout, state ``{"params": ...}`` in the
    bf16 TRAIN layout — what a training run saves).  The serving
    handoff quantizes after restore for int8/int4 configs (the same
    recipe tests/test_checkpoint.py::test_quantize_after_restore_serves
    pins).  The restore template is ABSTRACT (eval_shape), so nothing
    is materialized twice; with *mesh* each leaf restores directly
    onto its tensor-parallel placement, and WITHOUT one the bf16 tree
    restores to host memory and only the (possibly quantized) serving
    tree ships to the device — the single-chip quantized configs
    exist precisely because the bf16 tree may not fit HBM."""
    from .checkpoint import restore_checkpoint
    from .inference import quantize_lm_params_int4

    cfg = CONFIGS[config]
    model = llama.decoder(cfg, max_len=max_len, quantized=quantized)
    abstract = jax.eval_shape(_train_init(cfg))
    if mesh is not None:
        # TP: each bf16 leaf restores directly onto its mesh shard
        # (1/N of the tree per chip); quantize runs sharded and the
        # engine re-places the result
        from .transformer import lm_tree_shardings

        shardings = {"params": lm_tree_shardings(mesh, abstract)}
    else:
        # single-chip: the bf16 train tree may exceed HBM for exactly
        # the configs --quantized exists for (8B bf16 ~16 GB on a
        # 16 GB v5e) — restore to HOST memory, quantize there, and
        # ship only the quantized tree to the device
        cpu = jax.sharding.SingleDeviceSharding(
            jax.local_devices(backend="cpu")[0])
        shardings = {"params": jax.tree_util.tree_map(
            lambda _: cpu, abstract)}
    restored = restore_checkpoint(
        checkpoint_dir, step=step, template={"params": abstract},
        shardings=shardings)
    loaded = restored["params"]
    if quantized == "int4":
        params = quantize_lm_params_int4(loaded)
    elif quantized:
        params = quantize_lm_params(loaded)
    else:
        params = loaded
    if mesh is None:
        params = jax.device_put(params, jax.devices()[0])
    return cfg, model, params


def run(config: str, quantized, batch: int, steps: int,
        prompt_len: int, max_len: int, engine: bool = False,
        spec: int = 0, http_clients: int = 0, http_requests: int = 0,
        cancel_every: int = 0, burst: int = 0,
        interleave: bool = True, kv_paging: bool = False,
        tenants: int = 0, packed_prefill: bool = True,
        overlap_dispatch: bool = True, metrics_out=None,
        fused_decode: bool = False):
    # fail fast for library callers too, not just the CLI: engine mode
    # consumes (warmup + rounds) run_scan windows of cache headroom,
    # and a mid-benchmark ValueError from run_scan is a worse place to
    # learn that than here
    if spec:
        # 2 run_scan windows (plain-step reference) + warm + timed
        # spec rounds, each committing at most gamma+1; an exhausted
        # slot would turn timed rounds into no-ops
        budget = 2 * steps + (1 + _ENGINE_ROUNDS) * (spec + 1)
    elif http_clients:
        # the post-load direct-engine comparison is the deep consumer
        budget = steps * (_ENGINE_WARMUP + _ENGINE_ROUNDS)
    else:
        scans = (_ENGINE_WARMUP + _ENGINE_ROUNDS) if engine else 1
        budget = steps * scans
    if prompt_len + budget > max_len:
        raise ValueError(
            f"prompt_len {prompt_len} + decode budget {budget} "
            f"exceed max_len {max_len}")
    cfg, model, params = build_model_and_params(
        config, max_len, quantized)
    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (batch, prompt_len), 0, cfg.vocab)
    if spec:
        draft_name = DRAFT_FOR.get(config)
        if draft_name is None:
            raise ValueError(
                f"no draft pairing for {config} (DRAFT_FOR)")
        _, dmodel, dparams = build_model_and_params(
            draft_name, max_len, quantized)
        stats = _spec_throughput(
            model, params, dmodel, dparams, prompt, spec, steps)
        stats["draft"] = draft_name
    elif http_clients:
        stats = _http_throughput(
            model, params, prompt, steps, http_clients,
            http_requests or 4 * http_clients, slots=batch,
            cancel_every=cancel_every, burst=burst,
            interleave=interleave, kv_paging=kv_paging,
            tenants=tenants, packed_prefill=packed_prefill,
            overlap_dispatch=overlap_dispatch,
            metrics_out=metrics_out, fused_decode=fused_decode)
    elif engine:
        stats = _engine_throughput(model, params, prompt, steps)
    else:
        stats = decode_throughput(model, params, prompt, steps)
    stats["config"] = config
    stats["quantized"] = quantized
    return stats


# scans the engine benchmark actually runs: 1 warmup + the timed rounds
# (run()'s and main()'s headroom guards derive from these — in sync)
_ENGINE_WARMUP = 1
_ENGINE_ROUNDS = 3


def _engine_throughput(model, params, prompt, steps,
                       rounds: int = _ENGINE_ROUNDS):
    """tokens/sec through the continuous-batching engine: *batch*
    requests occupy slots, decode runs as run_scan windows (one
    compiled scan — no per-token host round-trip).  Prefill/admission
    excluded from the timed region, like decode_throughput."""
    import time

    import numpy as np

    from .serving import ServingEngine

    batch, _ = prompt.shape
    eng = ServingEngine(model, params, n_slots=batch)
    prompt_host = np.asarray(prompt)  # ONE transfer, not one per token
    for b in range(batch):
        eng.admit(prompt_host[b].tolist())
    eng.run_scan(steps)  # warm/compile
    best = None
    for _ in range(rounds):
        # fresh depth each round is irrelevant for timing (static
        # shapes); just keep scanning
        t0 = time.perf_counter()
        eng.run_scan(steps)
        dt = time.perf_counter() - t0
        best = dt if best is None or dt < best else best
    return {
        "tokens_per_sec": batch * steps / best,
        "tokens_per_sec_per_seq": steps / best,
        "batch": float(batch),
        "steps": float(steps),
        "engine": True,
    }


def _spec_throughput(model, params, draft_model, draft_params, prompt,
                     gamma, steps, rounds: int = _ENGINE_ROUNDS):
    """Speculative-round economics through the engine.  Random weights
    make the MEASURED accept rate meaningless (~1/vocab), but round
    latency is shape-static — so this reports the measured per-round
    and per-step costs plus the exact implied throughput curve over
    accept rate, and the break-even accept probability:

        E[commit | p] = 1 + sum_{k=1..gamma} p^k
        tokens/sec(p) = batch * E[commit | p] / t_round
        break-even:     E[commit | p*] = t_round / t_step
    """
    import time

    import numpy as np

    from .serving import ServingEngine

    batch, _ = prompt.shape
    eng = ServingEngine(model, params, n_slots=batch,
                        draft=(draft_model, draft_params), gamma=gamma)
    prompt_host = np.asarray(prompt)
    for b in range(batch):
        eng.admit(prompt_host[b].tolist())

    eng.run_scan(steps)  # warm the plain path
    t0 = time.perf_counter()
    eng.run_scan(steps)
    t_step = (time.perf_counter() - t0) / steps

    eng.spec_round()  # warm propose/verify
    best = None
    for _ in range(rounds):
        t0 = time.perf_counter()
        eng.spec_round()
        dt = time.perf_counter() - t0
        best = dt if best is None or dt < best else best

    def commit(p):
        return 1.0 + sum(p ** k for k in range(1, gamma + 1))

    # break-even accept prob: bisect E[commit | p] = t_round / t_step
    ratio = best / t_step
    if ratio <= 1.0:
        breakeven = 0.0
    elif ratio >= commit(1.0):
        breakeven = 1.0
    else:
        lo, hi = 0.0, 1.0
        for _ in range(40):
            mid = (lo + hi) / 2
            lo, hi = (mid, hi) if commit(mid) < ratio else (lo, mid)
        breakeven = (lo + hi) / 2
    out = {
        "spec_round_ms": best * 1e3,
        "plain_step_ms": t_step * 1e3,
        "gamma": float(gamma),
        "batch": float(batch),
        "breakeven_accept": breakeven,
        "measured_accept": eng.accept_rate,  # ~0 on random weights
    }
    for p in (0.5, 0.8, 1.0):
        out[f"tokens_per_sec_at_accept_{p}"] = batch * commit(p) / best
    return out


def _percentile(xs, q):
    xs = sorted(xs)
    if not xs:
        return float("nan")
    i = min(len(xs) - 1, int(round(q * (len(xs) - 1))))
    return xs[i]


def _http_burst(port, n_burst: int, tokens, lock):
    """Backpressure burst phase: *n_burst* simultaneous one-shot
    requests (half stall before reading — the slow-client posture)
    against the server's FIXED pool; overflow must come back as fast
    429 + Retry-After, not new threads.  Returns the status list
    (-1 = connection error/reset)."""
    import http.client
    import json as _json
    import threading
    import time

    statuses = []

    def one(i):
        status = -1
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=60)
            conn.request("POST", "/generate", _json.dumps(
                {"tokens": tokens, "max_new_tokens": 4,
                 "stream": False}),
                {"Content-Type": "application/json"})
            if i % 2:
                time.sleep(0.2)
            resp = conn.getresponse()
            resp.read()
            status = resp.status
            conn.close()
        except OSError:
            pass
        with lock:
            statuses.append(status)

    threads = [threading.Thread(target=one, args=(i,))
               for i in range(n_burst)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    return statuses


def _trace_breakdown(port, traced):
    """Admit→first-token breakdown aggregated over every traced
    request, straight from ``/debug/traces``: mean milliseconds spent
    in the queue, in admission (prefill + splice, possibly overlapped
    with an open decode window), and to the first token.  The
    per-request spans are the same ones `_print_slowest_traces` shows
    for the tail."""
    import http.client
    import json as _json

    sums = {"tpu_serve_queue_wait": [], "tpu_serve_admit": [],
            "tpu_serve_ttft": []}
    for _latency, tid in traced:
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=30)
            conn.request("GET", f"/debug/traces?trace_id={tid}")
            body = _json.loads(conn.getresponse().read())
            conn.close()
        except OSError:
            continue
        per = {}
        for ev in body.get("events", []):
            d = ev.get("attrs", {}).get("duration_s")
            if isinstance(d, (int, float)) and ev["name"] in sums:
                per[ev["name"]] = per.get(ev["name"], 0.0) + d
        for name, v in per.items():
            sums[name].append(v)
    out = {}
    for name, key in (("tpu_serve_queue_wait", "queue_wait_ms_mean"),
                      ("tpu_serve_admit", "admit_ms_mean"),
                      ("tpu_serve_ttft", "ttft_ms_mean")):
        if sums[name]:
            out[key] = 1e3 * sum(sums[name]) / len(sums[name])
    return out


def _print_slowest_traces(port, traced, k=3):
    """The bench explains its own tail: pull the *k* slowest benched
    requests' server-side timelines from ``/debug/traces`` and print
    each one's span breakdown — queue wait vs TTFT vs decode windows vs
    stream writes — so a bad p99 comes with its own diagnosis."""
    import http.client
    import json as _json

    for latency, tid in sorted(traced, reverse=True)[:k]:
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=30)
            conn.request("GET", f"/debug/traces?trace_id={tid}")
            body = _json.loads(conn.getresponse().read())
            conn.close()
        except OSError as e:
            print(f"slow-trace {tid}: /debug/traces failed: {e}",
                  flush=True)
            continue
        sums: dict = {}
        counts: dict = {}
        for ev in body.get("events", []):
            d = ev.get("attrs", {}).get("duration_s")
            if isinstance(d, (int, float)):
                sums[ev["name"]] = sums.get(ev["name"], 0.0) + d
                counts[ev["name"]] = counts.get(ev["name"], 0) + 1
        parts = [f"total={latency * 1e3:.1f}ms"]
        for name, label in (
                ("tpu_serve_queue_wait", "queue_wait"),
                ("tpu_serve_admit", "admit"),
                ("tpu_serve_ttft", "ttft"),
                ("tpu_serve_window", "windows"),
                ("tpu_serve_stream_write", "stream_writes")):
            if name in sums:
                parts.append(
                    f"{label}={sums[name] * 1e3:.1f}ms"
                    + (f"/{counts[name]}x" if counts[name] > 1 else ""))
        print(f"slow-trace {tid}: " + " ".join(parts), flush=True)


def _scrape_metrics_body(port, accept=None):
    """One /metrics scrape as text (plain, or OpenMetrics via
    *accept*)."""
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    headers = {"Accept": accept} if accept else {}
    conn.request("GET", "/metrics", headers=headers)
    body = conn.getresponse().read().decode()
    conn.close()
    return body


def _slo_counts(samples):
    """tpu_slo_requests_total samples -> ({class: total},
    {class: met})."""
    tot, met = {}, {}
    for name, lab, v in samples:
        if name != "tpu_slo_requests_total":
            continue
        c = lab.get("class", "")
        tot[c] = tot.get(c, 0.0) + v
        if lab.get("met") == "true":
            met[c] = met.get(c, 0.0) + v
    return tot, met


def _http_throughput(model, params, prompt, steps, clients,
                     n_requests, slots, cancel_every: int = 0,
                     burst: int = 0, interleave: bool = True,
                     kv_paging: bool = False, tenants: int = 0,
                     packed_prefill: bool = True,
                     overlap_dispatch: bool = True,
                     metrics_out=None, fused_decode: bool = False,
                     sampled: bool = False, logprobs_k: int = 0):
    """Front-door load test (VERDICT r4 #5): *clients* concurrent
    streaming HTTP clients drive *n_requests* total requests (mixed
    priorities; every *cancel_every*-th request disconnects after its
    first token, exercising the release path under load) against a
    live EngineServer.  Reports req/s and p50/p99 TTFT/TPOT as the
    wire sees them — queueing, scheduler windows, and HTTP framing
    included — next to the direct-engine tokens/sec for the same
    model, so the front-door overhead is a number, not a guess."""
    import http.client
    import json as _json
    import threading
    import time

    import numpy as np

    from tpu_k8s_device_plugin import obs

    from . import loadclient
    from .server import EngineServer
    from .serving import ServingEngine

    prompt_host = np.asarray(prompt)
    # the chunk-32 APC alignment this harness used to carry lives in
    # the ENGINE now (prefix_chunk="auto", the ServingEngine default):
    # every caller gets prefix reuse at chunk granularity, not just
    # this bench
    eng = ServingEngine(model, params, n_slots=slots,
                        kv_paging=kv_paging,
                        fused_decode=fused_decode,
                        logprobs_k=logprobs_k)
    # a deliberately SMALL pool/queue: the load phase fits inside it,
    # and the burst phase overflows it — so the measured path is the
    # production admission-control path, not an unbounded one
    # window 16: half the per-window fixed cost of the old 8 for ~13
    # ms of extra worst-case queueing TTFT at tiny-config step rates —
    # the throughput side of the dial for a load benchmark
    tenant_quotas = None
    if tenants:
        from .server import parse_tenant_quotas

        # mixed-priority tenants: tenant-0 is the heavy "batch" lane
        # (weight 1), the rest are interactive lanes at weight 4 — no
        # rate caps, so the phase measures WFQ scheduling, not sheds
        tenant_quotas = parse_tenant_quotas(
            ["tenant-0=0:0:1"]
            + [f"tenant-{i}=0:0:4" for i in range(1, tenants)])
    srv = EngineServer(eng, max_new_tokens=steps, window=16,
                       max_connections=clients + 2,
                       max_queue=max(clients, slots, 4, n_requests
                                     if tenants else 0),
                       interleave=interleave,
                       packed_prefill=packed_prefill,
                       overlap_dispatch=overlap_dispatch,
                       tenant_quotas=tenant_quotas)
    # pre-compile the scheduler's adaptive-window scan variants: each
    # distinct window length is its own XLA compile, and it would
    # otherwise land mid-traffic the first time the batch synchronizes
    srv.warm_scheduler()
    srv.start(host="127.0.0.1", port=0)
    lock = threading.Lock()
    ttfts, tpots, done_tokens, errors = [], [], [], []
    traced = []  # (request latency, trace_id) for the tail breakdown
    cancelled = [0]
    seq = iter(range(n_requests))

    def client_loop(cid):
        while True:
            with lock:
                i = next(seq, None)
            if i is None:
                return
            req_body = {
                "tokens": prompt_host[i % len(prompt_host)].tolist(),
                "max_new_tokens": steps,
                # mixed priorities: odd requests jump the queue
                "priority": i % 2,
                # SLO classes ride the priorities: the queue-jumpers
                # are the interactive (TTFT-target) lane, the rest the
                # batch (deadline) lane — goodput per class comes back
                # out of the server's tpu_slo_* families below
                "slo_class": "interactive" if i % 2 else "batch",
            }
            if tenants:
                # round-robin tenant identities: tenant-0 is the
                # heavy batch lane, the others the interactive lanes
                req_body["tenant"] = f"tenant-{i % tenants}"
            if sampled:
                # SEEDED sampling: deterministic per request (the
                # seeded chain ignores neighbors), yet the windows are
                # sampled — which is exactly the regime the fused
                # decode loop's relaxed overlap guard targets
                req_body["temperature"] = 0.8
                req_body["seed"] = i + 1
            if logprobs_k:
                req_body["logprobs"] = logprobs_k
            # the shared load client stamps a fresh traceparent per
            # benched request (the server-side trace becomes queryable
            # by an id THIS client chose) and executes the abandoner
            # behavior: every cancel_every-th request disconnects
            # after its first streamed frame, mid-stream
            beh = loadclient.ClientBehavior(
                abandon_after_tokens=1 if cancel_every
                and i % cancel_every == cancel_every - 1 else 0)
            res = loadclient.stream_request(
                "127.0.0.1", srv.port, req_body, behavior=beh,
                timeout_s=600)
            with lock:
                if res.outcome == loadclient.OUTCOME_ABANDONED:
                    cancelled[0] += 1
                elif res.outcome == loadclient.OUTCOME_OK:
                    if res.ttft_s is not None:
                        ttfts.append(res.ttft_s)
                    if res.tpot_s is not None:
                        tpots.append(res.tpot_s)
                    done_tokens.append(res.done_tokens)
                    traced.append((res.total_s, res.trace_id))
                else:
                    # errored requests must not vanish from the stats
                    # (clean-looking numbers over a broken run would
                    # be worse than no numbers)
                    errors.append(res.error or res.outcome)

    try:
        # warm the compiled paths outside the timed region (first
        # window compile would otherwise dominate every percentile);
        # TWICE with the same prompt: the second admit hits the
        # automatic prefix cache, compiling the donor-splice +
        # tail-extend shapes the timed repeats rely on
        def _warm_one(i):
            warm = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=600)
            warm_body = {
                "tokens": prompt_host[i % len(prompt_host)].tolist(),
                "max_new_tokens": steps, "stream": False}
            if sampled:
                # the sampled scan variant is its own XLA compile;
                # warm it here, not under the timed percentiles
                warm_body["temperature"] = 0.8
                warm_body["seed"] = 1
            if logprobs_k:
                warm_body["logprobs"] = logprobs_k
            warm.request("POST", "/generate", _json.dumps(warm_body),
                         {"Content-Type": "application/json"})
            warm.getresponse().read()
            warm.close()

        for _ in range(2):
            _warm_one(0)
        # ... and ONCE concurrently at full width: the iteration
        # scheduler's adaptive window sizes are each their own
        # compiled scan (quantized multiples of the floor), and every
        # distinct prompt's first admission is a cold prefill — both
        # belong to warmup, not to the timed percentiles
        warm_threads = [threading.Thread(target=_warm_one, args=(i,))
                        for i in range(slots)]
        for t in warm_threads:
            t.start()
        for t in warm_threads:
            t.join()
        # post-warmup snapshot: the timed phase's prefill/decode split
        # is reported as DELTAS against this (warmup prefills are
        # compile fodder, not workload); same for the SLO counters —
        # warmup requests must not inflate the goodput numbers
        stats_warm = srv.stats()
        slo_base_tot, slo_base_met = _slo_counts(
            obs.parse_exposition(_scrape_metrics_body(srv.port)))

        t_start = time.perf_counter()
        threads = [threading.Thread(target=client_loop, args=(c,))
                   for c in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t_start
        # timed-phase snapshot BEFORE the burst phase: the
        # prefill/decode split must not absorb burst-request prefills
        # (nor the goodput accounting the burst's deliberate 429s)
        stats_load = srv.stats()
        slo_load_tot, slo_load_met = _slo_counts(
            obs.parse_exposition(_scrape_metrics_body(srv.port)))
        burst_statuses = []
        if burst:
            burst_statuses = _http_burst(
                srv.port, burst, prompt_host[0].tolist(), lock)
        server_stats = srv.stats()
        # scrape the PR 3 latency histograms over the wire: the
        # reported percentiles come from /metrics itself, so the bench
        # validates the series a production dashboard would read
        metrics_body = _scrape_metrics_body(srv.port)
        if metrics_out:
            # both exposition modes to disk so CI can promlint the
            # exact bytes a production scrape would see (the smoke
            # gate for the tpu_slo_* / window-phase families)
            with open(metrics_out, "w") as f:
                f.write(metrics_body)
            with open(metrics_out + ".om", "w") as f:
                f.write(_scrape_metrics_body(
                    srv.port, accept=obs.OPENMETRICS_CONTENT_TYPE))
        # the tail explained: span breakdowns for the 3 slowest traced
        # requests, straight from the server's flight recorder — plus
        # the admit→first-token means over EVERY traced request
        _print_slowest_traces(srv.port, traced)
        breakdown = _trace_breakdown(srv.port, traced)
    finally:
        # a failure mid-bench must not leak the live server/engine
        # into the rest of the process
        srv.stop()
    if errors and not done_tokens:
        raise RuntimeError(
            f"every request errored; first: {errors[0]}")

    # the direct-engine ceiling for the same shapes: batch = slot count
    eng_stats = _engine_throughput(
        model, params,
        jnp.broadcast_to(prompt[:1], (slots, prompt.shape[1])), steps)
    http_tps = sum(done_tokens) / wall
    out = {
        "http": True,
        "clients": float(clients),
        "slots": float(slots),
        "requests_completed": float(len(done_tokens)),
        "requests_cancelled": float(cancelled[0]),
        # the abandonment is now visible on BOTH sides of the wire:
        # the client reports its deliberate disconnects as a terminal
        # outcome, and the server's journal/counter must agree
        # (tpu_serve_client_abandons_total, read back off /stats)
        "requests_abandoned": float(cancelled[0]),
        "server_client_abandons": float(
            server_stats.get("client_abandons", 0)),
        "requests_errored": float(len(errors)),
        "req_per_sec": len(done_tokens) / wall,
        "ttft_ms_p50": _percentile(ttfts, 0.5) * 1e3,
        "ttft_ms_p99": _percentile(ttfts, 0.99) * 1e3,
        "tpot_ms_p50": _percentile(tpots, 0.5) * 1e3,
        "tpot_ms_p99": _percentile(tpots, 0.99) * 1e3,
        "tokens_per_sec_http": http_tps,
        "tokens_per_sec_engine": eng_stats["tokens_per_sec"],
        # goodput (ROADMAP: the headline NEXT TO tokens/sec):
        # requests/sec meeting their class SLO over the timed phase,
        # sourced from the server's tpu_slo_requests_total deltas —
        # the same families the router's /fleet/statz aggregates
        "goodput_req_per_sec": sum(
            slo_load_met.get(c, 0.0) - slo_base_met.get(c, 0.0)
            for c in slo_load_tot) / wall,
        "front_door_overhead_pct":
            100.0 * (1.0 - http_tps / eng_stats["tokens_per_sec"]),
        "http_over_engine_ratio":
            http_tps / eng_stats["tokens_per_sec"],
        # prefill/decode split for the TIMED phase (warmup excluded):
        # decode tokens/s is the emitted-token rate above; prefill
        # tokens/s is how much prompt prefill the same wall clock
        # absorbed (APC-discounted — full-prompt cache hits prefill 0)
        "decode_tokens_per_sec": http_tps,
        "prefill_tokens_per_sec":
            (stats_load.get("prefill_tokens", 0)
             - stats_warm.get("prefill_tokens", 0)) / wall,
        "prefix_cache_hits": float(
            stats_load.get("prefix_cache_hits", 0)
            - stats_warm.get("prefix_cache_hits", 0)),
        "prefix_reused_tokens": float(
            stats_load.get("prefix_reused_tokens", 0)
            - stats_warm.get("prefix_reused_tokens", 0)),
        # ragged packed prefill + dispatch overlap telemetry (timed
        # phase deltas; zeros when the toggles are off)
        "packed_prefill": float(packed_prefill),
        "overlap_dispatch": float(overlap_dispatch),
        "packed_prefill_requests": float(
            stats_load.get("packed_prefill_requests", 0)
            - stats_warm.get("packed_prefill_requests", 0)),
        "packed_prefill_extends": float(
            stats_load.get("packed_prefill_extends", 0)
            - stats_warm.get("packed_prefill_extends", 0)),
        "packed_prefill_pad_tokens": float(
            stats_load.get("packed_prefill_pad_tokens", 0)
            - stats_warm.get("packed_prefill_pad_tokens", 0)),
        # fused decode loop telemetry (timed-phase deltas; zeros when
        # the toggle is off): windows run with the on-device boundary
        # carry, and tokens the vectorized harvest discarded past a
        # device-detected finish
        "fused_decode": float(fused_decode),
        "fused_windows": float(
            stats_load.get("fused_windows", 0)
            - stats_warm.get("fused_windows", 0)),
        "fused_truncated_tokens": float(
            stats_load.get("fused_truncated_tokens", 0)
            - stats_warm.get("fused_truncated_tokens", 0)),
    }
    # per-class goodput next to the tokens/sec headline: met/sec and
    # the met fraction for every class the timed phase touched
    for c in sorted(slo_load_tot):
        t = slo_load_tot[c] - slo_base_tot.get(c, 0.0)
        if t <= 0:
            continue
        m = slo_load_met.get(c, 0.0) - slo_base_met.get(c, 0.0)
        out[f"goodput_{c}_req_per_sec"] = m / wall
        out[f"goodput_{c}_ratio"] = m / t
    if kv_paging:
        # KV pool economics straight off the production surfaces: the
        # /metrics families a dashboard reads plus /stats occupancy —
        # occupancy and sharing say how far the pool dedupes the
        # repeated-prompt workload, preemptions/CoW say what the
        # pressure policy actually did
        total = max(1, server_stats.get("kv_pages", 0))
        used = total - server_stats.get("kv_pages_free", 0)
        out.update({
            "kv_paging": True,
            "kv_pages_total": float(server_stats.get("kv_pages", 0)),
            "kv_pool_occupancy": used / total,
            "kv_shared_page_ratio":
                server_stats.get("kv_pages_shared", 0) / max(1, used),
            "kv_cow_copies": float(server_stats.get(
                "kv_cow_copies", 0)),
            "kv_preemptions": float(server_stats.get(
                "kv_preemptions", 0)),
            "prefix_evictions": float(server_stats.get(
                "prefix_evictions", 0)),
        })
    if tenants:
        out["tenants"] = float(tenants)
    out.update(breakdown)
    # server-side percentiles, estimated from the scraped histogram
    # buckets (what PromQL histogram_quantile would show a dashboard)
    hist_samples = obs.parse_exposition(metrics_body)
    for key, hname in (("hist_ttft", "tpu_serve_ttft_seconds"),
                       ("hist_tpot", "tpu_serve_token_seconds"),
                       ("hist_request", "tpu_serve_request_seconds"),
                       ("hist_admit_to_first_step",
                        "tpu_serve_admit_to_first_step_seconds")):
        for q, tag in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
            v = obs.histogram_quantile(hist_samples, hname, q)
            if v == v:  # NaN = series absent (no samples)
                out[f"{key}_ms_{tag}"] = v * 1e3
    # mean host-side harvest cost per scheduler window, straight off
    # the tpu_serve_window_phase_seconds histogram — the fused loop's
    # vectorized harvest should move exactly this number
    ph_sum = sum(v for n, lbl, v in hist_samples
                 if n == "tpu_serve_window_phase_seconds_sum"
                 and lbl.get("phase") == "harvest")
    ph_cnt = sum(v for n, lbl, v in hist_samples
                 if n == "tpu_serve_window_phase_seconds_count"
                 and lbl.get("phase") == "harvest")
    if ph_cnt > 0:
        out["harvest_ms_per_window"] = ph_sum / ph_cnt * 1e3
    if burst:
        out.update({
            "burst_requests": float(burst),
            "burst_ok": float(
                sum(s == 200 for s in burst_statuses)),
            "burst_429": float(
                sum(s == 429 for s in burst_statuses)),
            "burst_errors": float(
                sum(s not in (200, 429) for s in burst_statuses)),
            # server-side shed accounting (429s at accept + heap)
            "connections_rejected": float(
                server_stats.get("connections_rejected", 0)),
            "requests_throttled": float(
                server_stats.get("requests_throttled", 0)),
            "http_workers": float(
                server_stats.get("http_workers", 0)),
        })
    return out


def _free_port() -> int:
    # kept as a name (chaos_soak and older callers import it); the
    # implementation lives with the shared load client now
    from .loadclient import free_port

    return free_port()


def _wait_http_ok(port, path, timeout_s, predicate=None):
    """Poll GET path until 200 (and *predicate*(json) when given)."""
    from .loadclient import wait_http_ok

    return wait_http_ok(port, path, timeout_s, predicate)


def _spawn_replica(config, quantized, idx, port, router_port, slots,
                   steps, prompt_len, max_len, role=None,
                   kv_paging=False):
    """One serving replica subprocess through the REAL CLI (the same
    path a pod runs), self-registering with the router.  *role* +
    *kv_paging* spawn a disaggregated-class replica (prefill/decode
    roles require the paged pool — migration is preempt/resume)."""
    import os
    import subprocess
    import sys

    cmd = [
        sys.executable, "-m",
        "tpu_k8s_device_plugin.workloads.server",
        "--config", config,
        "--n-slots", str(slots),
        "--max-len", str(max_len),
        "--max-new-tokens", str(steps),
        "--window", "16",
        "--host", "127.0.0.1", "--port", str(port),
        "--register-with", f"http://127.0.0.1:{router_port}",
        "--replica-id", f"replica-{idx}",
        "--register-interval", "0.5",
    ]
    if kv_paging or role not in (None, "mixed"):
        cmd.append("--kv-paging")
    if role is not None:
        cmd += ["--replica-role", role]
    if quantized == "int4":
        cmd.append("--int4")
    elif quantized:
        cmd.append("--quantized")
    return subprocess.Popen(
        cmd, env=dict(os.environ),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def _router_load(router_port, prompts, steps, clients, n_requests,
                 lock):
    """Drive *n_requests* streaming requests (round-robin over
    *prompts* — repeats are the affinity workload) through the router
    with *clients* concurrent clients.  Returns (wall, done_tokens,
    statuses, errors)."""
    import threading
    import time

    from . import loadclient

    done_tokens, statuses, errors = [], [], []
    seq = iter(range(n_requests))

    def client_loop():
        while True:
            with lock:
                i = next(seq, None)
            if i is None:
                return
            res = loadclient.stream_request(
                "127.0.0.1", router_port,
                {"tokens": prompts[i % len(prompts)],
                 "max_new_tokens": steps},
                timeout_s=600)
            with lock:
                if res.outcome == loadclient.OUTCOME_OK:
                    done_tokens.append(res.done_tokens)
                elif res.error is not None:
                    # in-band error frames, sheds, and transport
                    # failures all land here — the phases gate on it
                    errors.append(res.error)
                statuses.append(res.status)

    t0 = time.perf_counter()
    threads = [threading.Thread(target=client_loop)
               for _ in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=900)
    return time.perf_counter() - t0, done_tokens, statuses, errors


def run_router(config, quantized, n_replicas, clients, n_requests,
               slots, steps, prompt_len, max_len, kill=False,
               seed=0):
    """Multi-replica mode: N replica subprocesses (the real
    ``workloads.server`` CLI, self-registering) behind an in-process
    ``workloads.router`` tier.  Phase 1 measures aggregate tokens/sec
    through the router with ONE replica, phase 2 with all N — the
    ratio is the scaling number the router-smoke CI job gates.  Also
    reports per-replica request share and the affinity hit rate from
    the router's own /metrics, and (with *kill*) SIGKILLs a replica
    and proves the survivors absorb the follow-on traffic with zero
    non-429 errors."""
    import http.client
    import json as _json
    import random
    import threading
    import time

    from tpu_k8s_device_plugin import obs

    from .router import RouterServer

    if n_requests < 2 * n_replicas:
        raise ValueError(
            f"--requests {n_requests} too small for --router "
            f"{n_replicas} (need >= {2 * n_replicas})")
    from .router import affinity_key

    cfg = CONFIGS[config]
    rng = random.Random(seed)
    # a handful of DISTINCT prompts, each repeated many times: the
    # affinity workload (repeat traffic must pin to the replica whose
    # KV pool is already warm).  The set is BALANCED over the ring —
    # every replica id gets the same number of affine prompts (the
    # ring depends only on the ids, so a throwaway router computes the
    # mapping before any replica exists) — so the scaling measurement
    # reflects the router, not one seed's hash luck
    n_prompts = max(2, 2 * n_replicas)
    probe = RouterServer()
    for i in range(n_replicas):
        probe.register({"address": f"127.0.0.1:{9000 + i}",
                        "replica_id": f"replica-{i}"})
    want = {f"replica-{i}": n_prompts // n_replicas
            for i in range(n_replicas)}
    prompts = []
    while sum(want.values()):
        cand = [rng.randrange(1, cfg.vocab)
                for _ in range(prompt_len)]
        target = probe.affinity_target(
            affinity_key({"tokens": cand}, probe.prefix_chunk))
        if want.get(target, 0):
            want[target] -= 1
            prompts.append(cand)
    lock = threading.Lock()
    rt = RouterServer(statz_interval_s=0.25, replica_ttl_s=5.0,
                      breaker_reset_s=1.0, seed=seed)
    rt.start(host="127.0.0.1", port=0)
    procs = []
    out = {"router": True, "replicas": float(n_replicas)}

    def scrape_router():
        conn = http.client.HTTPConnection("127.0.0.1", rt.port,
                                          timeout=10)
        conn.request("GET", "/metrics")
        body = conn.getresponse().read().decode()
        conn.close()
        return obs.parse_exposition(body)

    try:
        # -- phase 1: one replica through the router ------------------
        port0 = _free_port()
        procs.append(_spawn_replica(
            config, quantized, 0, port0, rt.port, slots, steps,
            prompt_len, max_len))
        _wait_http_ok(port0, "/healthz", 600)
        _wait_http_ok(
            rt.port, "/replicas", 30,
            lambda b: sum(r["healthy"] for r in b["replicas"]) >= 1)
        # warm every prompt through the router (compile + APC donor)
        _router_load(rt.port, prompts, steps, min(clients, 4),
                     len(prompts), lock)
        wall, toks, statuses, errors = _router_load(
            rt.port, prompts, steps, clients, n_requests, lock)
        if errors:
            raise RuntimeError(
                f"single-replica phase errored: {errors[0]}")
        tps_1 = sum(toks) / wall
        out["tokens_per_sec_router_1"] = tps_1
        out["requests_completed_1"] = float(len(toks))
        if n_replicas > 1:
            # -- phase 2: the full fleet ------------------------------
            for idx in range(1, n_replicas):
                procs.append(_spawn_replica(
                    config, quantized, idx, _free_port(), rt.port,
                    slots, steps, prompt_len, max_len))
            _wait_http_ok(
                rt.port, "/replicas", 600,
                lambda b: sum(r["healthy"] for r in b["replicas"])
                >= n_replicas)
            # re-warm: prompts re-mapped onto the grown ring, and each
            # replica's first window sizes still need compiling
            _router_load(rt.port, prompts, steps, min(clients, 4),
                         2 * len(prompts), lock)
            base = scrape_router()
            base_req = {
                lab.get("replica"): v for n, lab, v in base
                if n == "tpu_router_requests_total"
                and lab.get("outcome") == "ok"}
            base_aff = sum(
                v for n, lab, v in base
                if n == "tpu_router_affinity_hits_total")
            wall, toks, statuses, errors = _router_load(
                rt.port, prompts, steps, clients, n_requests, lock)
            if errors:
                raise RuntimeError(
                    f"router phase errored: {errors[0]}")
            tps_n = sum(toks) / wall
            out["tokens_per_sec_router_n"] = tps_n
            out["requests_completed_n"] = float(len(toks))
            out["scaling_x"] = tps_n / tps_1
            out["scaling_efficiency"] = tps_n / tps_1 / n_replicas
            samples = scrape_router()
            served = {
                lab.get("replica"): v - base_req.get(
                    lab.get("replica"), 0.0)
                for n, lab, v in samples
                if n == "tpu_router_requests_total"
                and lab.get("outcome") == "ok"}
            total_ok = sum(served.values()) or 1.0
            for rid in sorted(served):
                out[f"share_{rid}"] = served[rid] / total_ok
            aff = sum(v for n, lab, v in samples
                      if n == "tpu_router_affinity_hits_total")
            out["affinity_hit_rate"] = (aff - base_aff) / total_ok
            # the fleet snapshot must aggregate EVERY replica: the
            # router-smoke CI job gates on this (a replica missing
            # from /fleet/statz is invisible to the autoscaler)
            conn = http.client.HTTPConnection(
                "127.0.0.1", rt.port, timeout=10)
            conn.request("GET", "/fleet/statz")
            fleet = _json.loads(conn.getresponse().read())
            conn.close()
            out["fleet_statz_replicas"] = float(fleet["replicas"])
            out["fleet_statz_healthy"] = float(fleet["healthy"])
            out["fleet_capacity"] = float(
                fleet["fleet"]["capacity"])
            goodput = fleet["fleet"].get("goodput", {})
            out["fleet_goodput_rps"] = float(sum(
                row.get("goodput_rps", 0.0)
                for row in goodput.values()))
            if fleet["replicas"] != n_replicas or \
                    len(fleet["per_replica"]) != n_replicas:
                raise RuntimeError(
                    f"/fleet/statz aggregates "
                    f"{fleet['replicas']} replica(s), expected "
                    f"{n_replicas}")
            if fleet["fleet"]["capacity"] != n_replicas * slots:
                raise RuntimeError(
                    "/fleet/statz capacity "
                    f"{fleet['fleet']['capacity']} != "
                    f"{n_replicas} x {slots} slots")
        if kill:
            # -- kill phase: SIGKILL one replica, survivors absorb ----
            victim = procs[-1]
            victim.kill()
            victim.wait(timeout=30)
            t0 = time.perf_counter()
            _w, ktoks, kstatuses, kerrors = _router_load(
                rt.port, prompts, steps, min(clients, 4),
                4 * max(1, n_replicas - 1), lock)
            out["kill_requests"] = float(len(kstatuses))
            out["kill_ok"] = float(
                sum(s == 200 for s in kstatuses))
            out["kill_429"] = float(
                sum(s == 429 for s in kstatuses))
            out["kill_errors"] = float(
                sum(s not in (200, 429) for s in kstatuses)
                + len(kerrors))
            out["kill_recovery_s"] = time.perf_counter() - t0
            samples = scrape_router()
            out["failovers_total"] = sum(
                v for n, lab, v in samples
                if n == "tpu_router_failovers_total")
    finally:
        rt.stop()
        import subprocess

        for proc in procs:
            proc.kill()
        for proc in procs:
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass
    out["config"] = config
    out["quantized"] = quantized
    return out


def _disagg_load(router_port, long_prompts, short_prompts, steps,
                 clients, n_requests, lock):
    """Mixed-phase load through the router: even request ids are
    long-prefill UNARY completions (the interference source), odd ids
    short-prompt STREAMING decodes (the interference victim).
    Returns (wall, unary_lat_s, ttft_s, tpot_s, statuses, errors) —
    TTFT is request-start to the first streamed line, TPOT the
    per-token gap over the rest of the stream."""
    import threading
    import time

    from . import loadclient

    unary_lat, ttfts, tpots = [], [], []
    statuses, errors = [], []
    seq = iter(range(n_requests))

    def client_loop():
        while True:
            with lock:
                i = next(seq, None)
            if i is None:
                return
            if i % 2 == 0:
                res = loadclient.unary_request(
                    "127.0.0.1", router_port,
                    {"tokens": long_prompts[
                        (i // 2) % len(long_prompts)],
                     "max_new_tokens": max(4, steps // 4),
                     "stream": False},
                    timeout_s=600)
                with lock:
                    if res.outcome == loadclient.OUTCOME_TRANSPORT:
                        errors.append(res.error)
                        continue
                    statuses.append(res.status)
                    if res.outcome == loadclient.OUTCOME_OK:
                        unary_lat.append(res.total_s)
                    elif res.error is not None and res.status == 200:
                        errors.append(res.error)
            else:
                res = loadclient.stream_request(
                    "127.0.0.1", router_port,
                    {"tokens": short_prompts[
                        (i // 2) % len(short_prompts)],
                     "max_new_tokens": steps,
                     "ignore_eos": True},
                    timeout_s=600)
                with lock:
                    if res.outcome == loadclient.OUTCOME_TRANSPORT:
                        errors.append(res.error)
                        continue
                    statuses.append(res.status)
                    if res.outcome != loadclient.OUTCOME_OK \
                            and res.error is not None:
                        errors.append(res.error)
                    elif res.ttft_s is not None:
                        ttfts.append(res.ttft_s)
                        if res.tpot_s is not None:
                            tpots.append(res.tpot_s)

    t0 = time.perf_counter()
    threads = [threading.Thread(target=client_loop)
               for _ in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=900)
    return (time.perf_counter() - t0, unary_lat, ttfts, tpots,
            statuses, errors)


def run_disagg(config, quantized, clients, n_requests, slots, steps,
               prompt_len, max_len, seed=0):
    """Disaggregated prefill/decode A/B (the ROADMAP router-v2 gate):
    the SAME mixed traffic — long-prefill unary completions
    interleaved with short-prompt streaming decodes — once against 2
    homogeneous mixed replicas and once against a prefill+decode pair
    with phase-aware routing + KV migration.  Reports decode TTFT p99
    and decode TPOT p99 per arm: on the homogeneous arm long prefills
    contend with decode windows on whichever replica the ring picks;
    on the disagg arm decode streams run on a replica that never
    prefills a long prompt."""
    import http.client
    import random
    import threading
    import time

    from tpu_k8s_device_plugin import obs

    from .router import RouterServer

    cfg = CONFIGS[config]
    long_len = min(max_len - steps - 8, max(64, prompt_len * 4))
    if long_len < 32:
        raise ValueError(
            f"--max-len {max_len} leaves no room for a long-prefill "
            "phase (need >= 32 prompt tokens + the decode budget)")
    short_len = max(4, prompt_len // 4)
    rng = random.Random(seed)
    # DISTINCT long prompts: every one pays a full prefill (no APC
    # dedupe) — that cost is exactly what the phase split relocates
    long_prompts = [
        [rng.randrange(1, cfg.vocab) for _ in range(long_len)]
        for _ in range(max(2, (n_requests + 1) // 2))]
    short_prompts = [
        [rng.randrange(1, cfg.vocab) for _ in range(short_len)]
        for _ in range(4)]
    lock = threading.Lock()
    out = {"disagg": True, "long_prompt_len": float(long_len),
           "short_prompt_len": float(short_len),
           "config": config, "quantized": quantized}

    def run_arm(arm):
        rt = RouterServer(statz_interval_s=0.25, replica_ttl_s=5.0,
                          breaker_reset_s=1.0, seed=seed,
                          prefill_threshold=long_len)
        rt.start(host="127.0.0.1", port=0)
        roles = (("prefill", "decode") if arm == "disagg"
                 else ("mixed", "mixed"))
        procs = []
        try:
            for i, role in enumerate(roles):
                procs.append(_spawn_replica(
                    config, quantized, i, _free_port(), rt.port,
                    slots, steps, prompt_len, max_len, role=role,
                    kv_paging=True))
            _wait_http_ok(
                rt.port, "/replicas", 600,
                lambda b: sum(r["healthy"]
                              for r in b["replicas"]) >= 2)
            # warm both request classes (window compiles, packed
            # shapes, the migration path itself)
            _disagg_load(rt.port, long_prompts[:2], short_prompts,
                         steps, min(clients, 4), 8, lock)
            wall, unary, ttfts, tpots, statuses, errors = \
                _disagg_load(rt.port, long_prompts, short_prompts,
                             steps, clients, n_requests, lock)
            if errors:
                raise RuntimeError(f"{arm} arm errored: {errors[0]}")
            if not ttfts or not tpots or not unary:
                raise RuntimeError(
                    f"{arm} arm produced no complete samples "
                    f"(statuses: {statuses[:8]})")
            res = {
                f"requests_ok_{arm}": float(
                    sum(s == 200 for s in statuses)),
                f"wall_s_{arm}": wall,
                f"long_unary_p99_ms_{arm}":
                    _percentile(unary, 0.99) * 1000.0,
                f"decode_ttft_p99_ms_{arm}":
                    _percentile(ttfts, 0.99) * 1000.0,
                f"decode_tpot_p99_ms_{arm}":
                    _percentile(tpots, 0.99) * 1000.0,
            }
            if arm == "disagg":
                conn = http.client.HTTPConnection(
                    "127.0.0.1", rt.port, timeout=10)
                conn.request("GET", "/metrics")
                samples = obs.parse_exposition(
                    conn.getresponse().read().decode())
                conn.close()
                res["migrations_ok"] = sum(
                    v for n, lab, v in samples
                    if n == "tpu_router_migrations_total"
                    and lab.get("outcome") == "ok")
                ships = [v for n, lab, v in samples
                         if n == "tpu_router_migrate_seconds_sum"]
                counts = [v for n, lab, v in samples
                          if n == "tpu_router_migrate_seconds_count"]
                if counts and counts[0]:
                    res["migrate_mean_ms"] = (
                        ships[0] / counts[0] * 1000.0)
            return res
        finally:
            rt.stop()
            import subprocess

            for proc in procs:
                proc.kill()
            for proc in procs:
                try:
                    proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    pass

    out.update(run_arm("homog"))
    out.update(run_arm("disagg"))
    if out.get("migrations_ok", 0) < 1:
        raise RuntimeError(
            "disagg arm routed no migration — the phase split never "
            "engaged (check roles/threshold)")
    out["ttft_p99_ratio"] = (out["decode_ttft_p99_ms_disagg"]
                             / out["decode_ttft_p99_ms_homog"])
    out["tpot_p99_ratio"] = (out["decode_tpot_p99_ms_disagg"]
                             / out["decode_tpot_p99_ms_homog"])
    return out


def run_prefill_heavy(config, quantized, clients, n_requests, slots,
                      steps, prompt_len, max_len):
    """Prefill-dominated A/B: long DISTINCT prompts (no APC dedupe)
    with short outputs, once with ragged packing + dispatch overlap ON
    and once OFF over the same model and load.  This is the residual
    BASELINE §ROUND-6 regime — admission cost, not decode, is the
    bill — so the delta is the packed-prefill/overlap win isolated
    from everything the interleave already fixed.  Reports both arms'
    prefill tok/s, HTTP/engine ratio, and the admit→first-token
    breakdown, plus the ON/OFF speedup."""
    budget = steps * (_ENGINE_WARMUP + _ENGINE_ROUNDS)
    if prompt_len + budget > max_len:
        raise ValueError(
            f"prompt_len {prompt_len} + decode budget {budget} "
            f"exceed max_len {max_len}")
    cfg, model, params = build_model_and_params(
        config, max_len, quantized)
    # one DISTINCT prompt per request: prefill every time, pack when
    # concurrent — the workload the packed path exists for
    prompt = jax.random.randint(
        jax.random.PRNGKey(7), (max(n_requests, clients), prompt_len),
        0, cfg.vocab)
    out = {"prefill_heavy": True, "config": config,
           "quantized": quantized, "prompt_len": float(prompt_len),
           "steps": float(steps)}
    for tag, on in (("off", False), ("on", True)):
        arm = _http_throughput(
            model, params, prompt, steps, clients, n_requests,
            slots=slots, packed_prefill=on, overlap_dispatch=on)
        for key in ("prefill_tokens_per_sec", "tokens_per_sec_http",
                    "http_over_engine_ratio", "ttft_ms_p50",
                    "ttft_ms_p99", "req_per_sec", "admit_ms_mean",
                    "queue_wait_ms_mean", "ttft_ms_mean",
                    "packed_prefill_requests",
                    "packed_prefill_extends",
                    "packed_prefill_pad_tokens"):
            if key in arm:
                out[f"{key}_{tag}"] = arm[key]
    base = out.get("prefill_tokens_per_sec_off", 0.0)
    if base > 0:
        out["prefill_speedup_x"] = (
            out.get("prefill_tokens_per_sec_on", 0.0) / base)
    if out.get("req_per_sec_off", 0.0) > 0:
        out["req_per_sec_speedup_x"] = (
            out.get("req_per_sec_on", 0.0) / out["req_per_sec_off"])
    return out


def run_decode_heavy(config, quantized, clients, n_requests, slots,
                     steps, prompt_len, max_len):
    """Decode-dominated A/B: SHORT prompts with LONG seeded-sampled
    outputs, once with the fused decode loop ON and once OFF over the
    same model and load.  This is the inverse of run_prefill_heavy —
    per-token harvest cost and the sampled-window overlap stand-down,
    not admission, are the bill — so the delta isolates the on-device
    boundary carry + vectorized harvest win.  Reports both arms' TPOT
    percentiles, harvest-ms per window (from the server's
    tpu_serve_window_phase_seconds{phase="harvest"} histogram), and
    the ON/OFF tokens/sec speedup."""
    budget = steps * (_ENGINE_WARMUP + _ENGINE_ROUNDS)
    if prompt_len + budget > max_len:
        raise ValueError(
            f"prompt_len {prompt_len} + decode budget {budget} "
            f"exceed max_len {max_len}")
    cfg, model, params = build_model_and_params(
        config, max_len, quantized)
    # one DISTINCT short prompt per request: decode dominates, and
    # every window is sampled (seeded per request, so both arms see
    # byte-identical token streams — the A/B measures the loop, not
    # divergent generations)
    prompt = jax.random.randint(
        jax.random.PRNGKey(11),
        (max(n_requests, clients), prompt_len), 0, cfg.vocab)
    out = {"decode_heavy": True, "config": config,
           "quantized": quantized, "prompt_len": float(prompt_len),
           "steps": float(steps)}
    for tag, on in (("off", False), ("on", True)):
        # best-of-2 per arm: wall-clock noise on a shared host easily
        # swamps a ~10% loop-level delta in a single pass, and the
        # quantity under test is each arm's CAPABILITY, not one
        # scheduler run's luck
        # logprobs ride every request: top-k harvest per emitted token
        # is the host-side cost the fused loop's bulk path vectorizes,
        # and the regime where the per-step loop actually hurts
        arm = max((_http_throughput(
            model, params, prompt, steps, clients, n_requests,
            slots=slots, sampled=True, fused_decode=on,
            logprobs_k=4)
            for _ in range(2)),
            key=lambda a: a["tokens_per_sec_http"])
        for key in ("tokens_per_sec_http", "http_over_engine_ratio",
                    "tpot_ms_p50", "tpot_ms_p99", "ttft_ms_p50",
                    "req_per_sec", "harvest_ms_per_window",
                    "fused_windows", "fused_truncated_tokens"):
            if key in arm:
                out[f"{key}_{tag}"] = arm[key]
    base = out.get("tokens_per_sec_http_off", 0.0)
    if base > 0:
        out["fused_speedup_x"] = (
            out.get("tokens_per_sec_http_on", 0.0) / base)
    # the decode-LOOP speedup, isolated: per-window harvest time off
    # vs on.  On a CPU proxy the forward pass is host-bound, so the
    # loop win lands here rather than in wall tokens/sec — this is
    # the gateable number; fused_speedup_x rides along for real
    # accelerators, where overlap + on-device early exit dominate
    hbase = out.get("harvest_ms_per_window_on", 0.0)
    if hbase > 0 and "harvest_ms_per_window_off" in out:
        out["harvest_speedup_x"] = (
            out["harvest_ms_per_window_off"] / hbase)
    return out


def _spawn_server(config, quantized, port, slots, steps, max_len,
                  extra):
    """One serving subprocess through the REAL CLI (the path a pod
    runs), no router — the cold-start phase's replica."""
    import os
    import subprocess
    import sys

    cmd = [
        sys.executable, "-m",
        "tpu_k8s_device_plugin.workloads.server",
        "--config", config,
        "--n-slots", str(slots),
        "--max-len", str(max_len),
        "--max-new-tokens", str(steps),
        "--window", "16",
        "--host", "127.0.0.1", "--port", str(port),
    ] + list(extra)
    if quantized == "int4":
        cmd.append("--int4")
    elif quantized:
        cmd.append("--quantized")
    return subprocess.Popen(
        cmd, env=dict(os.environ),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def run_cold_start(config, quantized, slots, steps, prompt_len,
                   max_len, cache_dir=None):
    """Replica cold-start economics: boot the real server CLI twice
    against ONE ``--compile-cache-dir`` — the first boot compiles and
    fills the cache (cold), the second loads executables from it
    (warm) — timing spawn → first successful completion each time.
    The warm boot MUST be measurably faster (asserted by the CLI exit
    code): that delta is what makes router-driven autoscaling real,
    because a scale-up replica that pays the per-shape warmup storm
    is not capacity for minutes."""
    import http.client
    import json as _json
    import shutil
    import subprocess
    import tempfile
    import time

    cache = cache_dir or tempfile.mkdtemp(prefix="tpu-compile-cache-")
    own_cache = cache_dir is None
    prompt = list(range(1, prompt_len + 1))
    out = {"cold_start": True, "config": config,
           "quantized": quantized, "compile_cache_dir": cache}
    try:
        for phase in ("cold", "warm"):
            port = _free_port()
            t0 = time.perf_counter()
            proc = _spawn_server(
                config, quantized, port, slots, steps, max_len,
                ["--compile-cache-dir", cache])
            try:
                _wait_http_ok(port, "/healthz", 900)
                out[f"{phase}_ready_s"] = time.perf_counter() - t0
                conn = http.client.HTTPConnection("127.0.0.1", port,
                                                  timeout=600)
                conn.request(
                    "POST", "/generate",
                    _json.dumps({"tokens": prompt,
                                 "max_new_tokens": steps,
                                 "stream": False}),
                    {"Content-Type": "application/json"})
                resp = conn.getresponse()
                body = resp.read()
                conn.close()
                if resp.status != 200:
                    raise RuntimeError(
                        f"{phase} start first request answered "
                        f"{resp.status}: {body[:120]!r}")
                out[f"{phase}_first_completion_s"] = (
                    time.perf_counter() - t0)
            finally:
                proc.kill()
                try:
                    proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    pass
        out["warm_speedup_x"] = (out["cold_first_completion_s"]
                                 / out["warm_first_completion_s"])
        out["warm_faster"] = float(out["warm_first_completion_s"]
                                   < out["cold_first_completion_s"])
    finally:
        if own_cache:
            shutil.rmtree(cache, ignore_errors=True)
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tpu-serving-bench")
    p.add_argument("--config", choices=sorted(CONFIGS), default="tiny")
    p.add_argument("--quantized", action="store_true",
                   help="weight-only int8")
    p.add_argument("--int4", action="store_true",
                   help="weight-only int4 (packed; dense configs only)")
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--steps", type=int, default=64)
    p.add_argument("--prompt-len", type=int, default=128)
    p.add_argument("--max-len", type=int, default=512)
    p.add_argument("--engine", action="store_true",
                   help="measure through the continuous-batching "
                        "engine (run_scan) instead of the uniform loop")
    p.add_argument("--spec", type=int, default=0, metavar="GAMMA",
                   help="speculative-round economics at this gamma "
                        "(paired draft per DRAFT_FOR; reports round "
                        "latency + implied tok/s over accept rate)")
    p.add_argument("--http", type=int, default=0, metavar="CLIENTS",
                   help="front-door load test: N concurrent streaming "
                        "HTTP clients (mixed priorities) against a "
                        "live EngineServer; --batch sets the slot "
                        "count; reports req/s + p50/p99 TTFT/TPOT vs "
                        "the direct-engine tokens/sec")
    p.add_argument("--requests", type=int, default=0,
                   help="total requests for --http (default 4x clients)")
    p.add_argument("--cancel-every", type=int, default=0, metavar="K",
                   help="with --http: every K-th request disconnects "
                        "after its first token (release-path stress)")
    p.add_argument("--burst", type=int, default=0, metavar="N",
                   help="with --http: after the timed load, N "
                        "simultaneous requests (half slow-reading) "
                        "against the fixed pool — reports the "
                        "200/429 shed mix (backpressure phase)")
    p.add_argument("--no-interleave", action="store_true",
                   help="with --http: disable iteration-level "
                        "prefill/decode interleaving (A/B against the "
                        "scheduler; outputs identical either way)")
    p.add_argument("--packed-prefill", default=True,
                   action=argparse.BooleanOptionalAction,
                   help="with --http: ragged packed prefill (batched "
                        "admission extends; default on, outputs "
                        "identical either way)")
    p.add_argument("--overlap-dispatch", default=True,
                   action=argparse.BooleanOptionalAction,
                   help="with --http: double-buffered dispatch/"
                        "harvest overlap (default on, outputs "
                        "identical either way)")
    p.add_argument("--prefill-heavy", action="store_true",
                   help="with --http: the prefill-dominated phase — "
                        "long DISTINCT prompts, short outputs, run "
                        "with packing+overlap ON vs OFF; reports both "
                        "arms' prefill tok/s, HTTP/engine ratio, and "
                        "admit→first-token breakdown plus the ON/OFF "
                        "speedup (--prompt-len/--steps shape it)")
    p.add_argument("--fused-decode", default=False,
                   action=argparse.BooleanOptionalAction,
                   help="with --http: run the engine's fused decode "
                        "loop (on-device stop/boundary carry + "
                        "vectorized harvest; default off, outputs "
                        "identical either way)")
    p.add_argument("--decode-heavy", action="store_true",
                   help="with --http: the decode-dominated phase — "
                        "short DISTINCT prompts, long seeded-sampled "
                        "outputs, run with the fused decode loop ON "
                        "vs OFF; reports both arms' TPOT p50/p99, "
                        "harvest-ms per window, and the ON/OFF "
                        "tokens/sec speedup "
                        "(--prompt-len/--steps shape it)")
    p.add_argument("--assert-fused-speedup", type=float, default=0.0,
                   metavar="FLOOR",
                   help="with --decode-heavy: exit nonzero unless the "
                        "fused harvest path is >= FLOOR x faster per "
                        "window (harvest_speedup_x — the loop win "
                        "isolated; on a CPU proxy the forward pass is "
                        "host-bound, so end-to-end fused_speedup_x is "
                        "reported but not gated)")
    p.add_argument("--cold-start", action="store_true",
                   help="replica cold-start phase: boot the real "
                        "server CLI twice against one "
                        "--compile-cache-dir (cold fill, warm load) "
                        "and time spawn → first completion; exits "
                        "nonzero unless the warm boot is faster (the "
                        "autoscaling gate)")
    p.add_argument("--compile-cache-dir", default=None, metavar="DIR",
                   help="with --cold-start: reuse DIR as the persistent "
                        "compile cache instead of a throwaway tempdir "
                        "(pass a pre-warmed dir to measure warm-only)")
    p.add_argument("--assert-ratio", type=float, default=0.0,
                   metavar="FLOOR",
                   help="with --http: exit nonzero unless "
                        "http_over_engine_ratio >= FLOOR (the CI "
                        "regression gate for the continuous-batching "
                        "target)")
    p.add_argument("--assert-goodput", action="store_true",
                   help="with --http: exit nonzero unless the timed "
                        "phase's goodput (requests/sec meeting class "
                        "SLOs, from the tpu_slo_* families) is "
                        "nonzero (the SLO-wiring CI smoke gate)")
    p.add_argument("--metrics-out", default=None, metavar="PATH",
                   help="with --http: write the post-run /metrics "
                        "scrape to PATH (plain) and PATH.om "
                        "(OpenMetrics) so CI can promlint both "
                        "exposition modes")
    p.add_argument("--kv-paging", action="store_true",
                   help="with --http: serve from the paged KV pool "
                        "(reports pool occupancy, shared-page ratio, "
                        "CoW copies and preemption counts from the "
                        "production /metrics surface)")
    p.add_argument("--tenants", type=int, default=0, metavar="N",
                   help="with --http: tag requests with N round-robin "
                        "tenant identities under weighted fair "
                        "queueing (tenant-0 = the weight-1 batch "
                        "lane, the rest weight-4 interactive lanes)")
    p.add_argument("--router", type=int, default=0, metavar="N",
                   help="with --http: multi-replica mode — spawn N "
                        "serving-replica subprocesses (the real CLI, "
                        "self-registering) behind the in-process "
                        "router tier; reports aggregate tokens/sec, "
                        "per-replica share, affinity hit rate, and "
                        "scaling vs 1 replica through the same hop")
    p.add_argument("--disagg", action="store_true",
                   help="disaggregated prefill/decode A/B: mixed "
                        "long-prefill-unary + short-streaming-decode "
                        "traffic against 2 homogeneous replicas vs a "
                        "prefill+decode pair with phase routing + KV "
                        "migration; reports decode TTFT/TPOT p99 per "
                        "arm (clients from --http, counts from "
                        "--requests)")
    p.add_argument("--assert-disagg", action="store_true",
                   help="with --disagg: exit nonzero unless the "
                        "disagg arm beats the homogeneous arm on "
                        "decode TTFT p99 or decode TPOT p99")
    p.add_argument("--assert-scaling", type=float, default=0.0,
                   metavar="FLOOR",
                   help="with --router: exit nonzero unless the "
                        "N-replica aggregate is >= FLOOR x the "
                        "1-replica aggregate (the router-smoke CI "
                        "gate)")
    p.add_argument("--router-kill", action="store_true",
                   help="with --router: SIGKILL one replica after the "
                        "timed phases and prove the survivors absorb "
                        "the follow-on traffic (zero non-429 errors, "
                        "failovers counted)")
    p.add_argument("--seed", type=int, default=0,
                   help="prompt/jitter RNG seed for --router")
    args = p.parse_args(argv)

    devs = jax.devices()
    print(f"devices: {len(devs)} x {devs[0].platform}", flush=True)
    if args.int4 and args.quantized:
        p.error("--quantized and --int4 are mutually exclusive")
    modes = [f for f, on in (("--engine", args.engine),
                             ("--spec", args.spec),
                             ("--http", args.http),
                             ("--cold-start", args.cold_start)) if on]
    if len(modes) > 1:
        # silently running a different experiment than the one asked
        # for is worse than an error
        p.error(f"{' and '.join(modes)} are mutually exclusive")
    if (args.requests or args.cancel_every or args.burst
            or args.assert_ratio or args.no_interleave
            or args.kv_paging or args.tenants or args.router
            or args.prefill_heavy or args.assert_goodput
            or args.metrics_out or args.disagg or args.decode_heavy
            or args.fused_decode) \
            and not args.http:
        p.error("--requests/--cancel-every/--burst/--assert-ratio/"
                "--no-interleave/--kv-paging/--tenants/--router/"
                "--prefill-heavy/--decode-heavy/--fused-decode/"
                "--assert-goodput/--metrics-out/"
                "--disagg only apply with --http")
    if args.assert_fused_speedup and not args.decode_heavy:
        p.error("--assert-fused-speedup needs --decode-heavy")
    if args.decode_heavy and args.prefill_heavy:
        p.error("--decode-heavy and --prefill-heavy are mutually "
                "exclusive")
    if args.compile_cache_dir and not args.cold_start:
        p.error("--compile-cache-dir only applies with --cold-start")
    if args.cold_start:
        quantized = "int4" if args.int4 else args.quantized
        try:
            stats = run_cold_start(
                args.config, quantized, slots=args.batch or 4,
                steps=args.steps, prompt_len=args.prompt_len,
                max_len=args.max_len,
                cache_dir=args.compile_cache_dir)
        except (ValueError, RuntimeError) as e:
            p.error(str(e))
        for k, v in stats.items():
            print(f"{k}: {v}")
        if not stats.get("warm_faster"):
            print("FAIL: warm start "
                  f"({stats['warm_first_completion_s']:.1f}s) not "
                  "faster than cold start "
                  f"({stats['cold_first_completion_s']:.1f}s)",
                  flush=True)
            return 1
        print(f"OK: warm start {stats['warm_speedup_x']:.2f}x faster "
              "than cold", flush=True)
        return 0
    if args.prefill_heavy:
        quantized = "int4" if args.int4 else args.quantized
        try:
            stats = run_prefill_heavy(
                args.config, quantized, clients=args.http,
                n_requests=args.requests or 4 * args.http,
                slots=args.batch, steps=args.steps,
                prompt_len=args.prompt_len, max_len=args.max_len)
        except (ValueError, RuntimeError) as e:
            p.error(str(e))
        for k, v in stats.items():
            print(f"{k}: {v}")
        if args.assert_ratio:
            ratio = stats.get("http_over_engine_ratio_on", 0.0)
            if ratio < args.assert_ratio:
                print(f"FAIL: http_over_engine_ratio_on {ratio:.3f} "
                      f"below the {args.assert_ratio:.2f} floor",
                      flush=True)
                return 1
            print(f"OK: http_over_engine_ratio_on {ratio:.3f} >= "
                  f"{args.assert_ratio:.2f}", flush=True)
        return 0
    if args.decode_heavy:
        quantized = "int4" if args.int4 else args.quantized
        try:
            stats = run_decode_heavy(
                args.config, quantized, clients=args.http,
                n_requests=args.requests or 4 * args.http,
                slots=args.batch, steps=args.steps,
                prompt_len=args.prompt_len, max_len=args.max_len)
        except (ValueError, RuntimeError) as e:
            p.error(str(e))
        for k, v in stats.items():
            print(f"{k}: {v}")
        rc = 0
        if args.assert_fused_speedup:
            speedup = stats.get("harvest_speedup_x", 0.0)
            if speedup < args.assert_fused_speedup:
                print(f"FAIL: harvest_speedup_x {speedup:.3f} below "
                      f"the {args.assert_fused_speedup:.2f} floor",
                      flush=True)
                rc = 1
            else:
                print(f"OK: harvest_speedup_x {speedup:.3f} >= "
                      f"{args.assert_fused_speedup:.2f} (end-to-end "
                      f"fused_speedup_x "
                      f"{stats.get('fused_speedup_x', 0.0):.3f})",
                      flush=True)
        if args.assert_ratio:
            ratio = stats.get("http_over_engine_ratio_on", 0.0)
            if ratio < args.assert_ratio:
                print(f"FAIL: http_over_engine_ratio_on {ratio:.3f} "
                      f"below the {args.assert_ratio:.2f} floor",
                      flush=True)
                rc = 1
            else:
                print(f"OK: http_over_engine_ratio_on {ratio:.3f} >= "
                      f"{args.assert_ratio:.2f}", flush=True)
        return rc
    if args.tenants < 0:
        p.error("--tenants must be >= 0")
    if args.router < 0:
        p.error("--router must be >= 0")
    if (args.assert_scaling or args.router_kill) and not args.router:
        p.error("--assert-scaling/--router-kill need --router")
    if args.router and (args.cancel_every or args.burst
                        or args.assert_ratio or args.kv_paging
                        or args.tenants or args.no_interleave):
        p.error("--router is its own mode: the single-replica phase "
                "flags do not apply")
    if args.assert_disagg and not args.disagg:
        p.error("--assert-disagg needs --disagg")
    if args.disagg and (args.router or args.cancel_every
                        or args.burst or args.assert_ratio
                        or args.kv_paging or args.tenants
                        or args.no_interleave):
        p.error("--disagg is its own mode: the single-replica and "
                "--router phase flags do not apply")
    quantized = "int4" if args.int4 else args.quantized
    if args.disagg:
        try:
            stats = run_disagg(
                args.config, quantized, clients=args.http,
                n_requests=args.requests or 8 * args.http,
                slots=args.batch, steps=args.steps,
                prompt_len=args.prompt_len, max_len=args.max_len,
                seed=args.seed)
        except (ValueError, RuntimeError) as e:
            p.error(str(e))
        for k, v in stats.items():
            print(f"{k}: {v}")
        if args.assert_disagg:
            ttft_r = stats["ttft_p99_ratio"]
            tpot_r = stats["tpot_p99_ratio"]
            if min(ttft_r, tpot_r) >= 1.0:
                print(f"FAIL: disagg beat the homogeneous arm on "
                      f"neither decode TTFT p99 (x{ttft_r:.3f}) nor "
                      f"decode TPOT p99 (x{tpot_r:.3f})", flush=True)
                return 1
            print(f"OK: disagg decode TTFT p99 x{ttft_r:.3f} / "
                  f"TPOT p99 x{tpot_r:.3f} vs homogeneous "
                  "(< 1.0 = better)", flush=True)
        return 0
    if args.router:
        try:
            stats = run_router(
                args.config, quantized, args.router,
                clients=args.http,
                n_requests=args.requests or 8 * args.http,
                slots=args.batch, steps=args.steps,
                prompt_len=args.prompt_len, max_len=args.max_len,
                kill=args.router_kill, seed=args.seed)
        except (ValueError, RuntimeError) as e:
            p.error(str(e))
        for k, v in stats.items():
            print(f"{k}: {v}")
        rc = 0
        if args.assert_scaling:
            scaling = stats.get("scaling_x", 0.0)
            if scaling < args.assert_scaling:
                print(f"FAIL: scaling_x {scaling:.3f} below the "
                      f"{args.assert_scaling:.2f} floor", flush=True)
                rc = 1
            else:
                print(f"OK: scaling_x {scaling:.3f} >= "
                      f"{args.assert_scaling:.2f}", flush=True)
        if args.router_kill and stats.get("kill_errors", 0):
            print(f"FAIL: {stats['kill_errors']:.0f} non-429 errors "
                  "after the replica kill", flush=True)
            rc = 1
        return rc
    try:
        stats = run(args.config, quantized, args.batch, args.steps,
                    args.prompt_len, args.max_len, engine=args.engine,
                    spec=args.spec, http_clients=args.http,
                    http_requests=args.requests,
                    cancel_every=args.cancel_every, burst=args.burst,
                    interleave=not args.no_interleave,
                    kv_paging=args.kv_paging, tenants=args.tenants,
                    packed_prefill=args.packed_prefill,
                    overlap_dispatch=args.overlap_dispatch,
                    metrics_out=args.metrics_out,
                    fused_decode=args.fused_decode)
    except ValueError as e:
        p.error(str(e))
    for k, v in stats.items():
        print(f"{k}: {v}")
    if args.assert_ratio:
        ratio = stats.get("http_over_engine_ratio", 0.0)
        if ratio < args.assert_ratio:
            print(f"FAIL: http_over_engine_ratio {ratio:.3f} below "
                  f"the {args.assert_ratio:.2f} floor", flush=True)
            return 1
        print(f"OK: http_over_engine_ratio {ratio:.3f} >= "
              f"{args.assert_ratio:.2f}", flush=True)
    if args.assert_goodput:
        goodput = stats.get("goodput_req_per_sec", 0.0)
        if goodput <= 0:
            print("FAIL: goodput_req_per_sec is zero — the SLO "
                  "accounting saw no met request", flush=True)
            return 1
        print(f"OK: goodput_req_per_sec {goodput:.2f} > 0",
              flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
