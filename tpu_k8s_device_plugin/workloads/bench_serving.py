"""Pod-runnable serving benchmark: tokens/sec of the native decode
engine (the counterpart of bench_main.py for BASELINE config #5).

Runs the KV-cache decode loop on whatever chips the plugin granted and
prints tokens/sec — e.g. Llama-3-8B weight-only int8 on a single v5e
(the model family the reference's vLLM example deploys, served by the
native engine instead of an opaque image):

    python -m tpu_k8s_device_plugin.workloads.bench_serving \
        --config llama3-8b --quantized --batch 1 --steps 64

Weights are random (throughput moves bytes, not meanings) and are
constructed DIRECTLY in the quantized layout so the 8B config fits on
one 16 GB chip (see llama.random_quantized_params).
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp

from . import llama
from .inference import decode_throughput, quantize_lm_params

CONFIGS = {
    "llama3-8b": llama.LLAMA3_8B,
    "llama2-7b": llama.LLAMA2_7B,
    "tiny": llama.TINY_LLAMA,
}


def run(config: str, quantized: bool, batch: int, steps: int,
        prompt_len: int, max_len: int):
    cfg = CONFIGS[config]
    model = llama.decoder(cfg, max_len=max_len, quantized=quantized)
    if quantized:
        params = llama.random_quantized_params(cfg)
    else:
        # small configs only: materializes the bf16 tree
        train = llama.train_model(cfg)
        tokens = jnp.zeros((1, 8), jnp.int32)
        pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
        params = train.init(jax.random.PRNGKey(0), tokens, pos)["params"]
    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (batch, prompt_len), 0, cfg.vocab)
    stats = decode_throughput(model, params, prompt, steps)
    stats["config"] = config
    stats["quantized"] = quantized
    return stats


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tpu-serving-bench")
    p.add_argument("--config", choices=sorted(CONFIGS), default="tiny")
    p.add_argument("--quantized", action="store_true")
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--steps", type=int, default=64)
    p.add_argument("--prompt-len", type=int, default=128)
    p.add_argument("--max-len", type=int, default=512)
    args = p.parse_args(argv)
    if args.prompt_len + args.steps > args.max_len:
        p.error("--prompt-len + --steps must fit in --max-len")

    devs = jax.devices()
    print(f"devices: {len(devs)} x {devs[0].platform}", flush=True)
    stats = run(args.config, args.quantized, args.batch, args.steps,
                args.prompt_len, args.max_len)
    for k, v in stats.items():
        print(f"{k}: {v}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
