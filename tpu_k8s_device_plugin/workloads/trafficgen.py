# tpulint: deterministic-path
"""Seeded production-shaped trace generation (tpu-trace/v1).

Closed-loop uniform load — bench_serving's historical posture —
structurally cannot exercise the QoS/preemption/disagg/router
machinery the observability plane exists to observe: a closed loop
self-throttles under overload (each client waits for its previous
request), arrivals are never bursty, prompts share no prefixes, and
every request looks the same.  Serving evaluations converge on trace
replay instead (vLLM's ShareGPT traces, Mooncake's overload-oriented
replay): tail behavior only appears under bursty, heavy-tailed,
prefix-skewed, OPEN-loop traffic.

This module generates such traces, fully deterministically:

- **arrivals**: a 2-state Markov-modulated Poisson process — a calm
  state and a burst state, each with its own rate, with geometric
  dwell times — so the replay harness sees genuine bursts (queue
  growth, shedding, preemption) rather than a flat rate,
- **prefixes**: Zipf-distributed shared prefix blocks whose lengths
  are multiples of the engine's ``--prefix-chunk``, so the APC cache
  and the router's prefix-affinity tier have real economics to win,
- **lengths**: lognormal prompt/output lengths (long-tailed, like
  production: most requests short, a heavy tail of huge ones),
- **mix**: tenants, SLO classes and priorities, unary-vs-stream, and
  per-request client behaviors (slow reader at N bytes/s, abandoner
  at T ms) — the misbehaviors :mod:`.loadclient` executes.

Determinism is the contract: one ``random.Random(seed)`` with a fixed
call order, virtual timestamps (no wall clock anywhere), and
canonical JSON encoding — the same seed + config produces a
byte-identical trace file, so a CI goodput gate replays EXACTLY the
traffic a developer replays locally.  Stdlib only, mypy --strict.
"""

from __future__ import annotations

import argparse
import bisect
import json
import random
import sys
from dataclasses import asdict, dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

from .loadclient import ClientBehavior

SCHEMA = "tpu-trace/v1"


class TraceError(ValueError):
    """A trace file that cannot be trusted: bad schema/version,
    truncation, count mismatch, malformed record.  Loading NEVER
    skips bad lines — a silently-shortened trace would make every
    downstream goodput number a lie."""


@dataclass(frozen=True)
class TraceConfig:
    """Knobs for one generated trace.  All rates/lengths are virtual:
    the generator never consults a clock."""

    n_requests: int = 200
    # MMPP arrivals: calm/burst rates + per-arrival switch probability
    # (geometric dwell: 1/p arrivals expected per state visit)
    base_rate_rps: float = 4.0
    burst_rate_rps: float = 40.0
    p_enter_burst: float = 0.02
    p_exit_burst: float = 0.10
    # Zipf shared prefixes, aligned to the engine's prefix chunk
    prefix_chunk: int = 32
    n_prefixes: int = 16
    zipf_alpha: float = 1.1
    max_prefix_chunks: int = 4
    # lognormal lengths (natural-log median / sigma), with clamps
    prompt_median: float = 48.0
    prompt_sigma: float = 0.8
    prompt_max: int = 512
    output_median: float = 32.0
    output_sigma: float = 0.7
    output_min: int = 4
    output_max: int = 256
    # mix (vocab default matches the tiny CPU config's 256 — a trace
    # must never emit ids the replayed model rejects as 400s)
    vocab: int = 256
    tenants: Tuple[str, ...] = ("default",)
    # optional per-tenant traffic weights (parallel to `tenants`).
    # None keeps the historical uniform randrange draw — and the
    # historical byte stream, so every existing seed+config pair
    # still produces an identical trace file.
    tenant_weights: Optional[Tuple[float, ...]] = None
    unary_frac: float = 0.25
    slow_reader_frac: float = 0.05
    slow_reader_bytes_per_s: int = 512
    abandon_frac: float = 0.05
    abandon_after_ms: float = 400.0
    # session-revisit dimension (PR 20): (P, gap_ms) — each request
    # revisits an earlier conversation with probability P after at
    # least gap_ms of think time, exercising the warm-resume path.
    # None (the default) keeps the historical draw sequence — and so
    # the byte stream of every existing seed+config pair.
    session_revisit: Optional[Tuple[float, float]] = None

    def __post_init__(self) -> None:
        if self.n_requests <= 0:
            raise ValueError("n_requests must be > 0")
        if self.base_rate_rps <= 0 or self.burst_rate_rps <= 0:
            raise ValueError("arrival rates must be > 0")
        if not 0 <= self.p_enter_burst <= 1 \
                or not 0 < self.p_exit_burst <= 1:
            raise ValueError("state-switch probabilities out of range")
        if self.prefix_chunk <= 0 or self.n_prefixes <= 0 \
                or self.max_prefix_chunks <= 0:
            raise ValueError("prefix shape must be positive")
        if self.vocab < 4:
            raise ValueError("vocab too small")
        if not self.tenants:
            raise ValueError("need at least one tenant")
        if self.tenant_weights is not None:
            if len(self.tenant_weights) != len(self.tenants):
                raise ValueError(
                    "tenant_weights must parallel tenants")
            if any(w <= 0 for w in self.tenant_weights):
                raise ValueError("tenant weights must be > 0")
        for frac in (self.unary_frac, self.slow_reader_frac,
                     self.abandon_frac):
            if not 0.0 <= frac <= 1.0:
                raise ValueError("fractions must be in [0, 1]")
        if self.session_revisit is not None:
            p_rev, gap_ms = self.session_revisit
            if not 0.0 <= p_rev <= 1.0:
                raise ValueError(
                    "session_revisit probability must be in [0, 1]")
            if gap_ms < 0:
                raise ValueError("session_revisit gap must be >= 0")


@dataclass
class TraceRequest:
    """One trace record: everything replay needs to issue the request
    at ``t_ms`` (virtual ms from trace start) with the right body and
    client behavior."""

    rid: str
    t_ms: float
    tenant: str
    slo_class: str
    priority: int
    prefix_id: int
    tokens: List[int]
    max_new_tokens: int
    behavior: ClientBehavior = field(default_factory=ClientBehavior)
    # session-revisit dimension: the conversation this request
    # belongs to ("" = anonymous) and whether it CONTINUES an
    # earlier visit (the replay harness chains its prompt onto the
    # session's history).  Emitted only when set, so unsessioned
    # traces keep their historical bytes.
    session: str = ""
    cont: bool = False

    def to_record(self) -> Dict[str, object]:
        rec: Dict[str, object] = {
            "rid": self.rid, "t_ms": round(self.t_ms, 3),
            "tenant": self.tenant, "slo_class": self.slo_class,
            "priority": self.priority, "prefix_id": self.prefix_id,
            "tokens": self.tokens,
            "max_new_tokens": self.max_new_tokens,
            "behavior": {
                "stream": self.behavior.stream,
                "read_bytes_per_s": self.behavior.read_bytes_per_s,
                "abandon_after_ms": self.behavior.abandon_after_ms,
            },
        }
        if self.session:
            rec["session"] = self.session
            rec["cont"] = self.cont
        return rec


def _prefix_block(seed: int, config: TraceConfig,
                  prefix_id: int) -> List[int]:
    """The shared prefix for one prefix id: its own derived generator
    (seeded from (seed, prefix_id), independent of draw order in the
    main stream) producing a chunk-aligned token block — so two
    requests with the same prefix_id share EXACTLY the tokens the APC
    cache and affinity key hash over."""
    drng = random.Random((seed << 20) ^ (prefix_id * 2654435761))
    n_chunks = 1 + drng.randrange(config.max_prefix_chunks)
    return [drng.randrange(1, config.vocab)
            for _ in range(n_chunks * config.prefix_chunk)]


def _zipf_cdf(n: int, alpha: float) -> List[float]:
    weights = [1.0 / (rank ** alpha) for rank in range(1, n + 1)]
    total = sum(weights)
    acc = 0.0
    cdf: List[float] = []
    for w in weights:
        acc += w / total
        cdf.append(acc)
    return cdf


def _clamped_lognormal(rng: random.Random, median: float,
                       sigma: float, lo: int, hi: int) -> int:
    import math

    return max(lo, min(hi, int(round(
        rng.lognormvariate(math.log(median), sigma)))))


def generate(config: TraceConfig, seed: int) -> List[TraceRequest]:
    """The trace: one ``random.Random(seed)`` with a FIXED per-request
    draw order (arrival, state switch, prefix, lengths, suffix, mix,
    behavior) — reordering any draw is a schema-visible change, so
    keep new draws at the END of the per-request block."""
    rng = random.Random(seed)
    cdf = _zipf_cdf(config.n_prefixes, config.zipf_alpha)
    tenant_cdf: List[float] = []
    if config.tenant_weights is not None:
        total_w = sum(config.tenant_weights)
        acc = 0.0
        for w in config.tenant_weights:
            acc += w / total_w
            tenant_cdf.append(acc)
    prefixes = [_prefix_block(seed, config, pid)
                for pid in range(config.n_prefixes)]
    rates = {False: config.base_rate_rps, True: config.burst_rate_rps}
    burst = False
    t_s = 0.0
    out: List[TraceRequest] = []
    # session-revisit state (only touched when the dimension is on)
    session_ids: List[str] = []
    session_last_ms: Dict[str, float] = {}
    for i in range(config.n_requests):
        t_s += rng.expovariate(rates[burst])
        switch = rng.random()  # drawn unconditionally: fixed order
        if burst:
            if switch < config.p_exit_burst:
                burst = False
        elif switch < config.p_enter_burst:
            burst = True
        prefix_id = bisect.bisect_left(cdf, rng.random())
        prefix_id = min(prefix_id, config.n_prefixes - 1)
        prompt_len = _clamped_lognormal(
            rng, config.prompt_median, config.prompt_sigma,
            1, config.prompt_max)
        max_new = _clamped_lognormal(
            rng, config.output_median, config.output_sigma,
            config.output_min, config.output_max)
        prefix = prefixes[prefix_id]
        suffix_len = max(1, prompt_len)
        suffix = [rng.randrange(1, config.vocab)
                  for _ in range(suffix_len)]
        # both arms consume exactly one draw, and the unweighted arm
        # keeps the historical randrange call — same seed + same old
        # config still yields a byte-identical trace
        if tenant_cdf:
            ti = bisect.bisect_left(tenant_cdf, rng.random())
            tenant = config.tenants[min(ti, len(config.tenants) - 1)]
        else:
            tenant = config.tenants[rng.randrange(len(config.tenants))]
        stream = rng.random() >= config.unary_frac
        slo_class = "interactive" if stream else "batch"
        priority = 0 if stream else 1
        slow = stream and rng.random() < config.slow_reader_frac
        abandon = stream and rng.random() < config.abandon_frac
        behavior = ClientBehavior(
            stream=stream,
            read_bytes_per_s=config.slow_reader_bytes_per_s
            if slow else 0,
            abandon_after_ms=config.abandon_after_ms
            * (0.5 + rng.random()) if abandon else 0.0)
        # session-revisit draws come LAST in the per-request block
        # and ONLY when the dimension is enabled: a None config
        # consumes zero draws, so every pre-existing seed+config
        # pair still produces a byte-identical trace
        session = ""
        cont = False
        if config.session_revisit is not None:
            p_rev, gap_ms = config.session_revisit
            if session_ids and rng.random() < p_rev:
                session = session_ids[
                    rng.randrange(len(session_ids))]
                cont = True
                # the revisit happens after the conversation's think
                # time; advancing the GLOBAL clock (never rewinding)
                # keeps trace timestamps monotonic for the loader
                t_s = max(t_s,
                          (session_last_ms[session] + gap_ms)
                          / 1000.0)
            else:
                session = f"s{i:05d}"
                session_ids.append(session)
            session_last_ms[session] = t_s * 1000.0
        out.append(TraceRequest(
            rid=f"r{i:05d}", t_ms=t_s * 1000.0, tenant=tenant,
            slo_class=slo_class, priority=priority,
            prefix_id=prefix_id, tokens=prefix + suffix,
            max_new_tokens=max_new, behavior=behavior,
            session=session, cont=cont))
    return out


def _header(config: TraceConfig, seed: int,
            n_requests: int) -> Dict[str, object]:
    return {"schema": SCHEMA, "seed": seed, "requests": n_requests,
            "config": asdict(config)}


def dumps_trace(config: TraceConfig, seed: int,
                requests: Iterable[TraceRequest]) -> str:
    """The canonical byte form: header line + one record per line,
    sorted keys, no whitespace — the determinism tests compare THIS
    string (and files written through :func:`write_trace`) for
    byte-identity."""
    reqs = list(requests)
    lines = [json.dumps(_header(config, seed, len(reqs)),
                        sort_keys=True, separators=(",", ":"))]
    lines.extend(json.dumps(r.to_record(), sort_keys=True,
                            separators=(",", ":")) for r in reqs)
    return "\n".join(lines) + "\n"


def write_trace(path: str, config: TraceConfig, seed: int,
                requests: Iterable[TraceRequest]) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(dumps_trace(config, seed, requests))


def _req_field(rec: Dict[str, object], key: str, lineno: int,
               kind: type) -> object:
    if key not in rec:
        raise TraceError(f"line {lineno}: missing field {key!r}")
    val = rec[key]
    if kind is float and isinstance(val, int):
        val = float(val)
    if not isinstance(val, kind) or (kind is int
                                     and isinstance(val, bool)):
        raise TraceError(
            f"line {lineno}: field {key!r} must be {kind.__name__}, "
            f"got {type(val).__name__}")
    return val


def _parse_record(rec: Dict[str, object],
                  lineno: int) -> TraceRequest:
    tokens_raw = _req_field(rec, "tokens", lineno, list)
    assert isinstance(tokens_raw, list)
    tokens: List[int] = []
    for t in tokens_raw:
        if not isinstance(t, int) or isinstance(t, bool):
            raise TraceError(f"line {lineno}: non-int token {t!r}")
        tokens.append(t)
    if not tokens:
        raise TraceError(f"line {lineno}: empty token list")
    beh_raw = _req_field(rec, "behavior", lineno, dict)
    assert isinstance(beh_raw, dict)
    try:
        behavior = ClientBehavior(
            stream=bool(beh_raw.get("stream", True)),
            read_bytes_per_s=int(
                beh_raw.get("read_bytes_per_s", 0) or 0),
            abandon_after_ms=float(
                beh_raw.get("abandon_after_ms", 0.0) or 0.0))
    except (TypeError, ValueError) as e:
        raise TraceError(f"line {lineno}: bad behavior block: {e}")
    max_new = _req_field(rec, "max_new_tokens", lineno, int)
    assert isinstance(max_new, int)
    if max_new <= 0:
        raise TraceError(f"line {lineno}: max_new_tokens must be > 0")
    t_ms = _req_field(rec, "t_ms", lineno, float)
    assert isinstance(t_ms, float)
    if t_ms < 0:
        raise TraceError(f"line {lineno}: negative t_ms")
    rid = _req_field(rec, "rid", lineno, str)
    tenant = _req_field(rec, "tenant", lineno, str)
    slo_class = _req_field(rec, "slo_class", lineno, str)
    priority = _req_field(rec, "priority", lineno, int)
    prefix_id = _req_field(rec, "prefix_id", lineno, int)
    assert isinstance(rid, str) and isinstance(tenant, str)
    assert isinstance(slo_class, str)
    assert isinstance(priority, int) and isinstance(prefix_id, int)
    # optional session fields (absent in unsessioned traces)
    session_raw = rec.get("session", "")
    if not isinstance(session_raw, str):
        raise TraceError(f"line {lineno}: 'session' must be str")
    cont_raw = rec.get("cont", False)
    if not isinstance(cont_raw, bool):
        raise TraceError(f"line {lineno}: 'cont' must be bool")
    return TraceRequest(
        rid=rid, t_ms=t_ms, tenant=tenant, slo_class=slo_class,
        priority=priority, prefix_id=prefix_id, tokens=tokens,
        max_new_tokens=max_new, behavior=behavior,
        session=session_raw, cont=cont_raw)


def loads_trace(text: str
                ) -> Tuple[Dict[str, object], List[TraceRequest]]:
    """Parse + validate one trace (header, records).  Raises
    :class:`TraceError` on any defect: unknown schema version,
    malformed line, record-count mismatch against the header
    (truncation), out-of-order timestamps."""
    lines = text.splitlines()
    if not lines or not lines[0].strip():
        raise TraceError("empty trace")
    try:
        header_raw = json.loads(lines[0])
    except ValueError as e:
        raise TraceError(f"line 1: unparseable header: {e}")
    if not isinstance(header_raw, dict):
        raise TraceError("line 1: header must be a JSON object")
    if header_raw.get("schema") != SCHEMA:
        raise TraceError(
            f"unsupported trace schema {header_raw.get('schema')!r} "
            f"(this reader speaks {SCHEMA})")
    declared = header_raw.get("requests")
    if not isinstance(declared, int) or isinstance(declared, bool) \
            or declared < 0:
        raise TraceError("header 'requests' must be a count")
    records: List[TraceRequest] = []
    prev_t = -1.0
    for lineno, line in enumerate(lines[1:], start=2):
        if not line.strip():
            continue
        try:
            rec_raw = json.loads(line)
        except ValueError as e:
            raise TraceError(f"line {lineno}: malformed record: {e}")
        if not isinstance(rec_raw, dict):
            raise TraceError(
                f"line {lineno}: record must be a JSON object")
        rec = _parse_record(rec_raw, lineno)
        if rec.t_ms < prev_t:
            raise TraceError(
                f"line {lineno}: t_ms goes backwards "
                f"({rec.t_ms} after {prev_t})")
        prev_t = rec.t_ms
        records.append(rec)
    if len(records) != declared:
        raise TraceError(
            f"truncated or padded trace: header declares {declared} "
            f"requests, file holds {len(records)}")
    return header_raw, records


def load_trace(path: str
               ) -> Tuple[Dict[str, object], List[TraceRequest]]:
    with open(path, "r", encoding="utf-8") as fh:
        return loads_trace(fh.read())


def summarize(requests: List[TraceRequest]) -> Dict[str, object]:
    """Shape summary for humans/CI logs: class/tenant/behavior mix,
    span, length tails — a sanity surface, not part of the schema."""
    if not requests:
        return {"requests": 0}
    by_class: Dict[str, int] = {}
    by_tenant: Dict[str, int] = {}
    by_prefix: Dict[str, int] = {}
    slow = abandoners = unary = revisits = 0
    sessions = set()
    for r in requests:
        by_class[r.slo_class] = by_class.get(r.slo_class, 0) + 1
        by_tenant[r.tenant] = by_tenant.get(r.tenant, 0) + 1
        key = f"p{r.prefix_id}"
        by_prefix[key] = by_prefix.get(key, 0) + 1
        if not r.behavior.stream:
            unary += 1
        if r.behavior.read_bytes_per_s > 0:
            slow += 1
        if r.behavior.abandon_after_ms > 0:
            abandoners += 1
        if r.session:
            sessions.add(r.session)
            if r.cont:
                revisits += 1
    lens = sorted(len(r.tokens) for r in requests)
    outs = sorted(r.max_new_tokens for r in requests)

    def pct(xs: List[int], q: float) -> int:
        return xs[min(len(xs) - 1, int(q * (len(xs) - 1)))]

    return {
        "requests": len(requests),
        "span_ms": round(requests[-1].t_ms - requests[0].t_ms, 3),
        "classes": by_class, "tenants": by_tenant,
        "top_prefixes": dict(sorted(
            by_prefix.items(), key=lambda kv: -kv[1])[:5]),
        "unary": unary, "slow_readers": slow,
        "abandoners": abandoners,
        "sessions": len(sessions), "revisits": revisits,
        "prompt_len": {"p50": pct(lens, 0.5), "p95": pct(lens, 0.95),
                       "max": lens[-1]},
        "max_new_tokens": {"p50": pct(outs, 0.5),
                           "p95": pct(outs, 0.95), "max": outs[-1]},
    }


def parse_tenant_mix(
        spec: Optional[str],
        fallback: Tuple[str, ...] = ("default",),
) -> Tuple[Tuple[str, ...], Optional[Tuple[float, ...]]]:
    """Parse a ``--tenants NAME[:WEIGHT],...`` mix.  Weights are
    optional per-entry (absent means 1.0); an all-default mix returns
    ``None`` weights so the unweighted draw — and byte-determinism of
    old traces — is preserved."""
    if not spec:
        return fallback, None
    names: List[str] = []
    weights: List[float] = []
    weighted = False
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, w_s = part.partition(":")
        if not name:
            raise ValueError(f"--tenants: empty name in {spec!r}")
        w = 1.0
        if sep:
            try:
                w = float(w_s)
            except ValueError:
                raise ValueError(
                    f"--tenants: bad weight {w_s!r} for {name!r}")
            weighted = True
        names.append(name)
        weights.append(w)
    if not names:
        raise ValueError(f"--tenants: no tenants in {spec!r}")
    return tuple(names), tuple(weights) if weighted else None


def parse_session_revisit(
        spec: Optional[str]) -> Optional[Tuple[float, float]]:
    """Parse ``--session-revisit P[:GAP_MS]`` (gap defaults to
    1000 ms of think time).  None in, None out — absence keeps the
    unsessioned draw sequence and its byte-identical traces."""
    if not spec:
        return None
    p_s, sep, gap_s = spec.partition(":")
    try:
        p_rev = float(p_s)
        gap_ms = float(gap_s) if sep else 1000.0
    except ValueError:
        raise ValueError(
            f"--session-revisit: bad spec {spec!r} "
            "(want P or P:GAP_MS)")
    if not 0.0 <= p_rev <= 1.0:
        raise ValueError(
            "--session-revisit: P must be in [0, 1]")
    if gap_ms < 0:
        raise ValueError("--session-revisit: GAP_MS must be >= 0")
    return p_rev, gap_ms


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description="Generate a seeded production-shaped trace "
                    "(tpu-trace/v1 JSON-lines) for workloads.replay")
    p.add_argument("--out", required=True, help="trace file to write")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--requests", type=int, default=200)
    p.add_argument("--base-rate", type=float, default=4.0,
                   help="calm-state arrival rate (req/s)")
    p.add_argument("--burst-rate", type=float, default=40.0,
                   help="burst-state arrival rate (req/s)")
    p.add_argument("--prefix-chunk", type=int, default=32,
                   help="prefix block alignment — match the server's "
                        "--prefix-chunk so APC/affinity engage")
    p.add_argument("--n-prefixes", type=int, default=16)
    p.add_argument("--zipf-alpha", type=float, default=1.1)
    p.add_argument("--prompt-median", type=float, default=48.0)
    p.add_argument("--prompt-max", type=int, default=512)
    p.add_argument("--output-median", type=float, default=32.0)
    p.add_argument("--output-max", type=int, default=256)
    p.add_argument("--vocab", type=int, default=256,
                   help="token-id bound; keep <= the served model's "
                        "vocab or every request 400s")
    p.add_argument("--tenant", action="append", default=None,
                   help="tenant name (repeatable; default: default)")
    p.add_argument("--tenants", default=None,
                   metavar="NAME[:WEIGHT],...",
                   help="tenant mix in one flag, optionally "
                        "weighted (e.g. 'team-a:3,team-b:1' sends "
                        "75%% of traffic as team-a); supersedes "
                        "--tenant")
    p.add_argument("--session-revisit", default=None,
                   metavar="P[:GAP_MS]",
                   help="session dimension: every request carries a "
                        "session id, and with probability P it "
                        "REVISITS an earlier conversation after at "
                        "least GAP_MS (default 1000) of think time — "
                        "replays then exercise the warm-resume "
                        "tiers.  Unset keeps traces unsessioned and "
                        "byte-identical to earlier versions")
    p.add_argument("--unary-frac", type=float, default=0.25)
    p.add_argument("--slow-reader-frac", type=float, default=0.05)
    p.add_argument("--slow-reader-bytes-per-s", type=int, default=512)
    p.add_argument("--abandon-frac", type=float, default=0.05)
    p.add_argument("--abandon-after-ms", type=float, default=400.0)
    args = p.parse_args(argv)
    tenants, tenant_weights = parse_tenant_mix(
        args.tenants, tuple(args.tenant) if args.tenant
        else ("default",))
    try:
        session_revisit = parse_session_revisit(args.session_revisit)
    except ValueError as e:
        p.error(str(e))
    config = TraceConfig(
        n_requests=args.requests, base_rate_rps=args.base_rate,
        burst_rate_rps=args.burst_rate,
        prefix_chunk=args.prefix_chunk, n_prefixes=args.n_prefixes,
        zipf_alpha=args.zipf_alpha, prompt_median=args.prompt_median,
        prompt_max=args.prompt_max,
        output_median=args.output_median,
        output_max=args.output_max, vocab=args.vocab,
        tenants=tenants, tenant_weights=tenant_weights,
        unary_frac=args.unary_frac,
        slow_reader_frac=args.slow_reader_frac,
        slow_reader_bytes_per_s=args.slow_reader_bytes_per_s,
        abandon_frac=args.abandon_frac,
        abandon_after_ms=args.abandon_after_ms,
        session_revisit=session_revisit)
    requests = generate(config, args.seed)
    write_trace(args.out, config, args.seed, requests)
    print(json.dumps({"trace": args.out, "seed": args.seed,
                      "summary": summarize(requests)}, indent=2,
                     sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
