"""AlexNet in flax, written TPU-first.

Replaces the reference's AlexNet TF benchmark workload
(/root/reference/example/pod/alexnet-gpu.yaml:16,
/root/reference/README.md:45-67) with a JAX implementation shaped for the
MXU: bf16 activations/weights for the systolic array, NHWC layout, static
shapes throughout, and a single jit-compiled train step so XLA fuses the
elementwise tail of every conv/matmul.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn
import optax

# bf16 compute, f32 params/optimizer state: the standard TPU mixed-precision
# recipe — matmuls/convs hit the MXU at bf16, updates accumulate in f32.
COMPUTE_DTYPE = jnp.bfloat16

NUM_CLASSES = 1000
IMAGE_SIZE = 224


S2D_BLOCK = 4  # space-to-depth block == the first conv's stride


def space_to_depth(x: jax.Array, block: int = S2D_BLOCK) -> jax.Array:
    """(B, H, W, C) → (B, H/b, W/b, b²·C): fold b×b pixel blocks into
    channels.  The stride-4 11×11 first conv over 3 input channels maps
    terribly onto the 128×128 MXU (3 channels ≪ the systolic array's
    contraction dim); after this transform it becomes a stride-1 3×3 conv
    over 48 channels — the standard TPU conv-net input trick.  Under
    VALID padding the mapping is exact: any 11×11/stride-4 kernel equals
    a 3×3 s2d kernel with the taps rearranged and zero-padded to 12×12
    (oracle-verified in tests/test_workloads.py).  The model's SAME
    padding differs only at the boundary ring (1 s2d block vs 3/4 raw
    pixels of padding), and the s2d form does ~1.4% MORE FLOPs per XLA's
    count — so images/sec comparisons against the raw form are
    conservative.  Measured +8.5% images/sec at batch 2048 on v5e-1."""
    b, h, w, c = x.shape
    x = x.reshape(b, h // block, block, w // block, block, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(
        b, h // block, w // block, block * block * c
    )


class FusedConvPool(nn.Module):
    """Stride-1 SAME conv + 3x3/s2 max-pool through the fused Pallas
    kernel (workloads/convpool.py) — the pre-pool activation never
    reaches HBM.  Param names/initializers match ``nn.Conv`` (f32
    params, compute-dtype cast at use); the bias is added AFTER the
    pool, which is exact: a per-channel constant commutes with max,
    and the scatter backward preserves the gradient sum."""

    features: int
    window: int
    dtype: Any = COMPUTE_DTYPE

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        from .convpool import conv_pool

        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (self.window, self.window, x.shape[-1], self.features))
        bias = self.param(
            "bias", nn.initializers.zeros_init(), (self.features,))
        y = conv_pool(x.astype(self.dtype), kernel.astype(self.dtype))
        return y + bias.astype(self.dtype)


class AlexNet(nn.Module):
    """Canonical 5-conv / 3-dense AlexNet (single-tower).

    With ``s2d=True`` the input is expected space-to-depth transformed
    (see above) and the first conv runs as 3×3/stride-1 over 48 channels —
    the same computation, laid out for the MXU."""

    num_classes: int = NUM_CLASSES
    dtype: Any = COMPUTE_DTYPE
    s2d: bool = False
    # "xla" = reduce_window/select_and_scatter; "pallas" = the
    # argmax-index pool kernel (workloads/pool.py) whose backward
    # avoids select_and_scatter; "fused" = conv+pool in ONE Pallas
    # kernel (workloads/convpool.py) so the pre-pool activation never
    # hits HBM (requires s2d — the raw 11×11/s4 first conv is not
    # stride-1).  All three are numerically equivalent (fwd AND grad,
    # tie-break included; tests/test_pool.py, tests/test_convpool.py),
    # so this is a performance knob to be set from measurement on the
    # target chip.  NOTE: "fused" swaps conv+pool stages to
    # FusedConvPool modules, which renames those param-tree nodes.
    pool: str = "xla"

    def _max_pool(self, x: jax.Array) -> jax.Array:
        if self.pool == "pallas":
            from .pool import max_pool as pallas_max_pool

            return pallas_max_pool(x, 3, 2)
        if self.pool != "xla":
            raise ValueError(
                f"unknown pool {self.pool!r}: expected 'xla', "
                "'pallas', or 'fused'")
        return nn.max_pool(x, window_shape=(3, 3), strides=(2, 2))

    def _conv_pool(self, x: jax.Array, features: int,
                   window: int) -> jax.Array:
        """One conv→pool stage, fused or as separate ops."""
        if self.pool == "fused":
            return FusedConvPool(features=features, window=window,
                                 dtype=self.dtype)(x)
        conv = functools.partial(nn.Conv, dtype=self.dtype,
                                 padding="SAME")
        x = conv(features=features, kernel_size=(window, window))(x)
        return self._max_pool(x)

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = True) -> jax.Array:
        if self.pool == "fused" and not self.s2d:
            raise ValueError(
                "pool='fused' requires s2d=True (the raw 11x11/s4 "
                "first conv is not stride-1)")
        conv = functools.partial(nn.Conv, dtype=self.dtype, padding="SAME")
        x = x.astype(self.dtype)
        # Wherever a max-pool follows a relu, pool FIRST: max and relu
        # commute (relu is monotone, and the gradients match too — the
        # scatter picks the same argmax in the >0 case and the relu mask
        # zeroes the ≤0 case either way), and pooling first shrinks the
        # relu (+ its backward select) to the 4x-smaller pooled tensor.
        # These activations are HBM-bandwidth-bound, not MXU-bound:
        # measured -4.2 ms (seg1) and -2.7 ms (seg2) fwd+bwd at batch
        # 4096 on v5e-1.
        if self.s2d:
            x = self._conv_pool(x, features=64, window=3)
        else:
            x = conv(features=64, kernel_size=(11, 11), strides=(4, 4))(x)
            x = self._max_pool(x)
        x = nn.relu(x)
        x = self._conv_pool(x, features=192, window=5)
        x = nn.relu(x)
        x = conv(features=384, kernel_size=(3, 3))(x)
        x = nn.relu(x)
        x = conv(features=256, kernel_size=(3, 3))(x)
        x = nn.relu(x)
        x = self._conv_pool(x, features=256, window=3)
        x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(4096, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(4096, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


def create_train_state(
    rng: jax.Array,
    batch_size: int = 128,
    image_size: int = IMAGE_SIZE,
    num_classes: int = NUM_CLASSES,
    learning_rate: float = 0.01,
    s2d: bool = False,
    pool: str = "xla",
) -> Tuple[AlexNet, Dict[str, Any]]:
    """Model + initial (params, opt_state) pytree."""
    model = AlexNet(num_classes=num_classes, s2d=s2d, pool=pool)
    if s2d:
        shape = (batch_size, image_size // S2D_BLOCK, image_size // S2D_BLOCK,
                 S2D_BLOCK * S2D_BLOCK * 3)
    else:
        shape = (batch_size, image_size, image_size, 3)
    dummy = jnp.zeros(shape, jnp.float32)
    params = model.init(rng, dummy, train=False)["params"]
    tx = optax.sgd(learning_rate, momentum=0.9)
    opt_state = tx.init(params)
    return model, {"params": params, "opt_state": opt_state, "tx": tx}


def loss_fn(model: AlexNet, params, images: jax.Array, labels: jax.Array):
    logits = model.apply({"params": params}, images, train=True)
    loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    return loss.mean()


def train_step(model: AlexNet, tx, params, opt_state, images, labels):
    """One SGD step.  Pure function of its inputs — jit/shard it from the
    caller; no Python control flow depends on traced values."""
    loss, grads = jax.value_and_grad(functools.partial(loss_fn, model))(
        params, images, labels
    )
    updates, opt_state = tx.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss


def synthetic_batch(
    rng: jax.Array, batch_size: int, image_size: int = IMAGE_SIZE,
    num_classes: int = NUM_CLASSES, s2d: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Synthetic data matching tf_cnn_benchmarks' default mode (no dataset
    flag → synthetic images), so throughput numbers are comparable.

    Images are emitted in bf16: the first conv casts to bf16 anyway, and
    feeding bf16 halves the input HBM traffic (measured +3% throughput at
    batch 2048 on v5e-1).  With ``s2d`` the space-to-depth transform is
    applied here — it belongs to the input pipeline, not the train step
    (a real loader fuses it into decode/augment)."""
    k1, k2 = jax.random.split(rng)
    images = jax.random.normal(
        k1, (batch_size, image_size, image_size, 3), jnp.float32
    ).astype(COMPUTE_DTYPE)
    if s2d:
        images = space_to_depth(images)
    labels = jax.random.randint(k2, (batch_size,), 0, num_classes)
    return images, labels
