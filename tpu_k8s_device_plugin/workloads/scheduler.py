# tpulint: deterministic-path -- the engine equivalence suites replay this file's decisions from seeds; D1 bans bare random/time.time() here
"""Iteration-level scheduler: chunked prefill interleaved with decode.

BASELINE §ROUND-6 priced the HTTP front door's remaining ~0.45× gap
precisely: prefill ran UNOVERLAPPED with decode (a full multi-chunk
prompt prefill stalled every running stream), a request admitted
mid-window waited for the window to close, and the prefix-cache-aware
admission grid lived in the bench harness instead of the engine.  This
module is the fix — the Orca/vLLM move (iteration-level scheduling /
continuous batching with chunked prefill) built on the engine's own
primitives:

* **unified work queue** — every iteration owns both kinds of work:
  decode-ready slots (one ``scan_dispatch`` window) and pending
  prefill chunks (``begin_admit`` tickets advanced one
  ``admit_step`` at a time).
* **interleave** — the decode window is DISPATCHED first (async), then
  prefill chunks, new admissions, and admission finishes are enqueued
  while the device chews the window; the window's one blocking
  ``scan_harvest`` then covers the scan AND the admissions.  Prefill
  compute overlaps in-flight decode instead of serializing with it,
  and the host bookkeeping between device calls overlaps device time
  instead of adding to it.
* **mid-window admission** — ``pull`` (the owner's intake callback)
  runs again between the window's dispatch and harvest, so a request
  that arrives while a window is open starts prefilling BEFORE that
  window closes instead of queueing behind it.
* **ragged packed prefill** — multiple pending admissions' next
  chunks dispatch as ONE batched extend
  (``engine.admit_step_packed``): K concurrent cold prompts cost one
  host dispatch per chunk-round instead of K, and on parallel
  hardware share one kernel's MXU pass.  Pack sizes form a small
  fixed compile set (2..``max_pack``, see ``warm_packed``); the head
  ticket still splices FIRST, so admission order — and with it the
  APC-donor and draw-chain order — is exactly the serial path's.
* **dispatch-ahead overlap** — after harvesting window N the
  scheduler immediately dispatches window N+1 (double-buffered
  dispatch/harvest), so the owner's host-side stream-write work
  between ``iterate()`` calls overlaps device compute instead of
  leaving the device idle.  GUARDED to the all-greedy knob regime: a
  live sampled slot retiring behind an already-dispatched window
  would shift the draw chain that seeded neighbors replay, so any
  sampled slot live ⇒ serial cadence (outputs stay byte-identical
  with overlap on or off — the equivalence suite pins it).

Correctness bar (the house invariant): outputs are bit-identical with
interleaving on or off — and with packing or overlap on or off.
Greedy and grammar-constrained slots are deterministic per slot;
seeded sampled slots draw from their own fold_in chain indexed by a
per-slot draw counter that advances only with picks the slot
participates in — all scheduling-order invariant.  (Unseeded sampled
streams depend on the global key stream by design; per-request seeds
exist precisely to opt out of that.)  The engine enforces the
mechanics: mid-window splices — and slots the owner releases while an
overlap window is in flight — land in the dispatched window's
``skip`` set so harvest never advances a lens or draw chain behind
their back.

Fault hook: ``serve.schedule`` fires at the top of every iteration
(error/hang kinds), and :meth:`IterationScheduler.supersede` lets the
crash supervisor invalidate an iteration a watchdog abandoned — the
abandoned worker re-checks the generation right after the hook and
bails before touching the engine (an outstanding dispatch-ahead
window is abandoned with it).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from tpu_k8s_device_plugin import obs
from tpu_k8s_device_plugin.resilience import faults

from .serving import AdmitState, ServingEngine, _knobs_live

# interleave granularity: how many prefill chunk DISPATCHES may ride
# one open window (a packed dispatch advances up to max_pack
# admissions but spends ONE unit — that is the point of packing).
# Bounds how far prefill can delay the window's harvest; the
# remainder rides the next window(s).
DEFAULT_PREFILL_BUDGET = 4

# ragged packed prefill: most admissions packed into one batched
# extend.  Each pack size in 2..max_pack is its own compiled extend
# shape, so the cap bounds the compile set (warm_packed pre-compiles
# it); 4 covers the common convoy widths without growing the set.
DEFAULT_MAX_PACK = 4

# batch-forming dwell at a fresh-batch boundary (the engine just went
# idle and admissions are landing): wait this long for stragglers so
# the whole convoy enters ONE synchronized window instead of desyncing
# into underfull ones.  First tokens already streamed at admit (eager
# resolve), so the dwell costs second-token latency only.
DEFAULT_SYNC_DWELL_S = 0.002

# adaptive-window growth cap, as a multiple of the configured window:
# the window may grow toward the smallest remaining per-request budget
# (fewer harvests when every stream still needs the steps) but never
# past FACTOR x the floor — the floor stays the operator's stream-
# pacing/shutdown-granularity knob, grown windows just amortize it
ADAPTIVE_WINDOW_FACTOR = 4


class SchedulerSuperseded(RuntimeError):
    """This iteration was invalidated (crash supervisor restarted the
    loop while a watchdog-abandoned worker still held it)."""


class Ticket:
    """One admission riding the scheduler: an engine
    :class:`~.serving.AdmitState` plus scheduling stamps.  ``slot`` is
    reserved from ``begin`` on; the request is live only after the
    ticket shows up in :class:`IterationResult` ``admitted``."""

    __slots__ = ("state", "t_begin", "t_done", "mid_window")

    def __init__(self, state: AdmitState, t_begin: float,
                 mid_window: bool):
        self.state = state
        self.t_begin = t_begin
        self.t_done = 0.0
        self.mid_window = mid_window

    @property
    def slot(self) -> int:
        return self.state.slot

    @property
    def chunks_done(self) -> int:
        return self.state.chunks_done

    @property
    def chunks_total(self) -> int:
        return self.state.chunks_total


class IterationResult:
    """What one :meth:`IterationScheduler.iterate` did: admissions
    that went live (their first token is in ``engine.output(slot)``),
    the decode output map (``{slot: [tokens]}`` for slots in the
    window/round), and how many decode steps ran."""

    __slots__ = ("admitted", "decoded", "steps")

    def __init__(self, admitted: List[Ticket],
                 decoded: Dict[int, List[int]], steps: int):
        self.admitted = admitted
        self.decoded = decoded
        self.steps = steps


class IterationScheduler:
    """Iteration-level scheduler over one :class:`ServingEngine`.

    Single-threaded by contract, like the engine it drives: exactly
    one loop calls :meth:`iterate`.  The owner supplies *pull*, called
    whenever the scheduler can take new work (``None`` = nothing
    waiting); it must create the ticket via :meth:`begin` and handle
    its own validation errors.

    With ``packed_prefill`` off (or an unpackable engine) one ticket
    is in flight at a time: admission is serial on the device anyway,
    and serializing tickets keeps sibling/repeat prompts hitting the
    prefix cache exactly as one-shot admission did (a prompt becomes a
    donor only once its splice lands).  With packing on, up to
    ``max_pack`` tickets prefill CONCURRENTLY through batched extends;
    splices stay strictly FIFO, and owners that care about sibling APC
    reuse defer conflicting pulls via :meth:`packing_conflict` (the
    HTTP server does), so the donor order a repeat prompt observes is
    unchanged."""

    def __init__(self, engine: ServingEngine, window: int = 8,
                 interleave: bool = True,
                 prefill_budget: int = DEFAULT_PREFILL_BUDGET,
                 pull: Optional[Callable[[], Optional[Ticket]]] = None,
                 on_admit: Optional[Callable[[Ticket], None]] = None,
                 budget_hint: Optional[
                     Callable[[int], Optional[int]]] = None,
                 sync_dwell_s: float = DEFAULT_SYNC_DWELL_S,
                 packed_prefill: bool = True,
                 max_pack: int = DEFAULT_MAX_PACK,
                 overlap: bool = True,
                 registry=None, recorder=None):
        if window < 1:
            raise ValueError("window must be >= 1")
        if prefill_budget < 1:
            raise ValueError("prefill_budget must be >= 1")
        if max_pack < 2:
            raise ValueError("max_pack must be >= 2")
        self.engine = engine
        self.window = window
        self.interleave = bool(interleave)
        self.prefill_budget = prefill_budget
        # packing needs a fixed chunk grid (the packed extend's shape)
        # and per-row-independent FFN math — the engine's _PrefillJob
        # re-checks per admission (plp jobs stay serial)
        self._packing = (bool(packed_prefill)
                         and engine.chunk is not None
                         and engine.model.n_experts == 0)
        self.max_pack = max_pack
        self.overlap = bool(overlap)
        self._pull = pull
        # called the moment an admission goes live (scheduler thread,
        # possibly MID-WINDOW): the owner streams the first token right
        # away instead of waiting for the window's harvest — TTFT stays
        # decoupled from the window size
        self._on_admit = on_admit
        # remaining-token hint per slot (None = unknown): lets the
        # window GROW past its floor when every running request still
        # needs that many steps — a batch-synchronized generation
        # harvests once instead of once per `window` steps, without
        # adding garbage decode (the window never outruns the smallest
        # remaining budget)
        self._budget_hint = budget_hint
        self.sync_dwell_s = sync_dwell_s
        self.recorder = recorder
        self._pending: List[Ticket] = []   # FIFO; len <= pack limit
        self._await_first: List[Ticket] = []  # finalized, pre-1st-step
        self._ahead: Optional[Tuple[object, int]] = None  # (handle, n)
        self._gen = 0                         # supersession counter
        self._m_chunk = self._m_first = None
        self._m_overlap_idle = self._m_overlap_windows = None
        self._g_prefill = self._g_decode = None
        # per-window phase breakdown + device duty cycle: cumulative
        # wall seconds by phase (dispatch = host-side scan_dispatch
        # work, harvest = blocking device sync incl. spec/jump/step
        # rounds, stream = the owner's emit work between iterations,
        # idle = the owner waiting for work).  Single writer (the
        # scheduler thread); the scrape-time duty collector only reads
        self._phase_acc: Dict[str, float] = {
            "dispatch": 0.0, "harvest": 0.0, "stream": 0.0,
            "idle": 0.0}
        # the phase executing RIGHT NOW (begin_phase sets it at each
        # section start) — the continuous profiler's phase_fn reads
        # this to tag stack samples, so a profile slice can split
        # "time under dispatch" from "time blocked in harvest".
        # Single writer (the loop's thread); racy reads are fine, a
        # sample tagged one phase late is still an honest sample.
        self.phase: str = "idle"
        self._phase_hist: Dict[str, object] = {}
        self._m_phase = None
        self._g_duty = None
        self._duty_snap = dict(self._phase_acc)
        if registry is not None:
            self._m_chunk = registry.histogram(
                "tpu_serve_prefill_chunk_seconds",
                "One prefill-chunk dispatch on the scheduler thread "
                "(async: device time overlaps the open decode window; "
                "a packed dispatch advances several admissions and "
                "observes once).",
                buckets=obs.FAST_BUCKETS_S)
            self._m_first = registry.histogram(
                "tpu_serve_admit_to_first_step_seconds",
                "Admission handoff to the slot's first decode-window "
                "dispatch (prefill + finalize, interleave included).",
                buckets=obs.LATENCY_BUCKETS_S)
            self._m_overlap_idle = registry.histogram(
                "tpu_serve_overlap_idle_seconds",
                "Device time a dispatch-ahead window still had left "
                "when its harvest was reached — overlap the host work "
                "did NOT cover (0-bucket harvests mean the window was "
                "already done: full overlap).",
                buckets=obs.FAST_BUCKETS_S)
            self._m_overlap_windows = registry.counter(
                "tpu_serve_overlap_windows_total",
                "Decode windows dispatched AHEAD of their harvest "
                "(double-buffered dispatch/harvest overlap).")
            # materialize the default children so overlap-off (or
            # not-yet-overlapped) servers still render the families
            # as zeros — dashboards see ONE schema
            self._m_overlap_idle._default()
            self._m_overlap_windows.inc(0)
            g = registry.gauge(
                "tpu_serve_scheduler_queue_depth",
                "Iteration-scheduler work-queue depth by kind: "
                "prefill (admissions in flight), decode (active "
                "slots).", ("kind",))
            self._g_prefill = g.labels(kind="prefill")
            self._g_decode = g.labels(kind="decode")
            self._m_phase = registry.histogram(
                "tpu_serve_window_phase_seconds",
                "Scheduler-loop time by phase: dispatch (host-side "
                "window dispatch), harvest (blocking device sync — "
                "scan harvest, spec/jump rounds, endgame steps), "
                "stream (the owner's emit/stream-write work between "
                "iterations), idle (waiting for work).",
                ("phase",), buckets=obs.FAST_BUCKETS_S)
            # one schema from boot: every phase child renders (zeros)
            # whether or not the loop has reached it yet
            self._phase_hist = {
                p: self._m_phase.labels(phase=p)
                for p in self._phase_acc}
            self._g_duty = registry.gauge(
                "tpu_serve_device_duty_cycle",
                "Fraction of scheduler-loop wall time the device was "
                "kept busy (dispatch+harvest over all phases) since "
                "the previous scrape — the direct measurement of the "
                "prefill-gap estimates.")
            self._g_duty.set(0.0)
            registry.on_collect(self._collect_duty)

    # -- intake -------------------------------------------------------------

    def begin(self, prompt, **admit_kwargs) -> Ticket:
        """Validate + reserve via ``engine.begin_admit`` and queue the
        ticket.  Called from inside the owner's *pull* callback (same
        thread as iterate — the engine has one owner).  Raises
        whatever begin_admit raises; nothing is queued then."""
        st = self.engine.begin_admit(prompt, **admit_kwargs)
        t = Ticket(st, time.perf_counter(),
                   mid_window=self.engine.scan_inflight)
        self._pending.append(t)
        return t

    def cancel(self, ticket: Ticket) -> None:
        """Abandon a queued admission (client went away)."""
        if ticket in self._pending:
            self._pending.remove(ticket)
            self.engine.abort_admit(ticket.state)

    def busy(self) -> bool:
        """Admission work still queued, or a dispatch-ahead window
        still awaiting its harvest?"""
        return bool(self._pending or self._await_first
                    or self._ahead is not None)

    def pending_tickets(self) -> List[Ticket]:
        return list(self._pending)

    def packing_conflict(self, prompt) -> bool:
        """Would beginning *prompt* NOW forfeit an APC match a serial
        admission would have had?  True when packing is active and an
        in-flight pending admission shares a >= chunk-grid prefix with
        *prompt* (the donor it would match has not spliced yet).
        Owners defer such pulls until the conflicting ticket lands —
        tokens would be identical either way, but sibling copies and
        repeat prompts would pay a full cold prefill the serial path
        never paid."""
        if not self._packing or not self._pending:
            return False
        c = self.engine.chunk
        p = np.asarray(prompt, np.int32).ravel()
        if len(p) < c:
            return False
        for t in self._pending:
            q = t.state.prompt_np[0]
            if len(q) >= c and np.array_equal(p[:c], q[:c]):
                return True
        return False

    def supersede(self) -> None:
        """Invalidate the current iteration (crash-supervisor restart
        path): a watchdog-abandoned worker re-checks the generation
        right after the fault hook and bails before touching the
        engine.  Pending admissions are aborted — their requests get
        the supervisor's 503 — and an outstanding dispatch-ahead
        window is abandoned (its slots are about to be released)."""
        self._gen += 1
        if self._ahead is not None:
            self.engine.scan_abandon(self._ahead[0])
            self._ahead = None
        for t in self._pending:
            try:
                self.engine.abort_admit(t.state)
            except RuntimeError:
                pass  # already spliced: the supervisor releases slots
        self._pending.clear()
        self._await_first.clear()

    # -- the iteration ------------------------------------------------------

    def _check(self, gen: int) -> None:
        if gen != self._gen:
            raise SchedulerSuperseded(
                "scheduler restarted while this iteration was "
                "abandoned by the watchdog")

    def _pull_limit(self) -> int:
        return self.max_pack if self._packing else 1

    def _pull_tickets(self) -> None:
        """Take new work while there is a free slot and ticket room —
        one in-flight ticket serially, up to ``max_pack`` when packing
        (concurrent prefills are what the batched extend packs)."""
        if self._pull is None:
            return
        limit = self._pull_limit()
        while len(self._pending) < limit and self.engine.free_slots():
            if self._pull() is None:
                return

    def _pack_group(self) -> List[AdmitState]:
        """The states the next prefill dispatch advances: the head
        alone (serial, or an unpackable head — plp jobs), or every
        packable in-flight state up to ``max_pack``."""
        head = self._pending[0].state
        if (not self._packing or head.gen is None
                or not head.gen.packable):
            return [head]
        group = [t.state for t in self._pending
                 if t.state.gen is not None and t.state.gen.packable]
        return group[:self.max_pack]

    def _admit_work(self, budget: int) -> List[Ticket]:
        """Admission work: spend up to *budget* prefill DISPATCHES
        (each serial or packed — a packed dispatch advances every
        in-flight packable admission one chunk), finalize-dispatch
        every admission that completes IN FIFO ORDER, and pull
        replacements as slots allow — multiple admissions can land
        inside ONE open window (slot turnover refills the whole batch
        without waiting a window per request).  Returns the
        splice-dispatched tickets; the caller resolves them after the
        window's harvest."""
        fins: List[Ticket] = []
        eng = self.engine
        n = budget
        while True:
            if len(self._pending) < self._pull_limit():
                self._pull_tickets()
            # splice strictly head-first: a later ticket may finish
            # its chunks early, but it becomes live (and an APC donor)
            # only in arrival order — the serial path's order
            while self._pending and self._pending[0].state.ready:
                t = self._finalize_dispatch()
                if t is not None:
                    # resolve EAGERLY: the first-token pick depends
                    # only on the prefill chain, so on runtimes that
                    # execute independent work concurrently the sync
                    # lands mid-window and the first token streams
                    # before the window closes (worst case it waits
                    # for the window — where it used to wait anyway)
                    fins += self._finalize_resolve(t)
                self._pull_tickets()
            if not self._pending or n <= 0:
                return fins
            group = self._pack_group()
            t0 = time.perf_counter()
            if len(group) >= 2:
                # one resident pack session: run until the shortest
                # member's last chunk (or the budget) — pack/unpack
                # copies amortize over the whole session
                rounds = min(n, min(st.gen.remaining for st in group))
                eng.admit_step_packed(group, rounds)
                n -= rounds
            else:
                eng.admit_step(group[0])
                n -= 1
            if self._m_chunk is not None:
                self._m_chunk.observe(time.perf_counter() - t0)

    def _finalize_dispatch(self) -> Optional[Ticket]:
        """Splice a fully-prefilled head ticket (device dispatch only;
        the first-token pick stays on device until resolve)."""
        if self._pending and self._pending[0].state.ready:
            t = self._pending.pop(0)
            self.engine._finish_admit_dispatch(t.state)
            return t
        return None

    def _finalize_resolve(self, t: Optional[Ticket]) -> List[Ticket]:
        if t is None:
            return []
        self.engine._finish_admit_resolve(t.state)
        t.t_done = time.perf_counter()
        self._await_first.append(t)
        if self._on_admit is not None:
            self._on_admit(t)
        return [t]

    def _drain_admissions(self) -> List[Ticket]:
        """Admit everything waiting, one-shot style (interleave off /
        spec & jump rounds / fresh-batch boundaries): pull → prefill
        to completion → finalize, until no capacity or no work.
        Serially this is byte-for-byte the admission order the
        pre-scheduler loop produced; with packing the prefills batch
        but the finalize order is unchanged."""
        return self._admit_work(1 << 30)

    def _note_first_step(self) -> None:
        """A decode dispatch is about to include every live slot:
        observe admit→first-step for freshly admitted ones."""
        if not self._await_first:
            return
        now = time.perf_counter()
        if self._m_first is not None:
            for t in self._await_first:
                if self.engine.active[t.slot]:
                    self._m_first.observe(now - t.t_begin)
        self._await_first.clear()

    def begin_phase(self, phase: str) -> None:
        """Mark *phase* as the section executing NOW (profiler tag —
        see ``self.phase``).  Time accounting still happens at section
        end via :meth:`note_phase`; callers pair the two."""
        if phase not in self._phase_acc:
            raise ValueError(f"unknown scheduler phase {phase!r}")
        self.phase = phase

    def note_phase(self, phase: str, dt: float) -> None:
        """Account *dt* wall seconds of scheduler-loop time under
        *phase* (dispatch | harvest | stream | idle).  dispatch and
        harvest are accounted internally; the loop's OWNER reports its
        stream-write and idle-wait time through this hook (the
        scheduler cannot see between its own iterations)."""
        if phase not in self._phase_acc:
            raise ValueError(f"unknown scheduler phase {phase!r}")
        if dt < 0:
            return
        self._phase_acc[phase] += dt
        child = self._phase_hist.get(phase)
        if child is not None:
            child.observe(dt)  # type: ignore[attr-defined]

    def _collect_duty(self) -> None:
        """Scrape-time device duty cycle: dispatch+harvest seconds
        over all phase seconds since the PREVIOUS scrape, falling back
        to the lifetime ratio on the first (delta-free) scrape."""
        cur = dict(self._phase_acc)
        prev = self._duty_snap
        busy = (cur["dispatch"] - prev["dispatch"]
                + cur["harvest"] - prev["harvest"])
        total = sum(cur.values()) - sum(prev.values())
        if total <= 0.0:
            busy = cur["dispatch"] + cur["harvest"]
            total = sum(cur.values())
        self._duty_snap = cur
        if self._g_duty is not None and total > 0.0:
            self._g_duty.set(max(0.0, min(1.0, busy / total)))

    def _timed_dispatch(self, window: int) -> object:
        t0 = time.perf_counter()
        self.begin_phase("dispatch")
        handle = self.engine.scan_dispatch(window)
        self.note_phase("dispatch", time.perf_counter() - t0)
        return handle

    def _gauges(self) -> None:
        if self._g_prefill is not None:
            self._g_prefill.set(len(self._pending)
                                + len(self._await_first))
            self._g_decode.set(sum(self.engine.active))

    def _choose_window(self, consumed: Optional[Dict[int, int]] = None
                       ) -> int:
        """Window length for the next scan: the configured floor,
        grown in quantized floor-multiples toward the smallest
        remaining per-request budget (full engine only), capped by
        cache headroom.  < 1 means a slot ran out of cache (endgame
        step territory — never dispatched ahead).  *consumed* adjusts
        the owner's budget hints by tokens a just-harvested window
        produced that the owner has not streamed yet (the
        dispatch-ahead path runs BEFORE the owner's emit, so its raw
        hints are stale by exactly one window)."""
        eng = self.engine
        headroom = min(eng.model.max_len - eng.lens[s]
                       for s in range(eng.n_slots) if eng.active[s])
        window = self.window
        if self._budget_hint is not None and not eng.free_slots():
            # adaptive window, gated on a FULL engine: grow toward the
            # smallest remaining per-request budget (one harvest per
            # synchronized generation instead of one per `window`
            # steps, with no slot decoding garbage past its
            # retirement).  With free or reserved slots the floor
            # window stands — a request arriving moments after a long
            # window opened would otherwise sit it out entirely, which
            # costs far more than the extra harvests (measured: the
            # ungated version oscillated between 1.3x and 0.5x of the
            # gated throughput depending on client arrival phase)
            need = None
            for s in range(eng.n_slots):
                if not eng.active[s]:
                    continue
                h = self._budget_hint(s)
                if h is not None and consumed:
                    h -= consumed.get(s, 0)
                if h is None:
                    need = None
                    break
                need = h if need is None or h < need else need
            if need is not None and need > window:
                # QUANTIZED to whole multiples of the floor: n_steps
                # is a static scan argument, so every distinct window
                # length is its own XLA compile — free-running growth
                # turned staggered budgets into a compile storm
                # (measured: 5x throughput collapse).  Multiples of
                # the floor cap the compiled variants at
                # ADAPTIVE_WINDOW_FACTOR.  Round UP when the overshoot
                # is under half a floor (a 63-step batch runs one
                # 64-window, not 48+16 — the single garbage step costs
                # less than the extra harvest); otherwise down.
                k, rem = divmod(need, self.window)
                if rem and self.window - rem <= self.window // 2:
                    k += 1
                window = self.window * max(
                    1, min(ADAPTIVE_WINDOW_FACTOR, k))
        return min(window, headroom)

    def _maybe_dispatch_ahead(
            self, decoded: Optional[Dict[int, List[int]]] = None
    ) -> None:
        """Double-buffered dispatch: put the NEXT window on the device
        before returning from iterate, so the owner's host-side
        harvest/stream-write work between calls overlaps device
        compute instead of leaving it idle.  Engaged ONLY when the
        post-harvest state would choose a plain scan anyway AND —
        without the fused decode loop — no sampled knob is live: a
        sampled slot retiring behind an already-dispatched window
        would shift the draw accounting seeded neighbors replay —
        greedy/grammar windows have no draw stream, and a slot the
        owner releases mid-window lands in the handle's skip set, so
        output bytes are unchanged (the equivalence suite pins overlap
        on == off).  With ``fused_decode`` the sampled stand-down
        lifts: dispatch-ahead runs AFTER the previous harvest applied
        all draw/retirement accounting, the picked rows are
        independent per slot (a retired neighbor's key-stream rows
        produce only discarded tokens, same masking contract as
        run_scan), and boundaries the carry detects truncate at
        harvest — so sampled windows overlap byte-identically too, and
        only the budget-imminent check below still stands windows
        down.

        *decoded* — the harvest this iterate just returned, which the
        owner has NOT streamed yet — adjusts the budget hints: if any
        stream's remaining budget (net of the unstreamed tokens) is
        exhausted, the owner is about to release its slot, and a
        pre-dispatched window would decode a garbage column the whole
        width; stand down and let the serial path re-evaluate after
        the owner's emit (measured: skipping this check cost ~2x on
        synchronized-batch retirement — every batch turnover burned
        one to two full garbage windows)."""
        if not (self.overlap and self.interleave):
            return
        eng = self.engine
        if not any(eng.active):
            return
        if eng.spec_ready() or eng.forced_pending():
            return
        if not getattr(eng, "fused_decode", False) and \
                _knobs_live(eng.temps, eng.topks, eng.topps,
                            eng.minps, eng.pres, eng.freqs, eng.reps):
            return
        consumed = ({s: len(t) for s, t in decoded.items()}
                    if decoded else None)
        if self._budget_hint is not None:
            for s in range(eng.n_slots):
                if not eng.active[s]:
                    continue
                h = self._budget_hint(s)
                if h is None:
                    continue
                if consumed:
                    h -= consumed.get(s, 0)
                if h < 1:
                    return      # retirement imminent: serial cadence
        window = self._choose_window(consumed)
        if window < 1:
            return
        self._note_first_step()
        handle = self._timed_dispatch(window)
        self._ahead = (handle, window)
        if self._m_overlap_windows is not None:
            self._m_overlap_windows.inc()

    def _iterate_ahead(self, gen: int) -> IterationResult:
        """One iteration against a window dispatched by the PREVIOUS
        iterate: admission work overlaps it exactly as it would a
        same-iteration window (same mid-window splice semantics, same
        skip set), then the harvest's blocking sync covers whatever
        device time the host work did not already hide."""
        eng = self.engine
        handle, window = self._ahead
        self._ahead = None
        if faults.ACTIVE is not None:
            faults.ACTIVE.fire("serve.step")
            faults.ACTIVE.fire("serve.schedule")
        self._check(gen)
        fins = self._admit_work(self.prefill_budget)
        t0 = time.perf_counter()
        self.begin_phase("harvest")
        decoded = eng.scan_harvest(handle)
        dt = time.perf_counter() - t0
        self.note_phase("harvest", dt)
        if self._m_overlap_idle is not None:
            self._m_overlap_idle.observe(dt)
        self._maybe_dispatch_ahead(decoded)
        self._gauges()
        return IterationResult(fins, decoded, window)

    def iterate(self) -> IterationResult:
        """One scheduler iteration: admission work + at most one
        decode round (scan window / spec round / jump round / endgame
        step), interleaved when enabled.  The owner loops this."""
        gen = self._gen
        eng = self.engine
        if self._ahead is not None:
            # overlap mode: window N+1 is already on the device —
            # admission work rides it, then its harvest
            return self._iterate_ahead(gen)
        admitted: List[Ticket] = []
        fresh_batch = self.interleave and not any(eng.active)
        if not self.interleave or fresh_batch:
            # interleave off, or an idle engine with no window to
            # overlap (cold start / whole-batch turnover): admit
            # everything that fits one-shot style, so the next window
            # dispatches with FULL slots — underfull windows cost more
            # than unoverlapped prefill here
            admitted += self._drain_admissions()
            if fresh_batch and self.sync_dwell_s > 0:
                # batch forming: closed-loop convoys arrive a couple
                # of milliseconds apart; a short dwell lets the
                # stragglers in so the whole batch shares one
                # synchronized (growable) window.  Bounded: each round
                # must admit someone or we dispatch with what we have.
                while admitted and eng.free_slots():
                    time.sleep(self.sync_dwell_s)
                    more = self._drain_admissions()
                    if not more:
                        break
                    admitted += more
        else:
            self._pull_tickets()
        if not any(eng.active):
            self._gauges()
            return IterationResult(admitted, {}, 0)
        # chaos hooks (inert attribute checks when no --fault-spec):
        # fire between admission and the decode round, so a crashed
        # iteration's requests are already ticket-bound (the crash
        # supervisor's drain 503s them) and an armed fault never
        # crashes an idle loop.  serve.step is the legacy decode-step
        # site; serve.schedule is the scheduler's own (error/hang)
        if faults.ACTIVE is not None:
            faults.ACTIVE.fire("serve.step")
            faults.ACTIVE.fire("serve.schedule")
        # a watchdog-abandoned worker wakes from an injected hang
        # HERE: bail before any engine mutation can race the restarted
        # loop (real device hangs have no such guarantee — the
        # supervisor's restart budget and the pod replacement policy
        # are the backstop there, as with the probe watchdog)
        self._check(gen)
        if eng.spec_ready() or eng.forced_pending():
            # speculative / jump rounds are single sync calls with no
            # dispatch/harvest seam — admissions go in ahead of them,
            # exactly like the pre-scheduler loop
            admitted += self._drain_admissions()
            self._note_first_step()
            if eng.spec_ready():
                t0 = time.perf_counter()
                self.begin_phase("harvest")
                decoded = eng.spec_round()
                self.note_phase("harvest", time.perf_counter() - t0)
                self._gauges()
                return IterationResult(admitted, decoded, 1)
            if eng.forced_pending():
                t0 = time.perf_counter()
                self.begin_phase("harvest")
                decoded = eng.jump_round()
                self.note_phase("harvest", time.perf_counter() - t0)
                if decoded is not None:
                    self._gauges()
                    return IterationResult(admitted, decoded, 1)
            if not any(eng.active):
                self._gauges()
                return IterationResult(admitted, {}, 0)
        window = self._choose_window()
        if window < 1:
            # a slot ran out of cache: one step() retires it
            self._note_first_step()
            t0 = time.perf_counter()
            self.begin_phase("harvest")
            decoded = {s: [t] for s, t in eng.step().items()}
            self.note_phase("harvest", time.perf_counter() - t0)
            self._gauges()
            return IterationResult(admitted, decoded, 1)
        self._note_first_step()
        handle = self._timed_dispatch(window)
        fins: List[Ticket] = []
        if self.interleave:
            # the window is on the device; everything below overlaps
            # it: prefill chunks (serial or packed), NEW arrivals
            # (mid-window admission), and completed admissions'
            # splices + first-token picks — as many as the chunk
            # budget lands, so turnover refills every free slot inside
            # one window
            self._check(gen)
            fins = self._admit_work(self.prefill_budget)
        t0 = time.perf_counter()
        self.begin_phase("harvest")
        decoded = eng.scan_harvest(handle)
        self.note_phase("harvest", time.perf_counter() - t0)
        admitted += fins
        self._maybe_dispatch_ahead(decoded)
        self._gauges()
        return IterationResult(admitted, decoded, window)
