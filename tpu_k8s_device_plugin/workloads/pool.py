"""Pallas max-pool with an argmax-index backward (no select_and_scatter).

Why this exists: the AlexNet conv head is HBM-bandwidth-bound, and the
single most expensive op in it is not a conv — it is the max-pool
*backward*, which XLA lowers to ``select_and_scatter`` (~10 ms of the
~35 ms seg1 fwd+bwd at batch 4096 on v5e-1; see BASELINE.md).  Every
HLO-level reformulation measured worse: shifted strided slices OOM,
a custom-vjp argmax pool in pure XLA materializes its slices/pads
(2.4x slower), separable 1D pools lose to the 2D window.  So the pool
is the one op in the model worth a hand kernel:

* forward: one pass computes the window max AND a compact int8
  "which window offset won" index (first-match tie-break — the same
  ge-select semantics ``select_and_scatter`` uses);
* backward: a pure scatter of the pooled gradient through that index —
  reads dp + idx, writes dy, touching each element once.  No
  select_and_scatter, no re-read of the pre-pool activation.

Layout note (measured, not guessed): XLA keeps these big NHWC conv
activations in *batch-minor* tiling on TPU (batch rides the 128-lane
dim — that is how its convs stay MXU-efficient at 48..64 channels), so
the kernels here block over (H, W, C, B) with batch as the minor dim
and slice H/W as untiled major dims.  Feeding them the logical
``(B, H, W, C)`` array through a transpose costs nothing when the
producer already carries the batch-minor physical layout.

Strides/windows are static Python ints; VALID padding only (what the
model uses — flax ``nn.max_pool`` default).  On non-TPU backends the
kernels run in interpreter mode so CPU test meshes exercise the same
code path (convention from flash_attention.py).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU memory spaces; absent on some non-TPU installs
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    pltpu = None
    _VMEM = None


def _block_spec(shape, index_map):
    if _VMEM is not None:
        return pl.BlockSpec(shape, index_map, memory_space=_VMEM)
    return pl.BlockSpec(shape, index_map)


def _compiler_params(interpret):
    """Both grid dims are embarrassingly parallel (distinct channel and
    batch slabs), and the full-spatial blocks plus their parity-plane
    temporaries need more than the default 16 MB scoped-VMEM stack —
    raise it (v5e has 128 MB VMEM; the blocks are sized so kernel
    footprint stays ~4x block, well under)."""
    if pltpu is None or interpret:
        return None
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel"),
        vmem_limit_bytes=100 * 1024 * 1024,
    )


def _offsets(window: int):
    return [(di, dj) for di in range(window) for dj in range(window)]


def _out_dim(size: int, window: int, stride: int) -> int:
    return (size - window) // stride + 1


_LANES = 128


def _pick_cb(c: int, itemsize: int) -> int:
    """Channel block = the dtype's sublane tile (16 for 2-byte, 8 for
    4-byte): the smallest block with zero sublane padding.  Bigger
    blocks only grow VMEM pressure — each (cb, 128-batch) slab already
    streams the full spatial extent."""
    cb = 32 // itemsize
    while c % cb:
        cb //= 2
    return max(cb, 1)


def _plane_dims(size: int, window: int, stride: int) -> int:
    """Rows per parity plane: enough to cover every offset's window."""
    out = _out_dim(size, window, stride)
    return max(-(-size // stride), (window - 1) // stride + out)


def _parity_planes(x, window, stride):
    """Split (H, W, ...) into stride x stride parity planes so that every
    strided window slice becomes a static unit-stride slice (Mosaic has
    no >2D gather; strided slices on loaded values lower to gathers).
    Pads with -inf, which never wins a max and never first-matches
    unless the real data is -inf too."""
    h, w = x.shape[0], x.shape[1]
    s = stride
    hh = _plane_dims(h, window, s)
    ww = _plane_dims(w, window, s)
    xp = jnp.pad(x, ((0, hh * s - h), (0, ww * s - w)) +
                 ((0, 0),) * (x.ndim - 2),
                 constant_values=_neg_inf(x.dtype))
    xr = xp.reshape((hh, s, ww, s) + x.shape[2:])
    # one int index at a time: multi-axis integer indexing lowers to
    # gather/scatter, which Mosaic does not implement beyond 2D
    return {(pr, pc): xr[:, pr][:, :, pc]
            for pr in range(s) for pc in range(s)}


def _neg_inf(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(-jnp.inf, dtype)
    return jnp.array(jnp.iinfo(dtype).min, dtype)


def _window_slice(planes, di, dj, oh, ow, stride):
    p = planes[(di % stride, dj % stride)]
    r0, c0 = di // stride, dj // stride
    return p[r0:r0 + oh, c0:c0 + ow]


def _first_match_idx(cands_f32, max_f32):
    """First-match argmax over *cands_f32* (a list of same-shaped f32
    tensors) against their elementwise max, as an f32 index tensor —
    the ge-select tie-break select_and_scatter uses.  Mask ARITHMETIC,
    not boolean algebra, and f32 compares (exact for bf16 inputs):
    i1 vectors from different-width compares carry incompatible Mosaic
    layouts and the VPU has no bf16 cmpf — the load-bearing rules for
    every kernel that shares this (pool fwd, fused conv+pool)."""
    one = jnp.ones((), jnp.float32)
    idx = jnp.zeros_like(max_f32)
    found = jnp.zeros_like(max_f32)
    for k, t in enumerate(cands_f32):
        hit = (t == max_f32).astype(jnp.float32) * (one - found)
        idx = idx + jnp.full((), k, jnp.float32) * hit
        found = found + hit
    return idx


def _fwd_kernel(window, stride, oh, ow, x_ref, y_ref, idx_ref):
    # block shapes: x (H, W, cb, bb), y/idx (oh, ow, cb, bb)
    planes = _parity_planes(x_ref[...], window, stride)
    y = None
    for di, dj in _offsets(window):
        s = _window_slice(planes, di, dj, oh, ow, stride)
        y = s if y is None else jnp.maximum(y, s)
    # idx via the shared first-match rule (_first_match_idx documents
    # the Mosaic layout/compare constraints); 0..window^2-1 is exact
    # in f32 for any sane window
    cands = [
        _window_slice(planes, di, dj, oh, ow, stride).astype(jnp.float32)
        for di, dj in _offsets(window)
    ]
    idx = _first_match_idx(cands, y.astype(jnp.float32))
    y_ref[...] = y
    idx_ref[...] = idx.astype(jnp.int8)


def _bwd_kernel(window, stride, h, w, idx_ref, dp_ref, dy_ref):
    # block shapes: idx/dp (oh, ow, cb, bb), dy (H, W, cb, bb).
    #
    # The scatter "place dp[i,j] at (stride*i+di, stride*j+dj)" is not
    # expressible as a strided .at[].add under Mosaic (gather/scatter is
    # 2D-only), so build dy from stride**2 parity planes instead: plane
    # (pr, pc) holds rows/cols congruent to (pr, pc) mod stride, every
    # offset's contribution is a static unit-stride pad into its plane,
    # and the planes interleave back via a static set + reshape.
    # same layout-homogeneity rule as the forward: compare in f32 (no
    # bf16/int cmp on the VPU) and use mask multiplication, never i1
    # selects
    idx = idx_ref[...].astype(jnp.float32)
    dp = dp_ref[...]
    oh, ow = dp.shape[0], dp.shape[1]
    s = stride
    hh = max(-(-h // s), (window - 1) // s + oh)
    ww = max(-(-w // s), (window - 1) // s + ow)
    planes = {}
    for k, (di, dj) in enumerate(_offsets(window)):
        mask = (idx == jnp.full((), k, jnp.float32)).astype(dp.dtype)
        contrib = mask * dp
        pr, pc = di % s, dj % s
        r0, c0 = di // s, dj // s
        p = jnp.pad(contrib,
                    ((r0, hh - oh - r0), (c0, ww - ow - c0),
                     (0, 0), (0, 0)))
        key = (pr, pc)
        planes[key] = p if key not in planes else planes[key] + p
    # Interleave the planes back with stacks + reshapes only: value
    # updates (.at[].set / dynamic_update_slice) have no Mosaic
    # lowering, but concatenate/reshape on major dims do.
    rows = []
    for pr in range(s):
        cols = jnp.stack([planes[(pr, pc)] for pc in range(s)], axis=2)
        rows.append(cols.reshape((hh, ww * s) + dp.shape[2:]))
    z = jnp.stack(rows, axis=1)
    dy = z.reshape((hh * s, ww * s) + dp.shape[2:])[:h, :w]
    dy_ref[...] = dy


def _to_hwcb(x, bpad):
    xt = x.transpose(1, 2, 3, 0)  # (H, W, C, B): batch-minor
    if bpad:
        xt = jnp.pad(xt, ((0, 0),) * 3 + ((0, bpad),))
    return xt


def _to_bhwc(x, b):
    return x.transpose(3, 0, 1, 2)[:b]


def _bpad(b: int) -> int:
    """Pad batch up to a multiple of the 128-lane tile: batch is the
    minor (lane) dim, and a short minor dim pads to 128 anyway — at
    16x the memory.  Real training batches are multiples of 128; the
    pad only triggers on small test shapes."""
    return (-b) % _LANES


def _batch_tiling(b: int, interpret: bool):
    """(bpad, lane block) for the batch-minor dim.  On TPU the lane
    dim tiles at 128; in interpret mode (CPU tests/fallback) there is
    no lane hardware and padding a tiny test batch to 128 would be up
    to 32x wasted arithmetic — use the true batch as the one block."""
    if interpret:
        return 0, b
    return _bpad(b), _LANES


def _pool_fwd_impl(x, window, stride, interpret):
    b, h, w, c = x.shape
    oh = _out_dim(h, window, stride)
    ow = _out_dim(w, window, stride)
    bpad, lanes = _batch_tiling(b, interpret)
    bt = b + bpad
    cb = _pick_cb(c, x.dtype.itemsize)
    xt = _to_hwcb(x, bpad)
    grid = (c // cb, bt // lanes)
    y, idx = pl.pallas_call(
        functools.partial(_fwd_kernel, window, stride, oh, ow),
        grid=grid,
        in_specs=[
            _block_spec((h, w, cb, lanes), lambda ci, bi: (0, 0, ci, bi)),
        ],
        out_specs=[
            _block_spec((oh, ow, cb, lanes),
                        lambda ci, bi: (0, 0, ci, bi)),
            _block_spec((oh, ow, cb, lanes),
                        lambda ci, bi: (0, 0, ci, bi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((oh, ow, c, bt), x.dtype),
            jax.ShapeDtypeStruct((oh, ow, c, bt), jnp.int8),
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(xt)
    return _to_bhwc(y, b), idx


def _pool_bwd_impl(idx, dp, xshape, window, stride, interpret):
    b, h, w, c = xshape
    oh = _out_dim(h, window, stride)
    ow = _out_dim(w, window, stride)
    bpad, lanes = _batch_tiling(b, interpret)
    bt = b + bpad
    cb = _pick_cb(c, dp.dtype.itemsize)
    dpt = _to_hwcb(dp, bpad)
    grid = (c // cb, bt // lanes)
    dy = pl.pallas_call(
        functools.partial(_bwd_kernel, window, stride, h, w),
        grid=grid,
        in_specs=[
            _block_spec((oh, ow, cb, lanes),
                        lambda ci, bi: (0, 0, ci, bi)),
            _block_spec((oh, ow, cb, lanes),
                        lambda ci, bi: (0, 0, ci, bi)),
        ],
        out_specs=_block_spec(
            (h, w, cb, lanes), lambda ci, bi: (0, 0, ci, bi)),
        out_shape=jax.ShapeDtypeStruct((h, w, c, bt), dp.dtype),
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(idx, dpt)
    return _to_bhwc(dy, b)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def max_pool(x, window: int = 3, stride: int = 2,
             interpret: Optional[bool] = None):
    """VALID max-pool over NHWC, drop-in for
    ``flax.linen.max_pool(x, (window, window), (stride, stride))``,
    with a scatter backward instead of select_and_scatter.  Gradient
    tie-break matches XLA's: first window offset in row-major order."""
    y, _ = _pool_fwd_impl(x, window, stride, _resolve(interpret))
    return y


def _resolve(interpret):
    if interpret is None:
        return jax.default_backend() != "tpu"
    return interpret


def _vjp_fwd(x, window, stride, interpret):
    y, idx = _pool_fwd_impl(x, window, stride, _resolve(interpret))
    return y, (idx, x.shape)


def _vjp_bwd(window, stride, interpret, res, dp):
    idx, xshape = res
    dy = _pool_bwd_impl(
        idx, dp, xshape, window, stride, _resolve(interpret))
    return (dy,)


max_pool.defvjp(_vjp_fwd, _vjp_bwd)
