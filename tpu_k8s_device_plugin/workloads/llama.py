"""Llama-family model configs over the native transformer stack.

The reference's serving example deploys Llama-class models through an
opaque vLLM image (/root/reference/example/vllm-serve/deployment.yaml:
28-56 serves Mistral-7B; our example/vllm-serve-tpu targets
Llama-3-8B).  This module makes that model family a first-class citizen
of the native stack instead: the same ``TransformerLM`` /
``DecodeTransformerLM`` modules, configured with the three Llama
architecture ingredients —

* **GQA** (``n_kv_heads < n_heads``): K/V project to 8 heads serving
  32 query heads, so the serving KV cache (the decode-bandwidth bound)
  shrinks 4x;
* **SwiGLU MLP** (``ffn="swiglu"``): down(silu(gate) ⊙ up);
* **RoPE theta 500000** (Llama-3's long-context base).

RMSNorm and rotary embeddings were already the stack's defaults.

Configs are plain frozen dataclasses; ``train_model(cfg)`` /
``decoder(cfg)`` build the training and serving twins with identical
parameter trees, so a trained tree (or converted checkpoint) drops
into serving unchanged, and ``inference.quantize_lm_params`` applies
as-is (mlp_gate quantizes with the other projections).

Memory note for the 8B config on one v5e (16 GB HBM): bf16 weights are
~16 GB — does not fit; weight-only int8 (~8 GB kernels + the 2.1 GB
f32 embed quantize keeps as-is ≈ 10.4 GB) fits with room for the GQA
cache (8 kv-heads × 128 dims × 32 layers ≈ 131 kB/token at bf16, so
4k context ≈ 0.54 GB at batch 1).  That is the single-chip serving
configuration; bf16 serving of 8B wants a 2-chip ``model``-axis
mesh.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from .inference import DecodeTransformerLM, make_decoder
from .transformer import COMPUTE_DTYPE, TransformerLM


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab: int
    d_model: int
    n_heads: int
    n_kv_heads: int
    n_layers: int
    d_ff: int
    rope_theta: float = 500000.0
    max_len: int = 8192

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def n_params(self) -> int:
        """Parameter count (embed + blocks + head), for sizing checks."""
        d, f, v = self.d_model, self.d_ff, self.vocab
        kv = self.n_kv_heads * self.head_dim
        per_block = (
            d * (d + 2 * kv)      # qkv
            + d * d               # out_proj
            + 3 * d * f           # gate, up, down
            + 2 * d               # two RMSNorm scales
        )
        return v * d + self.n_layers * per_block + d + d * v


# Llama-3-8B (meta-llama/Meta-Llama-3-8B): 32 layers, d=4096, 32 heads /
# 8 KV heads, d_ff=14336, vocab 128256, rope theta 500000
LLAMA3_8B = LlamaConfig(
    vocab=128256, d_model=4096, n_heads=32, n_kv_heads=8,
    n_layers=32, d_ff=14336,
)

# Llama-2-7B-shaped: MHA (n_kv == n_heads), theta 10000, vocab 32000
LLAMA2_7B = LlamaConfig(
    vocab=32000, d_model=4096, n_heads=32, n_kv_heads=32,
    n_layers=32, d_ff=11008, rope_theta=10000.0, max_len=4096,
)

# Llama-3.2-1B-shaped: the standard speculative DRAFT for the 8B
# target (same 128k vocab + tokenizer family, ~8x fewer FLOPs/token)
LLAMA32_1B = LlamaConfig(
    vocab=128256, d_model=2048, n_heads=32, n_kv_heads=8,
    n_layers=16, d_ff=8192,
)

# scaled-down config with the full Llama shape grammar (GQA 4:1, SwiGLU,
# big theta) for tests and CPU meshes
TINY_LLAMA = LlamaConfig(
    vocab=256, d_model=128, n_heads=8, n_kv_heads=2,
    n_layers=2, d_ff=352, max_len=128,
)

# 1-layer draft for TINY_LLAMA (CPU-mesh spec-decode benchmarks)
TINY_DRAFT = LlamaConfig(
    vocab=256, d_model=64, n_heads=4, n_kv_heads=2,
    n_layers=1, d_ff=128, max_len=128,
)


def train_model(
    cfg: LlamaConfig, dtype: Any = COMPUTE_DTYPE, **overrides
) -> TransformerLM:
    """Training-side model for *cfg* (attn_fn et al. via overrides)."""
    return TransformerLM(
        vocab=cfg.vocab, d_model=cfg.d_model, n_heads=cfg.n_heads,
        n_layers=cfg.n_layers, d_ff=cfg.d_ff, dtype=dtype,
        n_kv_heads=cfg.n_kv_heads, ffn="swiglu",
        rope_theta=cfg.rope_theta, **overrides,
    )


def decoder(
    cfg: LlamaConfig,
    max_len: Optional[int] = None,
    quantized: Any = False,  # False | True (int8) | "int4"
    dtype: Any = COMPUTE_DTYPE,
) -> DecodeTransformerLM:
    """Serving-side twin (KV-cached; same param tree as train_model)."""
    return make_decoder(
        vocab=cfg.vocab, d_model=cfg.d_model, n_heads=cfg.n_heads,
        n_layers=cfg.n_layers, d_ff=cfg.d_ff,
        max_len=max_len or cfg.max_len, dtype=dtype,
        quantized=quantized, n_kv_heads=cfg.n_kv_heads, ffn="swiglu",
        rope_theta=cfg.rope_theta,
    )


def random_quantized_params(
    cfg: LlamaConfig, seed: int = 0, dtype: Any = COMPUTE_DTYPE,
    bits: int = 8,
):
    """Random weight-only-int8 parameter tree for *cfg*, built DIRECTLY
    in the quantized layout.

    For throughput benchmarking the weight values are irrelevant — only
    their bytes move — but the construction path matters a lot at 8B
    scale: materializing the bf16 tree (~16 GB) and then quantizing
    would not fit next to the int8 copy on one 16 GB chip.  Each leaf
    is created at its final dtype — int8 kernels, f32 scales, and an
    f32 embed/norms exactly like a real ``quantize_lm_params`` output
    (flax param dtype is f32 regardless of the compute dtype, and
    quantize keeps embeds/norms as-is) — so peak memory is the true
    serving footprint (~10.4 GB for the 8B config: 8 GB int8 kernels +
    2.1 GB f32 embed).  Tree layout matches
    ``quantize_lm_params(train_model(cfg) params)`` exactly (asserted
    in tests/test_llama.py)."""
    del dtype  # leaf dtypes are fixed by the real quantized layout
    if bits not in (4, 8):
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    # jax-native leaf construction (jax.random, not host numpy): the
    # builder must stay TRACEABLE so tensor-parallel serving can jit it
    # with out_shardings and materialize each leaf directly on its TP
    # shard (bench_serving.build_model_and_params) — numpy leaves would
    # bake full-size device-0 constants into the trace, peaking the
    # whole tree on one chip, the exact failure the sharded build
    # exists to avoid.  Eager calls behave as before.
    root = jax.random.PRNGKey(seed)
    leaf_counter = iter(range(1 << 20))

    def nk():
        return jax.random.fold_in(root, next(leaf_counter))

    d, f, v = cfg.d_model, cfg.d_ff, cfg.vocab
    hd = cfg.head_dim
    qkv_out = (cfg.n_heads + 2 * cfg.n_kv_heads) * hd

    def kern(din, dout):
        if bits == 4:
            # packed two-per-byte + group-wise scales, same layout
            # quantize_lm_params_int4 emits (Llama-3-8B kernels: ~4 GB)
            from .inference import _int4_group

            g = _int4_group(din)
            return {
                "kernel_int4": jax.random.randint(
                    nk(), (din, dout // 2), -128, 128, jnp.int8),
                "scale": jnp.full((din // g, dout), 0.01, jnp.float32),
            }
        return {
            "kernel_int8": jax.random.randint(
                nk(), (din, dout), -127, 128, jnp.int8),
            "scale": jnp.full((dout,), 0.01, jnp.float32),
        }

    def norm():
        return {"scale": jnp.ones((d,), jnp.float32)}

    params = {
        "embed": {
            "embedding": jax.random.normal(
                nk(), (v, d), jnp.float32) * 0.02
        },
        "final_norm": norm(),
        "lm_head": kern(d, v),
    }
    for i in range(cfg.n_layers):
        params[f"block_{i}"] = {
            "attn_norm": norm(),
            "mlp_norm": norm(),
            "qkv": kern(d, qkv_out),
            "out_proj": kern(d, d),
            "mlp_gate": kern(d, f),
            "mlp_up": kern(d, f),
            "mlp_down": kern(f, d),
        }
    return params
