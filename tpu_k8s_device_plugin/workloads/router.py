"""Multi-replica serving router: prefix-affinity + least-loaded + failover.

One engine behind one HTTP server is the per-replica throughput
ceiling; this module is the horizontal-scale tier above it — a thin,
dependency-free HTTP router that fronts N ``workloads.server``
replicas and multiplies aggregate tokens/sec while PRESERVING the
prefix-cache hit rates the paged-KV copy-on-write pool makes cheap
(the replica-routing posture of production serving stacks: a shared
prefix is only warm on the replica that decoded it last).

Deliberately jax-free (stdlib + ``obs`` + ``resilience`` only): the
router runs on any box — a 1-vCPU sidecar, the bench driver, a CI
runner — and never pays an accelerator runtime import for what is
pure socket work.

Replica discovery (slice-coordinator-style registration + heartbeats):
replicas self-register over ``POST /register`` with their address,
model id, and capacity (``workloads.server --register-with`` does this
on a loop); each re-registration is the heartbeat, and a replica that
stops heartbeating AND stops answering the ``/statz`` poll past
``replica_ttl_s`` is evicted.  No config files, no ordering: replicas
may register before or after the router takes traffic, and a restarted
router relearns the fleet from the next heartbeat round.

Routing is two-tier:

1. **Prefix affinity** — a consistent hash (SHA-1 ring, ``vnodes``
   virtual points per replica) over the prompt's leading
   ``prefix_chunk``-aligned tokens.  Repeat and shared-prefix traffic
   lands on the replica whose paged KV pool already holds those pages
   (the engine's APC matches whole admission chunks, so the hash key
   aligns to the same grid).  The ring depends ONLY on the sorted
   replica ids — the same prompt maps to the same replica across
   router restarts and registration orderings.
2. **Least-loaded fallback** — when the affinity target is down,
   breaker-open, or overloaded (queue depth + in-flight past
   ``overload_factor``x its capacity), the request falls back to the
   lowest ``(queue + in_flight) / capacity`` replica.  The load signal
   is each replica's ``/statz`` JSON snapshot (queue depth, in-flight,
   free KV pages, scheduler health), polled on a short cadence and
   cached — the hot path never parses Prometheus text or blocks on a
   health probe.

**Disaggregated prefill/decode (router v2).**  Replicas register a
role (``mixed`` | ``prefill`` | ``decode``); when both specialized
classes are routable, prefill-heavy requests (prompt length >=
``prefill_threshold``, or explicit unary completions) route
phase-aware: the prefill replica runs packed prefill only and answers
with its bit-exact KV checkpoint (``prefill_only`` marker), the
router ships that payload to a decode replica's internal
``POST /migrate``, and the decode replica resumes the slot and takes
over the client stream — outputs byte-identical to single-replica
serving (the DistServe/Splitwise phase split).  Every failure mode
falls back BEFORE any client byte: the request re-routes whole
through the normal path.  Role-filtered ring walks keep phase
affinity deterministic over the id+role set.

**Globally-correct tenant quotas.**  A SECOND hash ring (distinct
salt) pins each tenant to one replica (``tenant_pinning``), making
replica-local buckets/WFQ chains globally coherent per tenant; and
router-level token buckets (``tenant_quotas``, same grammar and
semantics as the serving flag) charge the same prompt+budget
estimate at route time — the arbiter when role routing overrides
pinning.  Either way a tenant's fleet-wide rate is RATE, not
RATE x replicas.

Failover rides the resilience layer: a per-replica
:class:`~tpu_k8s_device_plugin.resilience.CircuitBreaker` plus a
seeded :class:`~tpu_k8s_device_plugin.resilience.RetryPolicy`.  A
connect error or 5xx BEFORE any body byte was forwarded retries on the
next-best replica (the failed one excluded, its breaker recording the
failure); once streaming has started the router never re-frames or
replays — a mid-stream replica death terminates the stream with a
well-formed in-band error frame (JSON-lines or SSE, matching the
response content type) and opens the breaker so the next request
routes around the corpse.

Streaming is passed through BYTE-IDENTICAL: the router de-chunks the
replica's response and re-chunks the same bytes — it never parses,
buffers whole, or re-frames a stream (the equivalence suite pins
router-vs-direct byte equality for JSON-lines and SSE).  ``traceparent``
propagates through the hop as a child context and every response
carries ``X-Replica`` naming the replica that served it.

API:

  POST /generate | /v1/completions | /v1/chat/completions
                     proxied to one replica (affinity -> least-loaded)
  POST /register     replica registration + heartbeat:
                     {"address": "host:port", "replica_id"?, "model"?,
                      "capacity"?} -> {"ok": true, "interval_s": ...}
  GET  /healthz      200 when >= 1 routable replica, else 503
  GET  /replicas     the replica table (id, address, health, load)
  GET  /metrics      tpu_router_* families (Prometheus exposition;
                     OpenMetrics content negotiation like every other
                     surface)
  GET  /fleet/statz  one fleet snapshot: per-replica statz plus
                     aggregated queue/shed/goodput signals (built from
                     the cached statz — no fan-out on the read path)
  GET  /debug/traces[?trace_id=…]  the CROSS-REPLICA stitched span
                     tree: the router's route/proxy events merged with
                     every replica's timeline for the trace-id and
                     re-linked via the traceparent parent chain
                     (index of recent router traces without the param)
  GET  /debug/events the router's flight-recorder journal

Metric families::

    tpu_router_requests_total{replica,outcome}   ok | upstream_error |
                                 stream_abort | client_gone | shed |
                                 client_error | unroutable
    tpu_router_route_seconds         routing decision -> upstream
                                     response headers (per attempt)
    tpu_router_replica_healthy{replica}          1 routable, 0 not
    tpu_router_failovers_total       retries that moved a request to
                                     another replica
    tpu_router_affinity_hits_total   requests served by their
                                     prefix-affinity target
    tpu_router_shed_total{reason}    router-side 429/503 sheds
                                     (connections | no_replicas |
                                      tenant_quota)
    tpu_router_replica_evictions_total   stale replicas dropped
    tpu_router_migrations_total{outcome}   disagg KV migrations
                                     (ok | declined | fallback |
                                      prefill_unavailable |
                                      prefill_error)
    tpu_router_migrate_seconds       checkpoint ship: payload read ->
                                     /migrate response headers
    tpu_router_role_requests_total{role}   phases forwarded per
                                     replica role
    tpu_router_tenant_pins_total     requests served by their
                                     tenant-ring pinned replica
"""

from __future__ import annotations

import argparse
import hashlib
import http.client
import json
import logging
import os
import queue
import threading
import time
from bisect import bisect_left
from collections import OrderedDict
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, HTTPServer
from urllib.parse import parse_qs, quote, urlparse
from typing import (
    Any,
    Dict,
    List,
    Optional,
    Protocol,
    Set,
    Tuple,
    Type,
)

from tpu_k8s_device_plugin import obs, resilience
from tpu_k8s_device_plugin.resilience import faults

from .migrate import MIGRATE_CONTENT_TYPE
from .qos import TenantQuota, parse_tenant_quotas, resolve_quota

log = logging.getLogger(__name__)

# replica classes for disaggregated prefill/decode serving (the
# DistServe/Splitwise-style phase split): replicas advertise one via
# /register, the router routes phase-aware when both specialized
# classes are present
REPLICA_ROLES = ("mixed", "prefill", "decode")

# default prompt length (tokens) above which a request counts as
# prefill-heavy and rides the disagg path; unary requests qualify
# regardless (their whole latency IS prefill + one batch of decode)
DEFAULT_PREFILL_THRESHOLD = 128

# budget estimate for router-side tenant accounting when the request
# does not carry max_new_tokens/max_tokens (mirrors the serving CLI's
# --max-new-tokens default)
DEFAULT_BUDGET_ESTIMATE = 256

# the engine's default APC admission grid (ServingEngine
# prefix_chunk="auto" lowers to 32 when max_len allows): hashing on
# the same grid means two prompts sharing an APC-matchable prefix
# share an affinity key
DEFAULT_PREFIX_CHUNK = 32

# proxied endpoints (everything else on POST is 404)
PROXY_PATHS = ("/generate", "/v1/completions", "/v1/chat/completions")

# hop-by-hop headers the router owns itself and never copies through
_HOP_HEADERS = frozenset({
    "connection", "keep-alive", "transfer-encoding", "content-length",
    "te", "trailer", "upgrade", "proxy-connection", "server", "date",
})

_STREAM_READ = 65536  # upstream read granularity on the stream path


def _now() -> float:
    return time.monotonic()


def _sha1_int(data: bytes) -> int:
    return int.from_bytes(hashlib.sha1(data).digest()[:8], "big")


def affinity_key(body: Dict[str, Any],
                 prefix_chunk: int) -> Optional[bytes]:
    """The consistent-hash key for one request body, or None when the
    body carries nothing hashable (the replica will 400 it anyway).

    Token prompts hash their leading ``prefix_chunk``-aligned tokens —
    the engine's APC matches whole admission chunks, so requests that
    can share cached KV pages share a key (a sub-chunk prompt hashes
    whole: it can never APC-match, but determinism still holds).
    String prompts / chat messages hash their full text: the router
    cannot tokenize, so string affinity is exact-prefix-by-content —
    still deterministic, still repeat-friendly."""
    tokens = body.get("tokens")
    if tokens is None:
        tokens = body.get("prompt")
    if tokens is None:
        tokens = body.get("messages")
    if isinstance(tokens, list) and tokens and all(
            isinstance(t, int) and not isinstance(t, bool)
            for t in tokens):
        aligned = len(tokens) - len(tokens) % prefix_chunk
        key = tokens[:aligned] if aligned else tokens
        return b",".join(str(int(t)).encode() for t in key)
    if isinstance(tokens, str) and tokens:
        return tokens.encode("utf-8", "surrogatepass")
    if isinstance(tokens, list) and tokens and all(
            isinstance(m, dict) for m in tokens):
        # chat messages: the rendered prompt is the replica's business;
        # the JSON text is a stable stand-in for content affinity
        try:
            return json.dumps(tokens, sort_keys=True).encode()
        except (TypeError, ValueError):
            return None
    return None


@dataclass
class Replica:
    """One registered serving replica and its cached load signal."""

    rid: str
    address: str                      # "host:port"
    model: str = ""
    capacity: int = 0
    role: str = "mixed"               # mixed | prefill | decode
    registered_at: float = 0.0        # wall clock, for /replicas
    last_seen: float = 0.0            # monotonic: heartbeat OR statz
    statz: Dict[str, Any] = field(default_factory=dict)
    statz_at: float = 0.0             # monotonic stamp of the snapshot
    breaker: Optional[resilience.CircuitBreaker] = None
    # administratively out of rotation (POST /drain): heartbeats keep
    # flowing and in-flight streams finish, but pick() skips it — the
    # fleet reconciler's graceful-stop lever
    draining: bool = False

    def host_port(self) -> Tuple[str, int]:
        host, _, port = self.address.rpartition(":")
        return host, int(port)

    def load_score(self) -> float:
        """Normalized queue pressure for least-loaded ordering: lower
        is better.  An unknown snapshot scores a neutral 1.0 so a
        fresh replica takes traffic without being preferred over a
        provably-idle one."""
        if not self.statz:
            return 1.0
        depth = float(self.statz.get("queue_depth", 0)) \
            + float(self.statz.get("in_flight", 0))
        cap = float(self.capacity
                    or self.statz.get("capacity", 0) or 1.0)
        return depth / max(cap, 1.0)

    def overloaded(self, factor: float) -> bool:
        """Past the affinity overload gate?  Only a KNOWN snapshot can
        say yes — affinity is the default, not the exception."""
        if not self.statz:
            return False
        depth = float(self.statz.get("queue_depth", 0)) \
            + float(self.statz.get("in_flight", 0))
        cap = float(self.capacity
                    or self.statz.get("capacity", 0) or 1.0)
        return depth >= factor * max(cap, 1.0)

    def scheduler_alive(self) -> bool:
        if not self.statz:
            return True  # unknown: the breaker is the arbiter
        return bool(self.statz.get("scheduler_alive", True))


class _IncCounter(Protocol):
    """The slice of an obs counter child the pooled server needs."""

    def inc(self, amount: float = 1.0) -> None: ...


class _UpstreamError(Exception):
    """A pre-stream replica failure (connect error or 5xx): safe to
    retry on another replica — no body byte reached the client."""

    def __init__(self, message: str, status: int = 0) -> None:
        super().__init__(message)
        self.status = status


class _PooledRouterHTTPServer(HTTPServer):
    """Fixed-worker HTTP server for the router (the serving server's
    pooled-accept posture without importing its jax-heavy module):
    *workers* connections proxy concurrently, *workers* more wait, and
    overflow is shed 429 on the accept thread."""

    daemon_threads = True
    allow_reuse_address = True
    request_queue_size = 128

    _REJECT_BODY = (json.dumps({"error": {
        "message": "router connection limit reached; retry later",
        "type": "rate_limit_exceeded"}}) + "\n").encode()
    _REJECT = (b"HTTP/1.1 429 Too Many Requests\r\n"
               b"Content-Type: application/json\r\n"
               b"Retry-After: 1\r\n"
               b"Content-Length: %d\r\n"
               b"Connection: close\r\n\r\n" % len(_REJECT_BODY)
               ) + _REJECT_BODY

    def __init__(self, addr: Tuple[str, int],
                 handler: Type[BaseHTTPRequestHandler],
                 workers: int, shed: _IncCounter) -> None:
        super().__init__(addr, handler)
        self._conns: "queue.Queue[Optional[Tuple[Any, Any]]]" = \
            queue.Queue(maxsize=workers)
        self._shed = shed
        self._pool = [
            threading.Thread(target=self._worker,
                             name=f"router-http-{i}", daemon=True)
            for i in range(workers)]
        for t in self._pool:
            t.start()

    def process_request(self, request: Any,
                        client_address: Any) -> None:
        try:
            self._conns.put_nowait((request, client_address))
        except queue.Full:
            self._shed.inc()
            try:
                request.settimeout(0.5)
                request.sendall(self._REJECT)
                try:
                    request.recv(1 << 20)
                except OSError:
                    pass
            except OSError:
                pass
            self.shutdown_request(request)

    def _worker(self) -> None:
        while True:
            item = self._conns.get()
            if item is None:
                return
            request, client_address = item
            try:
                self.finish_request(request, client_address)
            except Exception:
                self.handle_error(request, client_address)
            finally:
                self.shutdown_request(request)

    def server_close(self) -> None:
        super().server_close()
        for _ in self._pool:
            try:
                self._conns.put_nowait(None)
            except queue.Full:
                break
        for t in self._pool:
            t.join(timeout=1)


class RouterServer:
    """The router tier: replica table + consistent-hash ring + proxy.

    >>> rt = RouterServer().start(port=0)
    >>> # replicas: python -m ...workloads.server --register-with \\
    >>> #     http://host:rt.port
    >>> rt.stop()
    """

    def __init__(self,
                 prefix_chunk: int = DEFAULT_PREFIX_CHUNK,
                 replica_ttl_s: float = 10.0,
                 statz_interval_s: float = 0.5,
                 max_connections: int = 64,
                 failover_attempts: int = 3,
                 overload_factor: float = 4.0,
                 vnodes: int = 64,
                 breaker_threshold: int = 2,
                 breaker_reset_s: float = 2.0,
                 connect_timeout_s: float = 5.0,
                 client_timeout_s: float = 600.0,
                 seed: Optional[int] = None,
                 registry: Optional[obs.Registry] = None,
                 flight_record_dir: Optional[str] = None,
                 flight_record_capacity: int = 4096,
                 disagg: bool = True,
                 prefill_threshold: int = DEFAULT_PREFILL_THRESHOLD,
                 tenant_quotas: Optional[
                     Dict[str, TenantQuota]] = None,
                 tenant_pinning: bool = True,
                 session_affinity: bool = True,
                 session_home_max: int = 4096,
                 default_budget: int = DEFAULT_BUDGET_ESTIMATE,
                 slo_policies: Optional[Dict[str, Any]] = None,
                 alert_rules: Optional[List[Any]] = None,
                 alert_interval_s: float = 5.0,
                 alert_window_scale: float = 1.0,
                 incident_dir: Optional[str] = None,
                 profiler_hz: float = 19.0
                 ) -> None:
        if prefix_chunk < 1:
            raise ValueError("prefix_chunk must be >= 1")
        if failover_attempts < 1:
            raise ValueError("failover_attempts must be >= 1")
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        if prefill_threshold < 1:
            raise ValueError("prefill_threshold must be >= 1")
        if default_budget < 1:
            raise ValueError("default_budget must be >= 1")
        self.prefix_chunk = prefix_chunk
        self.replica_ttl_s = replica_ttl_s
        self.statz_interval_s = statz_interval_s
        self.max_connections = max_connections
        self.failover_attempts = failover_attempts
        self.overload_factor = overload_factor
        self.vnodes = vnodes
        self.breaker_threshold = breaker_threshold
        self.breaker_reset_s = breaker_reset_s
        self.connect_timeout_s = connect_timeout_s
        self.client_timeout_s = client_timeout_s
        # disaggregated prefill/decode (router v2): phase-aware
        # routing + KV migration are engaged per request, only when
        # both specialized classes are registered and routable
        self.disagg = bool(disagg)
        self.prefill_threshold = prefill_threshold
        # router-level tenant accounting: the GLOBAL token buckets a
        # replica-local quota cannot be (an evenly-routed tenant got
        # RATE x N before), plus sticky tenant->replica pinning on a
        # SECOND hash ring so replica-local WFQ/quota state stays
        # coherent per tenant even without router buckets configured
        self.tenant_quotas: Dict[str, TenantQuota] = dict(
            tenant_quotas or {})
        self.tenant_pinning = bool(tenant_pinning)
        # session KV tiering (PR 20): a THIRD hash ring plus a bounded
        # last-served map route a returning conversation back to the
        # replica holding its warm KV; when the pick still lands
        # elsewhere (home sick/overloaded), the router MOVES the
        # parked checkpoint (/session/export -> /session/import)
        # before forwarding, so the session resumes instead of
        # re-prefilling.  Every move failure degrades to plain
        # forwarding — affinity is a latency optimization, never a
        # correctness dependency
        self.session_affinity = bool(session_affinity)
        if session_home_max < 1:
            raise ValueError("session_home_max must be >= 1")
        self.session_home_max = session_home_max
        self._session_home: "OrderedDict[str, str]" = OrderedDict()
        self.default_budget = default_budget
        self._lock = threading.Lock()
        self._replicas: Dict[str, Replica] = {}
        # the ring caches (point -> rid) sorted by point; rebuilt only
        # when the replica-ID SET changes, so lookups are O(log n).
        # _tring is the tenant-pinning ring: same ids, different salt,
        # so one replica's share of tenants is independent of its
        # share of prefix keys
        self._ring: List[Tuple[int, str]] = []
        self._tring: List[Tuple[int, str]] = []
        self._sring: List[Tuple[int, str]] = []
        self._stop = threading.Event()
        self._httpd: Optional[_PooledRouterHTTPServer] = None
        self._poller: Optional[threading.Thread] = None
        # seeded like every other resilience consumer: a chaos run
        # replays the same failover backoff schedule from its seed
        self.retry = resilience.RetryPolicy(
            max_attempts=failover_attempts, initial_backoff_s=0.02,
            max_backoff_s=0.25, seed=seed)
        self.registry = registry if registry is not None \
            else obs.Registry()
        reg = self.registry
        self._rmetrics = resilience.ResilienceMetrics(reg)
        self.recorder = obs.FlightRecorder(
            capacity=flight_record_capacity, registry=reg)
        if flight_record_dir:
            self.recorder.install_dump_handlers(flight_record_dir)
        self._m_requests = reg.counter(
            "tpu_router_requests_total",
            "Requests routed, by serving replica and outcome (ok, "
            "client_error, shed, upstream_error, stream_abort, "
            "client_gone, unroutable).", ("replica", "outcome"))
        self._m_route = reg.histogram(
            "tpu_router_route_seconds",
            "Routing decision through upstream response headers for "
            "one attempt (connect + request write + headers).",
            buckets=obs.FAST_BUCKETS_S)
        self._m_healthy = reg.gauge(
            "tpu_router_replica_healthy",
            "1 when the replica is routable (fresh + breaker not "
            "open + scheduler alive), else 0.", ("replica",))
        self._m_failovers = reg.counter(
            "tpu_router_failovers_total",
            "Pre-stream retries that moved a request onto another "
            "replica after a connect error or 5xx.")
        self._m_affinity = reg.counter(
            "tpu_router_affinity_hits_total",
            "Requests served by their prefix-affinity target replica "
            "(consistent hash over the chunk-aligned prompt prefix).")
        self._m_shed = reg.counter(
            "tpu_router_shed_total",
            "Router-side sheds by reason (connections = worker pool "
            "full at accept, no_replicas = nothing routable).",
            ("reason",))
        self._shed_conns = self._m_shed.labels(reason="connections")
        self._m_evictions = reg.counter(
            "tpu_router_replica_evictions_total",
            "Replicas evicted for staleness (no heartbeat and no "
            "/statz answer within the TTL).")
        # -- disaggregated prefill/decode -------------------------------
        self._m_migrations = reg.counter(
            "tpu_router_migrations_total",
            "KV-state migrations attempted by outcome: ok (prefill "
            "checkpoint resumed on a decode replica), declined (the "
            "prefill replica served the request whole), "
            "prefill_unavailable / prefill_error (fell back to "
            "normal routing before / after prefill), fallback (no "
            "decode replica accepted the checkpoint; the request "
            "re-ran normally).", ("outcome",))
        for oc in ("ok", "declined", "fallback"):
            self._m_migrations.labels(outcome=oc).inc(0)
        self._m_migrate_s = reg.histogram(
            "tpu_router_migrate_seconds",
            "Checkpoint ship time: prefill payload fully read to the "
            "decode replica's /migrate response headers (serialize + "
            "hop + resume admission).", buckets=obs.FAST_BUCKETS_S)
        self._m_role_requests = reg.counter(
            "tpu_router_role_requests_total",
            "Request phases forwarded, by serving-replica role "
            "(mixed = the homogeneous path; prefill + decode = the "
            "two halves of one disagg-routed request).", ("role",))
        for role in REPLICA_ROLES:
            self._m_role_requests.labels(role=role).inc(0)
        self._m_tenant_pins = reg.counter(
            "tpu_router_tenant_pins_total",
            "Requests served by their tenant-ring pinned replica "
            "(sticky tenant->replica placement).")
        self._m_tenant_pins.inc(0)
        self._m_session_moves = reg.counter(
            "tpu_router_session_moves_total",
            "Cross-replica session KV moves attempted when a "
            "returning session routed away from its home replica: "
            "ok (checkpoint exported + imported, warm resume), miss "
            "(home had nothing parked; plain re-prefill), error "
            "(export/import failed; plain re-prefill).", ("outcome",))
        for oc in ("ok", "miss", "error"):
            self._m_session_moves.labels(outcome=oc).inc(0)
        # plain int twin of shed{no_replicas}: fleet_statz surfaces it
        # so the reconciler can see demand arriving at an empty fleet
        # (replica statz cannot carry that signal when there are none)
        self._no_replica_total = 0
        reg.on_collect(self._collect_health)
        # -- fleet-level retention + alerting (PR 18) --------------------
        # the cached per-replica goodput blocks aggregate into bridge
        # gauges at collect time (HTTP scrape or TSDB tick), and the
        # router's OWN burn-rate rule pairs evaluate over the fleet
        # aggregate — so one drowning replica masked by an idle one
        # still pages here even when no single replica's local rules
        # fire.  Firing state rides /alerts and the /fleet/statz
        # firing_alerts roll-up the autoscaler reads.
        self._m_fleet_burn = reg.gauge(
            "tpu_router_fleet_burn_rate",
            "Fleet-aggregate error-budget burn rate per SLO class "
            "(max across replicas, from cached statz).", ("class",))
        self._m_fleet_goodput = reg.gauge(
            "tpu_router_fleet_goodput_ratio",
            "Fleet-aggregate goodput ratio per SLO class (window met "
            "over window total, summed across replicas).", ("class",))
        reg.on_collect(self._collect_fleet_goodput)
        self.scrape_meta = obs.ScrapeMeta(reg)
        self.tsdb = obs.TSDB(reg)
        self.alert_interval_s = float(alert_interval_s)
        policies = (dict(slo_policies) if slo_policies
                    else obs.default_slo_policies())
        rules = obs.burn_rate_rules(
            policies, metric="tpu_router_fleet_burn_rate",
            window_scale=alert_window_scale)
        rules.extend(alert_rules or ())
        self.alerts = obs.AlertEvaluator(
            self.tsdb, rules, recorder=self.recorder)
        # -- continuous profiling + fleet incident bundles (PR 19) -------
        # the router samples its OWN stacks (proxy workers, poller) and
        # on a fleet-level page additionally pulls every registered
        # replica's bundle fragments (statz / alerts / profile slice)
        # into replicas/<id>/ of ONE fleet bundle — an unreachable
        # replica degrades to an {unreachable: true} marker instead of
        # wedging the subscriber (chaos episode 16 SIGKILLs one to
        # prove it)
        self.profiler = obs.SamplingProfiler(
            reg, hz=profiler_hz,
            active_fn=lambda: len(self._replicas))
        self.incident_dir = incident_dir
        self._incidents: Optional[obs.IncidentManager] = None
        if incident_dir:
            self._incidents = obs.IncidentManager(
                incident_dir, self.alerts, registry=reg,
                recorder=self.recorder, tsdb=self.tsdb,
                profiler=self.profiler,
                collectors={"statz.json": self.fleet_statz},
                extra_files_fn=self._incident_replica_fragments)

    # -- replica table ------------------------------------------------------

    def register(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """Registration AND heartbeat (idempotent): upsert the replica
        row, refresh its liveness stamp.  Raises ValueError on a
        malformed payload (the HTTP surface answers 400)."""
        address = payload.get("address")
        if not isinstance(address, str) or ":" not in address:
            raise ValueError("'address' must be \"host:port\"")
        host, _, port_s = address.rpartition(":")
        if not host or not port_s.isdigit():
            raise ValueError("'address' must be \"host:port\"")
        rid = str(payload.get("replica_id") or address)
        model = str(payload.get("model") or "")
        capacity = int(payload.get("capacity") or 0)
        role = str(payload.get("role") or "mixed")
        if role not in REPLICA_ROLES:
            raise ValueError(
                f"'role' must be one of {'/'.join(REPLICA_ROLES)}")
        with self._lock:
            rep = self._replicas.get(rid)
            fresh = rep is None
            if rep is None:
                rep = Replica(
                    rid=rid, address=address, model=model,
                    capacity=capacity, role=role,
                    registered_at=time.time(),
                    breaker=resilience.CircuitBreaker(
                        op=f"router.replica.{rid}",
                        failure_threshold=self.breaker_threshold,
                        reset_timeout_s=self.breaker_reset_s,
                        metrics=self._rmetrics,
                        recorder=self.recorder))
                self._replicas[rid] = rep
                self._rebuild_ring_locked()
            rep.address = address
            rep.model = model or rep.model
            rep.capacity = capacity or rep.capacity
            rep.role = role
            rep.last_seen = _now()
            # an inline statz piggybacked on the heartbeat freshens the
            # load signal without waiting for the next poll round
            inline = payload.get("statz")
            if isinstance(inline, dict):
                rep.statz = inline
                rep.statz_at = rep.last_seen
        if fresh:
            log.info("replica registered: %s at %s (model=%s cap=%d "
                     "role=%s)", rid, address, model, capacity, role)
            self.recorder.record("tpu_router_replica_registered",
                                 replica=rid, address=address,
                                 model=model, capacity=capacity,
                                 role=role)
        return {"ok": True, "replica_id": rid,
                "interval_s": max(self.replica_ttl_s / 3.0, 0.2)}

    def drain(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """POST /drain — take a replica out of rotation without killing
        it: pick() skips a draining replica, its heartbeats keep the
        row fresh, and in-flight streams run to completion on their
        already-open connections.  ``{"draining": false}`` puts it
        back.  Raises ValueError (400) on a malformed body and KeyError
        (404) for an unknown replica — draining a ghost is a caller
        bug, not a no-op."""
        rid = payload.get("replica_id")
        if not isinstance(rid, str) or not rid:
            raise ValueError("'replica_id' must be a non-empty string")
        draining = bool(payload.get("draining", True))
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None:
                raise KeyError(rid)
            rep.draining = draining
            statz = rep.statz if isinstance(rep.statz, dict) else {}
            queue_depth = int(statz.get("queue_depth", 0) or 0)
            in_flight = int(statz.get("in_flight", 0) or 0)
        self.recorder.record("tpu_router_replica_draining",
                             replica=rid, draining=draining)
        log.info("replica %s %s rotation", rid,
                 "leaving" if draining else "rejoining")
        return {"ok": True, "replica_id": rid, "draining": draining,
                "queue_depth": queue_depth, "in_flight": in_flight}

    def _rebuild_ring_locked(self) -> None:
        """The consistent-hash ring over the CURRENT replica-id set.
        Points depend only on the ids (``sha1(rid#v)``), never on
        registration order or wall time — the property the
        same-prompt-same-replica-across-restarts test pins."""
        ring: List[Tuple[int, str]] = []
        tring: List[Tuple[int, str]] = []
        sring: List[Tuple[int, str]] = []
        for rid in self._replicas:
            for v in range(self.vnodes):
                ring.append((_sha1_int(f"{rid}#{v}".encode()), rid))
                # distinct salt: a replica's share of TENANTS is
                # independent of its share of prefix keys (one
                # unlucky id should not concentrate both)
                tring.append(
                    (_sha1_int(f"tenant|{rid}#{v}".encode()), rid))
                # third salt: SESSION placement independent of both
                # (a session's home should not follow its tenant's
                # pin, or one hot tenant concentrates every tier)
                sring.append(
                    (_sha1_int(f"session|{rid}#{v}".encode()), rid))
        ring.sort()
        tring.sort()
        sring.sort()
        self._ring = ring
        self._tring = tring
        self._sring = sring

    def _evict_stale_locked(self) -> List[str]:
        now = _now()
        dead = [rid for rid, rep in self._replicas.items()
                if now - rep.last_seen > self.replica_ttl_s]
        for rid in dead:
            del self._replicas[rid]
        if dead:
            self._rebuild_ring_locked()
        return dead

    def _routable(self, rep: Replica) -> bool:
        """May traffic go to *rep* right now?  Fresh, breaker closed,
        scheduler alive.  Deliberately side-effect-free: the half-open
        probe slot belongs to the /statz poller (which records the
        probe's outcome), so a health CHECK must never consume it —
        recovery is detected by the poll loop and the breaker closes
        within about one poll interval of the replica coming back."""
        if rep.draining:
            return False
        if _now() - rep.last_seen > self.replica_ttl_s:
            return False
        if not rep.scheduler_alive():
            return False
        assert rep.breaker is not None
        return rep.breaker.state == resilience.BREAKER_CLOSED

    def affinity_target(self, key: Optional[bytes],
                        role: Optional[str] = None) -> Optional[str]:
        """The ring's verdict for *key* over ALL registered replicas
        (health is the pick's business, not the hash's — a temporarily
        sick target must get its traffic back when it recovers, not
        have it re-hashed away forever).  With *role* the walk skips
        replicas of other classes: the first matching id clockwise
        from the hash point — still deterministic over the id+role
        set, so phase-aware affinity keeps the same restart/order
        stability the plain ring has."""
        if key is None:
            return None
        with self._lock:
            ring = self._ring
            roles = ({rid: r.role
                      for rid, r in self._replicas.items()}
                     if role is not None else None)
        return self._ring_walk(ring, _sha1_int(key), roles, role)

    @staticmethod
    def _ring_walk(ring: List[Tuple[int, str]], h: int,
                   roles: Optional[Dict[str, str]],
                   role: Optional[str]) -> Optional[str]:
        if not ring:
            return None
        i = bisect_left(ring, (h, ""))
        n = len(ring)
        for step in range(n):
            rid = ring[(i + step) % n][1]
            if role is None or (roles is not None
                                and roles.get(rid) == role):
                return rid
        return None

    def tenant_target(self, tenant: str) -> Optional[str]:
        """Sticky tenant->replica pinning: the tenant ring's verdict
        (same determinism contract as prefix affinity).  Pinning one
        tenant's traffic to one replica is what makes the replica's
        LOCAL WFQ/quota state globally coherent for that tenant."""
        if not tenant:
            return None
        with self._lock:
            tring = self._tring
        return self._ring_walk(
            tring, _sha1_int(tenant.encode("utf-8", "surrogatepass")),
            None, None)

    # -- session affinity (PR 20) -------------------------------------------

    @staticmethod
    def _session_of(parsed: Dict[str, Any]) -> str:
        """The request's conversation key, exactly as the replicas
        resolve it: native ``session_id``/``session``, or the OpenAI
        extension ``session`` scoped under ``user`` (the replica's
        _openai_to_native mapping) — the router must hash the SAME
        string the replica keys its tier store on."""
        sid = parsed.get("session_id")
        if sid is None:
            sid = parsed.get("session")
        if not sid:
            return ""
        sid = str(sid)
        # OpenAI bodies have no session_id key; their session scopes
        # under user.  Native bodies may carry both session_id and
        # tenant — session_id is already fully qualified there.
        if parsed.get("session_id") is None \
                and parsed.get("user") is not None:
            return f"{parsed['user']}/{sid}"
        return sid

    def session_target(self, sid: str) -> Optional[str]:
        """Where a returning session PREFERS to land: its recorded
        home (the replica that last served it, and so holds its
        parked/spilled KV), else the session ring's verdict (same
        determinism contract as the other two rings)."""
        if not sid or not self.session_affinity:
            return None
        with self._lock:
            home = self._session_home.get(sid)
            sring = self._sring
        if home is not None:
            return home
        return self._ring_walk(
            sring, _sha1_int(sid.encode("utf-8", "surrogatepass")),
            None, None)

    def _note_session_home(self, sid: str, rid: str) -> None:
        """Record where *sid* was just served (bounded LRU: an
        abandoned session's row ages out; its DISK state still
        survives on the old home for the ring to find)."""
        if not sid or not self.session_affinity:
            return
        with self._lock:
            self._session_home.pop(sid, None)
            self._session_home[sid] = rid
            while len(self._session_home) > self.session_home_max:
                self._session_home.popitem(last=False)

    def _maybe_move_session(self, sid: str, chosen: Replica,
                            trace: "obs.TraceContext") -> None:
        """A returning session is about to be served by a replica
        that is NOT its home: move the parked checkpoint first
        (POST /session/export on the home -> /session/import on the
        chosen replica) so the request warm-resumes there.  Strictly
        best-effort — any failure (home gone, nothing parked, sick
        disk, import refused) just forwards the request for a plain
        re-prefill.  A tiering failure must never fail the request."""
        if not sid or not self.session_affinity:
            return
        with self._lock:
            home_rid = self._session_home.get(sid)
            home = (self._replicas.get(home_rid)
                    if home_rid is not None else None)
        if home is None or home.rid == chosen.rid \
                or not self._routable(home):
            return
        outcome = "error"
        try:
            payload = self._session_export(home, sid)
            if payload is None:
                outcome = "miss"
                return
            self._session_import(chosen, payload)
            outcome = "ok"
        except Exception as e:
            log.warning("session move %s -> %s failed: %s",
                        home.rid, chosen.rid, e)
        finally:
            self._m_session_moves.labels(outcome=outcome).inc()
            self.recorder.record(
                "tpu_router_session_move", trace=trace,
                session=hashlib.sha1(
                    sid.encode("utf-8", "surrogatepass")
                ).hexdigest()[:20],
                src=home.rid, dst=chosen.rid, outcome=outcome)

    def _session_export(self, rep: Replica,
                        sid: str) -> Optional[bytes]:
        """One export attempt against the session's home replica.
        None = the home has nothing parked under *sid* (404 — a
        plain miss, not an error); raises on transport/5xx."""
        host, port = rep.host_port()
        body = json.dumps({"session_id": sid}).encode()
        conn = http.client.HTTPConnection(
            host, port, timeout=self.connect_timeout_s)
        try:
            conn.request("POST", "/session/export", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = resp.read()
            if resp.status == 404:
                return None
            if resp.status != 200:
                raise OSError(f"export HTTP {resp.status}")
            return data
        finally:
            conn.close()

    def _session_import(self, rep: Replica, payload: bytes) -> None:
        host, port = rep.host_port()
        conn = http.client.HTTPConnection(
            host, port, timeout=self.connect_timeout_s)
        try:
            conn.request("POST", "/session/import", body=payload,
                         headers={
                             "Content-Type": MIGRATE_CONTENT_TYPE})
            resp = conn.getresponse()
            resp.read()
            if resp.status != 200:
                raise OSError(f"import HTTP {resp.status}")
        finally:
            conn.close()

    def _note_evictions(self, dead: List[str]) -> None:
        for rid in dead:
            self._m_evictions.inc()
            self.recorder.record("tpu_router_replica_evicted",
                                 replica=rid)
            log.warning("replica %s evicted (stale past %.1fs)",
                        rid, self.replica_ttl_s)

    def pick(self, key: Optional[bytes],
             exclude: Optional[Set[str]] = None,
             role: Optional[str] = None,
             pin: Optional[str] = None
             ) -> Tuple[Optional[Replica], bool]:
        """Choose the replica for one attempt, in precedence order:
        the *pin* target (sticky tenant placement), the
        prefix-affinity target, then the least-loaded routable
        replica — each gated on routable + not overloaded.  *role*
        restricts every tier to one replica class (the disagg path
        picks prefill- and decode-class replicas separately).
        Returns (replica, affinity_hit); (None, False) when nothing
        is routable."""
        exclude = exclude or set()
        target = self.affinity_target(key, role=role)
        with self._lock:
            dead = self._evict_stale_locked()
            candidates = [r for rid, r in self._replicas.items()
                          if rid not in exclude
                          and (role is None or r.role == role)]
        self._note_evictions(dead)
        for want, is_affinity in ((pin, False), (target, True)):
            if want is None or want in exclude:
                continue
            for rep in candidates:
                if rep.rid == want and self._routable(rep) \
                        and not rep.overloaded(self.overload_factor):
                    if not is_affinity:
                        self._m_tenant_pins.inc()
                    return rep, is_affinity and want == target
        routable = [r for r in candidates if self._routable(r)]
        if not routable:
            return None, False
        routable.sort(key=lambda r: (r.load_score(), r.rid))
        return routable[0], False

    def replicas(self) -> List[Dict[str, Any]]:
        """The /replicas debug view (sorted, JSON-ready)."""
        with self._lock:
            reps = list(self._replicas.values())
        now = _now()
        out = []
        for rep in sorted(reps, key=lambda r: r.rid):
            assert rep.breaker is not None
            out.append({
                "replica_id": rep.rid,
                "address": rep.address,
                "model": rep.model,
                "capacity": rep.capacity,
                "role": rep.role,
                "healthy": self._routable(rep),
                "draining": rep.draining,
                "breaker_state": rep.breaker.state,
                "age_s": round(now - rep.last_seen, 3),
                "load_score": round(rep.load_score(), 4),
                "statz": rep.statz,
            })
        return out

    def fleet_statz(self) -> Dict[str, Any]:
        """One fleet snapshot (GET /fleet/statz): per-replica statz
        plus aggregated load + goodput — the signal the autoscaler
        (ROADMAP fleet control plane) and dashboards read without
        touching N replicas themselves.  Built entirely from the
        CACHED statz the poller/heartbeats keep fresh: serving this is
        O(replicas), no fan-out on the read path."""
        with self._lock:
            reps = list(self._replicas.values())
        now = _now()
        agg = {"queue_depth": 0, "in_flight": 0, "capacity": 0,
               "kv_pages": 0, "kv_pages_free": 0,
               "requests_served": 0}
        shed_agg: Dict[str, int] = {}
        # session-tier occupancy roll-up (PR 20): parked-conversation
        # pressure per tier, the signal alert rules and the
        # autoscaler read for "the fleet is full of idle sessions"
        tier_agg = {"device": 0, "host": 0, "disk": 0,
                    "host_bytes": 0, "disk_bytes": 0}
        # per-class goodput aggregation: sums of window met/total
        # re-derive the fleet ratio (a mean of ratios would let an
        # idle replica mask a drowning one)
        classes: Dict[str, Dict[str, float]] = {}
        per_replica: Dict[str, Any] = {}
        # firing-alert roll-up (PR 18): every replica's statz alert
        # brief plus the router's own fleet-level evaluator, tagged
        # by source so the autoscaler can key on page severity
        firing_alerts: List[Dict[str, Any]] = []
        healthy = 0
        for rep in sorted(reps, key=lambda r: r.rid):
            ok = self._routable(rep)
            healthy += 1 if ok else 0
            statz = rep.statz if isinstance(rep.statz, dict) else {}
            per_replica[rep.rid] = {
                "healthy": ok,
                "role": rep.role,
                "draining": rep.draining,
                "age_s": round(now - rep.last_seen, 3),
                "statz": statz,
            }
            for k in agg:
                v = statz.get(k)
                if isinstance(v, (int, float)):
                    agg[k] += int(v)
            shed = statz.get("shed")
            if isinstance(shed, dict):
                for k, v in shed.items():
                    if isinstance(v, (int, float)):
                        shed_agg[k] = shed_agg.get(k, 0) + int(v)
            tiers = statz.get("kv_tiers")
            if isinstance(tiers, dict):
                for k in tier_agg:
                    v = tiers.get(k)
                    if isinstance(v, (int, float)):
                        tier_agg[k] += int(v)
            alerts = statz.get("alerts")
            if isinstance(alerts, dict):
                for f in alerts.get("firing") or []:
                    if isinstance(f, dict):
                        firing_alerts.append(
                            {"source": rep.rid, **f})
            goodput = statz.get("goodput")
            if not isinstance(goodput, dict):
                continue
            gclasses = goodput.get("classes")
            if not isinstance(gclasses, dict):
                continue
            for name, row in gclasses.items():
                if not isinstance(row, dict):
                    continue
                acc = classes.setdefault(name, {
                    "total": 0.0, "met": 0.0, "window_total": 0.0,
                    "window_met": 0.0, "goodput_rps": 0.0,
                    "burn_rate_max": 0.0})
                for src, dst in (("total", "total"), ("met", "met"),
                                 ("window_total", "window_total"),
                                 ("window_met", "window_met"),
                                 ("goodput_rps", "goodput_rps")):
                    v = row.get(src)
                    if isinstance(v, (int, float)):
                        acc[dst] += float(v)
                burn = row.get("burn_rate")
                if isinstance(burn, (int, float)):
                    acc["burn_rate_max"] = max(acc["burn_rate_max"],
                                               float(burn))
        goodput_out: Dict[str, Any] = {}
        for name, acc in sorted(classes.items()):
            wt, wm = acc["window_total"], acc["window_met"]
            goodput_out[name] = {
                "total": int(acc["total"]),
                "met": int(acc["met"]),
                "window_total": int(wt),
                "window_met": int(wm),
                "goodput_ratio": (wm / wt) if wt else 1.0,
                "goodput_rps": acc["goodput_rps"],
                "burn_rate_max": acc["burn_rate_max"],
            }
        with self._lock:
            no_replica_total = self._no_replica_total
        own = self.alerts.brief()
        for f in own["firing"]:
            firing_alerts.append({"source": "router", **f})
        return {
            "replicas": len(reps),
            "healthy": healthy,
            "fleet": {**agg, "shed": shed_agg,
                      "kv_tiers": tier_agg,
                      "goodput": goodput_out,
                      "firing_alerts": firing_alerts},
            "router": {"no_replica_total": no_replica_total,
                       "alerts": own},
            "per_replica": per_replica,
        }

    # -- fleet incident bundles (PR 19) -------------------------------------

    def _fetch_replica_json(self, rep: Replica, path: str,
                            timeout_s: float = 2.0) -> Dict[str, Any]:
        """One replica's JSON debug surface for the incident bundle
        fan-out.  Short timeout by design — a dead replica must cost
        the bundle seconds, not minutes — and EVERY failure mode
        returns an ``{"unreachable": true}`` marker instead of
        raising (the bundle records the death, it does not share it)."""
        host, port = rep.host_port()
        conn = http.client.HTTPConnection(host, port,
                                          timeout=timeout_s)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                return {"unreachable": True,
                        "error": f"HTTP {resp.status}"}
            out = json.loads(body)
            return out if isinstance(out, dict) else {"body": out}
        # tpulint: disable=R2 -- not a swallow: the failure IS the payload — the bundle records the replica as unreachable with the error text (chaos episode 16 asserts exactly this marker)
        except Exception as e:
            return {"unreachable": True,
                    "error": f"{type(e).__name__}: {e}"}
        finally:
            try:
                conn.close()
            # tpulint: disable=R2 -- close() on an already-broken connection during bundle fan-out; nothing to account, the fetch outcome was recorded above
            except Exception:
                pass

    def _incident_replica_fragments(self) -> Dict[str, Any]:
        """The fleet-level bundle's per-replica half: pull each
        registered replica's statz / alerts / continuous-profile slice
        into ``replicas/<id>/``.  Replicas whose breaker is open are
        still ATTEMPTED (the page may be ABOUT them) — unreachable
        ones degrade to their marker file."""
        with self._lock:
            reps = list(self._replicas.values())
        out: Dict[str, Any] = {}
        for rep in reps:
            # replica ids default to "host:port" — keep the path one
            # directory level deep whatever the operator chose
            safe = rep.rid.replace("/", "_").replace("..", "_")
            base = f"replicas/{safe}"
            out[f"{base}/statz.json"] = self._fetch_replica_json(
                rep, "/statz")
            out[f"{base}/alerts.json"] = self._fetch_replica_json(
                rep, "/alerts")
            out[f"{base}/profile.json"] = self._fetch_replica_json(
                rep, "/debug/pprof?seconds=60&format=json")
        return out

    # -- cross-replica trace stitching --------------------------------------

    def _fetch_replica_trace(self, rep: Replica, trace_id: str
                             ) -> List[Dict[str, object]]:
        """One replica's /debug/traces timeline for *trace_id* (the
        stitch fan-out; failures degrade the stitch, never fail it —
        the statz breaker gates obviously-dead replicas out)."""
        assert rep.breaker is not None
        if rep.breaker.state == resilience.BREAKER_OPEN:
            return []
        host, port = rep.host_port()
        conn = http.client.HTTPConnection(
            host, port, timeout=self.connect_timeout_s)
        try:
            conn.request(
                "GET",
                f"/debug/traces?trace_id={quote(trace_id, safe='')}")
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                return []
            out = json.loads(body)
        finally:
            conn.close()
        events = out.get("events") if isinstance(out, dict) else None
        if not isinstance(events, list):
            return []
        return [e for e in events if isinstance(e, dict)]

    def stitched_trace(self, trace_id: str) -> Dict[str, Any]:
        """GET /debug/traces?trace_id= — the fleet view: the router's
        own route/proxy events merged with every registered replica's
        timeline for the same trace-id, re-linked into ONE span tree
        via the traceparent parent links (obs.stitch).  A replica that
        cannot answer (dead, evicting) just contributes nothing — its
        flight-recorder DUMP still holds its half for
        tools/obs_query.py."""
        events: List[Dict[str, object]] = []
        for ev in self.recorder.events(trace_id=trace_id):
            ev["source"] = "router"
            events.append(ev)
        with self._lock:
            reps = list(self._replicas.values())
        for rep in sorted(reps, key=lambda r: r.rid):
            try:
                fetched = self._fetch_replica_trace(rep, trace_id)
            except (OSError, ValueError,
                    http.client.HTTPException) as e:
                resilience.suppressed("router.trace_fanout", e,
                                      logger=log,
                                      metrics=self._rmetrics)
                continue
            for ev in fetched:
                ev.setdefault("source", rep.rid)
                events.append(ev)
        return {
            "trace_id": trace_id,
            "events": len(events),
            "tree": obs.stitch(events),
        }

    def _collect_health(self) -> None:
        """Scrape-time refresh of tpu_router_replica_healthy."""
        with self._lock:
            reps = list(self._replicas.values())
        for rep in reps:
            self._m_healthy.labels(replica=rep.rid).set(
                1 if self._routable(rep) else 0)

    def _collect_fleet_goodput(self) -> None:
        """Scrape-time refresh of the fleet-aggregate goodput bridge
        gauges the router's burn-rate alert rules evaluate over.
        Built from the same cached statz rows fleet_statz() reads —
        O(replicas), no fan-out.  Classes rebuild from scratch so a
        class that left the fleet leaves no stale burning series."""
        goodput = self.fleet_statz()["fleet"]["goodput"]
        self._m_fleet_burn.clear()
        self._m_fleet_goodput.clear()
        for name, row in goodput.items():
            self._m_fleet_burn.labels(**{"class": name}).set(
                row["burn_rate_max"])
            self._m_fleet_goodput.labels(**{"class": name}).set(
                row["goodput_ratio"])

    # -- statz poller -------------------------------------------------------

    def _fetch_statz(self, rep: Replica) -> Dict[str, Any]:
        host, port = rep.host_port()
        conn = http.client.HTTPConnection(
            host, port, timeout=self.connect_timeout_s)
        try:
            conn.request("GET", "/statz")
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                raise _UpstreamError(
                    f"/statz answered {resp.status}", resp.status)
            out = json.loads(body)
            if not isinstance(out, dict):
                raise _UpstreamError("/statz body is not an object")
            return out
        finally:
            conn.close()

    def _poll_once(self) -> None:
        with self._lock:
            dead = self._evict_stale_locked()
            reps = list(self._replicas.values())
        self._note_evictions(dead)
        for rep in reps:
            if self._stop.is_set():
                return
            assert rep.breaker is not None
            if not rep.breaker.allow():
                continue
            if faults.ACTIVE is not None:
                try:
                    faults.ACTIVE.fire("router.statz")
                except Exception as e:
                    rep.breaker.record_failure()
                    resilience.suppressed(
                        "router.statz_poll", e, logger=log,
                        metrics=self._rmetrics)
                    continue
            try:
                snap = self._fetch_statz(rep)
            except (OSError, ValueError, _UpstreamError) as e:
                rep.breaker.record_failure()
                resilience.suppressed("router.statz_poll", e,
                                      logger=log,
                                      metrics=self._rmetrics)
                continue
            rep.breaker.record_success()
            with self._lock:
                cur = self._replicas.get(rep.rid)
                if cur is not None:
                    cur.statz = snap
                    cur.statz_at = cur.last_seen = _now()

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.statz_interval_s):
            self._poll_once()

    # -- proxy --------------------------------------------------------------

    def _open_upstream(self, rep: Replica, path: str, body: bytes,
                       headers: Dict[str, str]
                       ) -> Tuple[http.client.HTTPConnection,
                                  http.client.HTTPResponse]:
        """One upstream attempt up to response HEADERS; raises
        :class:`_UpstreamError` on anything retryable.  The breaker
        records the outcome (a 5xx is a replica failure; 2xx-4xx
        means the replica is alive and answering)."""
        assert rep.breaker is not None
        if not rep.breaker.allow():
            raise _UpstreamError(f"{rep.rid}: breaker open")
        host, port = rep.host_port()
        if faults.ACTIVE is not None:
            try:
                faults.ACTIVE.fire("router.proxy")
            except Exception as e:
                rep.breaker.record_failure()
                raise _UpstreamError(f"injected: {e}") from e
        conn = http.client.HTTPConnection(
            host, port, timeout=self.client_timeout_s)
        try:
            conn.request("POST", path, body, headers)
            resp = conn.getresponse()
        except OSError as e:
            conn.close()
            rep.breaker.record_failure()
            raise _UpstreamError(f"{rep.rid}: {e}") from e
        if resp.status >= 500:
            # the replica answered but is broken (scheduler dead,
            # shutdown drain): drain the body and fail this attempt so
            # the request can land somewhere healthy
            try:
                detail = resp.read(4096).decode("utf-8", "replace")
            except OSError:
                detail = ""
            conn.close()
            rep.breaker.record_failure()
            raise _UpstreamError(
                f"{rep.rid}: upstream {resp.status}: "
                f"{detail.strip()[:200]}", resp.status)
        rep.breaker.record_success()
        return conn, resp

    @staticmethod
    def _error_frame(content_type: str, message: str,
                     code: int) -> bytes:
        """A WELL-FORMED in-band terminal error for a broken stream,
        in the stream's own framing: a JSON line for the native
        JSON-lines wire, an SSE error event for the OpenAI wire.  A
        client parsing the stream sees a structured error, never a
        silent truncation that looks like success."""
        payload = {"error": message, "code": code}
        if content_type.startswith("text/event-stream"):
            wire = {"error": {"message": message,
                              "type": "server_error"}}
            return ("data: " + json.dumps(wire) + "\n\n").encode()
        return (json.dumps(payload) + "\n").encode()

    @staticmethod
    def _tenant_of(parsed: Dict[str, Any]) -> str:
        """The request's QoS identity: 'tenant' (native) or 'user'
        (OpenAI), exactly the mapping the replicas apply."""
        tenant = parsed.get("tenant")
        if tenant is None:
            tenant = parsed.get("user")
        return str(tenant) if tenant else ""

    def _est_cost(self, parsed: Dict[str, Any]) -> float:
        """The same prompt+budget token estimate the replicas charge
        their local buckets (string prompts approximate at 4 chars
        per token — the router cannot tokenize)."""
        tokens = parsed.get("tokens")
        if isinstance(tokens, list):
            prompt_toks = len(tokens)
        else:
            prompt = parsed.get("prompt")
            if isinstance(prompt, str):
                prompt_toks = max(1, len(prompt) // 4)
            elif isinstance(prompt, list):
                prompt_toks = len(prompt)
            else:
                prompt_toks = 1
        budget = parsed.get("max_new_tokens",
                            parsed.get("max_tokens",
                                       self.default_budget))
        try:
            budget_i = int(budget)
        except (TypeError, ValueError):
            budget_i = self.default_budget
        try:
            n = max(1, int(parsed.get("n", 1)))
        except (TypeError, ValueError):
            n = 1
        return float((prompt_toks + budget_i) * n)

    def _charge_tenant(self, tenant: str, cost: float) -> bool:
        """Fleet-level token bucket: True = admitted.  Only engaged
        when router quotas are configured; the '*' template clones
        per-tenant state exactly like the replica-local buckets."""
        if not tenant or not self.tenant_quotas:
            return True
        with self._lock:
            quota = resolve_quota(self.tenant_quotas, tenant)
            return quota is None or quota.try_charge(cost)

    def _prefill_heavy(self, parsed: Dict[str, Any]) -> bool:
        """Does this request belong on a prefill-class replica?
        Prompt length above the threshold, or a unary completion
        (its whole latency is prefill + batched decode — exactly the
        work that interferes with latency-sensitive decode streams)."""
        if int(parsed.get("n", 1) or 1) != 1:
            return False    # multi-copy requests never migrate
        tokens = parsed.get("tokens")
        prompt = parsed.get("prompt")
        if isinstance(tokens, list):
            prompt_toks = len(tokens)
        elif isinstance(prompt, list):
            prompt_toks = len(prompt)
        elif isinstance(prompt, str):
            prompt_toks = max(1, len(prompt) // 4)
        else:
            return False    # chat messages etc.: length unknowable
        if prompt_toks >= self.prefill_threshold:
            return True
        # default stream semantics differ per wire: native /generate
        # defaults to streaming, OpenAI completions to unary — the
        # router only trusts an EXPLICIT stream flag either way
        return parsed.get("stream") is False

    def _disagg_ready(self) -> bool:
        """Both specialized classes registered and routable?"""
        if not self.disagg:
            return False
        with self._lock:
            reps = list(self._replicas.values())
        has = {"prefill": False, "decode": False}
        for rep in reps:
            if rep.role in has and self._routable(rep):
                has[rep.role] = True
        return has["prefill"] and has["decode"]

    def proxy(self, handler: "BaseHTTPRequestHandler", path: str,
              body: bytes, trace: "obs.TraceContext") -> None:
        """Route one request: pick -> forward -> stream back.  All the
        failover semantics live here; see the module docstring."""
        t_arrival = time.perf_counter()
        parsed: Dict[str, Any] = {}
        try:
            decoded = json.loads(body) if body else {}
            if isinstance(decoded, dict):
                parsed = decoded
            key = affinity_key(parsed, self.prefix_chunk) \
                if parsed else None
        except (ValueError, TypeError):
            key = None
        tenant = self._tenant_of(parsed)
        if not self._charge_tenant(tenant, self._est_cost(parsed)):
            # fleet-level 429: the tenant's GLOBAL rate is spent —
            # same wire shape as a replica quota shed, so clients
            # cannot tell (and need not care) which tier said no
            self._m_shed.labels(reason="tenant_quota").inc()
            self._m_requests.labels(replica="none",
                                    outcome="shed").inc()
            self.recorder.record("tpu_router_tenant_quota_shed",
                                 trace=trace, tenant=tenant)
            out = (json.dumps({
                "error": f"tenant {tenant} over fleet token-rate "
                         "quota; retry later", "code": 429})
                + "\n").encode()
            handler.send_response(429)
            handler.send_header("Content-Type", "application/json")
            handler.send_header("Content-Length", str(len(out)))
            handler.send_header("Retry-After", "1")
            handler.end_headers()
            try:
                handler.wfile.write(out)
            except OSError:
                pass
            return
        if parsed and self._prefill_heavy(parsed) \
                and self._disagg_ready():
            if self._proxy_disagg(handler, path, parsed, key, trace,
                                  t_arrival):
                return
            # every disagg fallback happens BEFORE any client byte:
            # the request re-runs whole through the normal path
        headers = {
            "Content-Type": handler.headers.get(
                "Content-Type", "application/json"),
            "traceparent": trace.to_traceparent(),
        }
        pin = (self.tenant_target(tenant)
               if tenant and self.tenant_pinning else None)
        # session affinity: a returning conversation prefers the
        # replica holding its warm KV.  Tenant pinning still wins
        # (quota coherence beats resume latency) — the session moves
        # its checkpoint to the pinned replica instead.
        sid = self._session_of(parsed) if parsed else ""
        if pin is None and sid:
            pin = self.session_target(sid)
        tried: Set[str] = set()
        conn: Optional[http.client.HTTPConnection] = None
        resp: Optional[http.client.HTTPResponse] = None
        rep: Optional[Replica] = None
        hit = False
        last_err: Optional[_UpstreamError] = None
        for attempt in range(1, self.failover_attempts + 1):
            rep, hit = self.pick(key, exclude=tried, pin=pin)
            if rep is None:
                break
            if attempt > 1:
                # a prior attempt failed and a DIFFERENT replica is
                # taking the request: that handoff is the failover
                self._m_failovers.inc()
                self.recorder.record(
                    "tpu_router_failover", trace=trace,
                    replica=rep.rid, attempt=attempt)
            tried.add(rep.rid)
            if sid:
                # landing away from the session's home: ship its
                # parked KV over BEFORE the request, so admission
                # finds a warm checkpoint (best-effort; see helper)
                self._maybe_move_session(sid, rep, trace)
            t0 = time.perf_counter()
            try:
                conn, resp = self._open_upstream(
                    rep, path, body, headers)
            except _UpstreamError as e:
                last_err = e
                self._m_route.observe(time.perf_counter() - t0)
                self.recorder.record(
                    "tpu_router_attempt_failed", trace=trace,
                    replica=rep.rid, attempt=attempt, error=str(e))
                if attempt < self.failover_attempts:
                    # seeded jitter between failover attempts: brief,
                    # bounded, replayable
                    time.sleep(self.retry.backoff_s(attempt))
                continue
            self._m_route.observe(time.perf_counter() - t0)
            break
        if resp is None or conn is None or rep is None:
            reason = ("no healthy replicas"
                      if not tried else
                      f"all {len(tried)} replica(s) failed: "
                      f"{last_err}")
            self._m_shed.labels(reason="no_replicas").inc()
            with self._lock:
                self._no_replica_total += 1
            self._m_requests.labels(
                replica="none",
                outcome="unroutable" if not tried
                else "upstream_error").inc()
            self.recorder.record("tpu_router_unroutable", trace=trace,
                                 tried=",".join(sorted(tried)),
                                 error=str(last_err) if last_err
                                 else "")
            code = 503
            body_out = (json.dumps(
                {"error": reason, "code": code}) + "\n").encode()
            handler.send_response(code)
            handler.send_header("Content-Type", "application/json")
            handler.send_header("Content-Length", str(len(body_out)))
            handler.send_header("Retry-After", "1")
            handler.end_headers()
            try:
                handler.wfile.write(body_out)
            except OSError:
                pass
            self.recorder.record(
                "tpu_router_proxy", trace=trace, replica="none",
                outcome="unroutable",
                duration_s=time.perf_counter() - t_arrival)
            return
        if sid:
            self._note_session_home(sid, rep.rid)
        self._relay(handler, conn, resp, rep, hit, len(tried), trace,
                    t_arrival)

    def _proxy_disagg(self, handler: "BaseHTTPRequestHandler",
                      path: str, parsed: Dict[str, Any],
                      key: Optional[bytes],
                      trace: "obs.TraceContext",
                      t_arrival: float) -> bool:
        """The phase-disaggregated route for one prefill-heavy
        request: forward it to a prefill-class replica with the
        ``prefill_only`` marker (it runs packed prefill, then
        preempts the fresh slot and answers with the bit-exact
        serialized checkpoint), ship that checkpoint to a
        decode-class replica's ``POST /migrate`` (it resumes the slot
        and takes over the stream), and pass the decode replica's
        response through to the client byte-identically.

        Every failure mode falls back BEFORE any client byte: returns
        False and the caller re-routes the ORIGINAL request through
        the normal path (prefill already freed its pages at export,
        so a re-run recomputes from scratch — slower, never wrong).
        True means the response was fully handled here."""
        body2 = dict(parsed)
        body2["prefill_only"] = True
        raw2 = json.dumps(body2).encode()
        headers = {"Content-Type": "application/json",
                   "traceparent": trace.to_traceparent()}
        tried: Set[str] = set()
        prep: Optional[Replica] = None
        conn: Optional[http.client.HTTPConnection] = None
        resp: Optional[http.client.HTTPResponse] = None
        hit = False
        for attempt in range(1, self.failover_attempts + 1):
            prep, hit = self.pick(key, exclude=tried, role="prefill")
            if prep is None:
                break
            tried.add(prep.rid)
            t0 = time.perf_counter()
            try:
                conn, resp = self._open_upstream(
                    prep, path, raw2, headers)
            except _UpstreamError as e:
                self._m_route.observe(time.perf_counter() - t0)
                self.recorder.record(
                    "tpu_router_attempt_failed", trace=trace,
                    replica=prep.rid, attempt=attempt, error=str(e),
                    phase="prefill")
                if attempt < self.failover_attempts:
                    time.sleep(self.retry.backoff_s(attempt))
                continue
            self._m_route.observe(time.perf_counter() - t0)
            break
        if resp is None or conn is None or prep is None:
            self._m_migrations.labels(
                outcome="prefill_unavailable").inc()
            self.recorder.record("tpu_router_migrate_fallback",
                                 trace=trace, stage="prefill_pick",
                                 tried=",".join(sorted(tried)))
            return False
        ctype = resp.headers.get("Content-Type", "")
        if resp.status != 200 \
                or not ctype.startswith(MIGRATE_CONTENT_TYPE):
            # the prefill replica declined (request finished at its
            # first token, or eligibility said no) or answered a
            # client error: its response IS the response — relay it
            self._m_migrations.labels(outcome="declined").inc()
            self.recorder.record("tpu_router_migrate_declined",
                                 trace=trace, replica=prep.rid,
                                 status=resp.status)
            self._relay(handler, conn, resp, prep, hit, len(tried),
                        trace, t_arrival)
            return True
        try:
            payload = resp.read()
        except (OSError, http.client.HTTPException) as e:
            conn.close()
            assert prep.breaker is not None
            prep.breaker.record_failure()
            self._m_migrations.labels(outcome="prefill_error").inc()
            self.recorder.record("tpu_router_migrate_fallback",
                                 trace=trace, stage="payload_read",
                                 replica=prep.rid, error=str(e))
            return False
        conn.close()
        self._m_role_requests.labels(role="prefill").inc()
        t_ship = time.perf_counter()
        mheaders = {"Content-Type": MIGRATE_CONTENT_TYPE,
                    "traceparent": trace.to_traceparent()}
        dtried: Set[str] = set()
        for attempt in range(1, self.failover_attempts + 1):
            drep, dhit = self.pick(key, exclude=dtried, role="decode")
            if drep is None:
                break
            dtried.add(drep.rid)
            try:
                dconn, dresp = self._open_upstream(
                    drep, "/migrate", payload, mheaders)
            except _UpstreamError as e:
                self.recorder.record(
                    "tpu_router_attempt_failed", trace=trace,
                    replica=drep.rid, attempt=attempt, error=str(e),
                    phase="migrate")
                if attempt < self.failover_attempts:
                    time.sleep(self.retry.backoff_s(attempt))
                continue
            if dresp.status != 200:
                # a 4xx from /migrate is a malformed/unresumable
                # payload, not replica pressure (pressure answers
                # 503 and was retried above): re-running the request
                # whole beats poking other replicas with bad bytes
                dconn.close()
                break
            ship_dt = time.perf_counter() - t_ship
            self._m_migrate_s.observe(ship_dt)
            self._m_migrations.labels(outcome="ok").inc()
            self.recorder.record(
                "tpu_router_migrated", trace=trace,
                prefill=prep.rid, decode=drep.rid,
                bytes=len(payload), ship_s=ship_dt)
            self._relay(handler, dconn, dresp, drep, dhit,
                        len(tried) + len(dtried), trace, t_arrival)
            return True
        self._m_migrations.labels(outcome="fallback").inc()
        self.recorder.record("tpu_router_migrate_fallback",
                             trace=trace, stage="decode_pick",
                             prefill=prep.rid,
                             tried=",".join(sorted(dtried)))
        return False

    def _relay(self, handler: "BaseHTTPRequestHandler",
               conn: http.client.HTTPConnection,
               resp: http.client.HTTPResponse, rep: Replica,
               hit: bool, attempts: int, trace: "obs.TraceContext",
               t_arrival: float) -> None:
        """Stream one upstream response back, byte-identical (the
        shared tail of the normal and disagg proxy paths)."""
        outcome = "ok" if resp.status < 400 else (
            "shed" if resp.status == 429 else "client_error")
        if hit:
            self._m_affinity.inc()
        self._m_role_requests.labels(role=rep.role).inc()
        self.recorder.record(
            "tpu_router_routed", trace=trace, replica=rep.rid,
            status=resp.status, affinity=hit, attempts=attempts,
            duration_s=time.perf_counter() - t_arrival)
        content_type = resp.headers.get("Content-Type",
                                        "application/json")
        chunked = (resp.headers.get("Transfer-Encoding", "")
                   .lower() == "chunked")
        try:
            handler.send_response(resp.status)
            for name, value in resp.headers.items():
                if name.lower() in _HOP_HEADERS:
                    continue
                handler.send_header(name, value)
            handler.send_header("X-Replica", rep.rid)
            if chunked:
                handler.send_header("Transfer-Encoding", "chunked")
                handler.end_headers()
                streamed = self._stream_through(
                    handler, conn, resp, rep, content_type, trace)
                if streamed != "ok":
                    outcome = streamed
                self._m_requests.labels(replica=rep.rid,
                                        outcome=outcome).inc()
                self.recorder.record(
                    "tpu_router_proxy", trace=trace, replica=rep.rid,
                    outcome=outcome,
                    duration_s=time.perf_counter() - t_arrival)
                return
            payload = resp.read()
            handler.send_header("Content-Length", str(len(payload)))
            handler.end_headers()
            handler.wfile.write(payload)
        except OSError as e:
            # body read/send failed: mid-body upstream death on a
            # Content-Length response cannot be patched in-band —
            # the short read IS the client's signal
            outcome = "stream_abort"
            assert rep.breaker is not None
            rep.breaker.record_failure()
            self.recorder.record("tpu_router_stream_abort",
                                 trace=trace, replica=rep.rid,
                                 error=str(e))
        finally:
            conn.close()
        self._m_requests.labels(replica=rep.rid,
                                outcome=outcome).inc()
        self.recorder.record(
            "tpu_router_proxy", trace=trace, replica=rep.rid,
            outcome=outcome,
            duration_s=time.perf_counter() - t_arrival)

    def _stream_through(self, handler: "BaseHTTPRequestHandler",
                        conn: http.client.HTTPConnection,
                        resp: http.client.HTTPResponse, rep: Replica,
                        content_type: str,
                        trace: "obs.TraceContext") -> str:
        """The pass-through loop: de-chunk upstream, re-chunk the SAME
        bytes to the client.  Upstream death mid-stream emits a
        well-formed error frame + terminator and opens the breaker;
        client death just abandons the upstream read.  Returns the
        outcome label ("ok", "stream_abort", "client_gone")."""
        outcome = "ok"
        try:
            while True:
                try:
                    # read1, NOT read: read(n) on a chunked response
                    # blocks until n bytes accumulate — it would turn
                    # the pass-through into a 64 KiB store-and-forward
                    # buffer; read1 hands back each upstream chunk's
                    # available bytes as they arrive
                    chunk = resp.read1(_STREAM_READ)
                except (OSError, http.client.HTTPException) as e:
                    # replica died mid-stream: forward whatever valid
                    # payload the failed read salvaged, then an
                    # in-band structured error + clean chunked
                    # terminator; the breaker opens
                    outcome = "stream_abort"
                    assert rep.breaker is not None
                    rep.breaker.record_failure()
                    self.recorder.record(
                        "tpu_router_stream_abort", trace=trace,
                        replica=rep.rid, error=str(e))
                    partial = getattr(e, "partial", b"") or b""
                    if partial:
                        handler.wfile.write(
                            b"%x\r\n%s\r\n" % (len(partial), partial))
                    frame = self._error_frame(
                        content_type,
                        f"replica {rep.rid} died mid-stream; "
                        "retry the request", 502)
                    handler.wfile.write(
                        b"%x\r\n%s\r\n" % (len(frame), frame))
                    break
                if not chunk:
                    break
                handler.wfile.write(
                    b"%x\r\n%s\r\n" % (len(chunk), chunk))
            handler.wfile.write(b"0\r\n\r\n")
        except OSError:
            # the CLIENT went away: nothing to send an error to
            outcome = "client_gone"
            self.recorder.record("tpu_router_client_gone",
                                 trace=trace, replica=rep.rid)
        return outcome

    def healthy(self) -> bool:
        """>= 1 routable replica."""
        with self._lock:
            reps = list(self._replicas.values())
        return any(self._routable(r) for r in reps)

    # -- lifecycle ----------------------------------------------------------

    def start(self, host: str = "0.0.0.0",
              port: int = 8100) -> "RouterServer":
        router = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            timeout = router.client_timeout_s

            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                if self.path == "/healthz":
                    if router.healthy():
                        self._send(200, "text/plain", b"ok\n")
                    else:
                        self._send(503, "text/plain",
                                   b"no healthy replicas\n")
                elif self.path == "/replicas":
                    body = json.dumps(
                        {"replicas": router.replicas()},
                        indent=2).encode() + b"\n"
                    self._send(200, "application/json", body)
                elif self.path == "/metrics":
                    om = obs.negotiate_openmetrics(
                        self.headers.get("Accept"))
                    try:
                        # ScrapeMeta accounts the exposition itself
                        # (tpu_scrape_*); the fleet bridge gauges
                        # refresh via the registry collect hook
                        body = router.scrape_meta.render(
                            openmetrics=om).encode()
                    except Exception:
                        log.exception("/metrics render failed")
                        self._send(500, "text/plain",
                                   b"internal error\n")
                        return
                    self._send(200, obs.OPENMETRICS_CONTENT_TYPE
                               if om else obs.TEXT_CONTENT_TYPE, body)
                elif self.path == "/alerts":
                    self._send(200, "application/json",
                               (router.alerts.status_json()
                                + "\n").encode())
                elif self.path.startswith("/debug/query"):
                    params = {k: v[0] for k, v in parse_qs(
                        urlparse(self.path).query).items()}
                    try:
                        qbody = router.tsdb.handle_query_json(params)
                    except ValueError as e:
                        self._send(400, "application/json",
                                   (json.dumps({"error": str(e)})
                                    + "\n").encode())
                        return
                    self._send(200, "application/json",
                               (qbody + "\n").encode())
                elif self.path == "/fleet/statz":
                    body = json.dumps(
                        router.fleet_statz(),
                        indent=2).encode() + b"\n"
                    self._send(200, "application/json", body)
                elif self.path.startswith("/debug/traces"):
                    # ?trace_id=… -> the CROSS-REPLICA stitched tree
                    # (router + every replica's timeline re-linked via
                    # traceparent parents); without it, the router's
                    # own recent-trace index
                    q = parse_qs(urlparse(self.path).query)
                    tid = q.get("trace_id", [""])[0]
                    if tid:
                        payload: Dict[str, Any] = \
                            router.stitched_trace(tid)
                    else:
                        payload = {
                            "traces": router.recorder.trace_ids()}
                    body = json.dumps(
                        payload, indent=2).encode() + b"\n"
                    self._send(200, "application/json", body)
                elif self.path.startswith("/debug/events"):
                    body = json.dumps({
                        "dropped": router.recorder.dropped,
                        "events": router.recorder.events(),
                    }, indent=2).encode() + b"\n"
                    self._send(200, "application/json", body)
                elif self.path.startswith("/debug/pprof"):
                    # the router's own continuous-profile ring (PR 19)
                    try:
                        ctype, text = router.profiler.handle_pprof(
                            parse_qs(urlparse(self.path).query))
                    except ValueError as e:
                        self._send(400, "application/json",
                                   (json.dumps({"error": str(e)})
                                    + "\n").encode())
                        return
                    self._send(200, ctype, text.encode())
                else:
                    self._send(404, "text/plain", b"not found\n")

            def do_POST(self) -> None:  # noqa: N802
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
                if self.path == "/register":
                    try:
                        out = router.register(
                            json.loads(body) if body else {})
                    except (ValueError, TypeError) as e:
                        self._send(400, "application/json",
                                   (json.dumps({"error": str(e)})
                                    + "\n").encode())
                        return
                    self._send(200, "application/json",
                               (json.dumps(out) + "\n").encode())
                    return
                if self.path == "/drain":
                    try:
                        out = router.drain(
                            json.loads(body) if body else {})
                    except (ValueError, TypeError) as e:
                        self._send(400, "application/json",
                                   (json.dumps({"error": str(e)})
                                    + "\n").encode())
                        return
                    except KeyError as e:
                        self._send(404, "application/json",
                                   (json.dumps({"error": "unknown "
                                    f"replica {e.args[0]!r}"})
                                    + "\n").encode())
                        return
                    self._send(200, "application/json",
                               (json.dumps(out) + "\n").encode())
                    return
                if self.path not in PROXY_PATHS:
                    self._send(404, "text/plain", b"not found\n")
                    return
                trace = obs.trace_from_header(
                    self.headers.get("traceparent"))
                try:
                    router.proxy(self, self.path, body, trace)
                except (BrokenPipeError, ConnectionResetError,
                        TimeoutError):
                    pass

            def _send(self, code: int, ctype: str,
                      body: bytes) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                try:
                    self.wfile.write(body)
                except OSError:
                    pass

            def log_message(self, format: str,  # noqa: A002
                            *args: Any) -> None:
                log.debug("router-http: " + format, *args)

        self._httpd = _PooledRouterHTTPServer(
            (host, port), Handler, workers=self.max_connections,
            shed=self._shed_conns)
        threading.Thread(target=self._httpd.serve_forever,
                         name="router-http", daemon=True).start()
        self._poller = threading.Thread(
            target=self._poll_loop, name="router-statz", daemon=True)
        self._poller.start()
        self.tsdb.start(self.alert_interval_s)
        self.profiler.start()
        if self._incidents is not None:
            self._incidents.start()
        log.info("router on http://%s:%d", host, self.port)
        return self

    @property
    def port(self) -> int:
        if self._httpd is None:
            return 0
        return int(self._httpd.server_address[1])

    def stop(self) -> None:
        self.tsdb.stop()
        self.profiler.stop()
        if self._incidents is not None:
            self._incidents.stop()
        self._stop.set()
        if self._poller is not None:
            self._poller.join(timeout=2)
            self._poller = None
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


def main(argv: Optional[List[str]] = None) -> int:
    """CLI: run the router tier.  Replicas register themselves
    (``workloads.server --register-with http://this-router``); static
    fleets can be pre-seeded with --replica."""
    p = argparse.ArgumentParser(prog="tpu-serve-router")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8100)
    p.add_argument("--replica", action="append", default=None,
                   metavar="HOST:PORT",
                   help="pre-seed a replica (repeatable); replicas "
                        "normally self-register via POST /register")
    p.add_argument("--prefix-chunk", type=int,
                   default=DEFAULT_PREFIX_CHUNK,
                   help="affinity-hash alignment in tokens; match the "
                        "replicas' --prefix-chunk (default 32 = the "
                        "engine's auto grid)")
    p.add_argument("--replica-ttl", type=float, default=10.0,
                   help="seconds without a heartbeat or /statz answer "
                        "before a replica is evicted")
    p.add_argument("--statz-interval", type=float, default=0.5,
                   help="seconds between /statz load-signal polls")
    p.add_argument("--max-connections", type=int, default=64,
                   help="router HTTP worker pool size (429 past 2x)")
    p.add_argument("--failover-attempts", type=int, default=3,
                   help="replicas tried per request before 503")
    p.add_argument("--overload-factor", type=float, default=4.0,
                   help="skip the affinity target when its queue+"
                        "in-flight exceeds this many times its "
                        "capacity (falls back to least-loaded)")
    p.add_argument("--breaker-reset", type=float, default=2.0,
                   help="per-replica circuit-breaker reset timeout")
    p.add_argument("--disagg", default=True,
                   action=argparse.BooleanOptionalAction,
                   help="phase-aware routing (default on, engaged "
                        "only when prefill- AND decode-class "
                        "replicas are registered): prefill-heavy "
                        "requests prefill on a prefill replica, the "
                        "finished KV state migrates to a decode "
                        "replica over POST /migrate, and decode "
                        "streams from there undisturbed")
    p.add_argument("--prefill-threshold", type=int,
                   default=DEFAULT_PREFILL_THRESHOLD, metavar="N",
                   help="prompt length (tokens) at or above which a "
                        "request counts as prefill-heavy; unary "
                        "requests qualify regardless")
    p.add_argument("--tenant-quota", action="append", default=None,
                   metavar="NAME=RATE[:BURST[:WEIGHT]]",
                   help="FLEET-level per-tenant token-rate quota "
                        "(same grammar as the serving flag; '*' is "
                        "the template for unknown tenants): the "
                        "router charges prompt+budget estimates at "
                        "route time and sheds 429 past the rate — "
                        "the globally-correct bucket replica-local "
                        "quotas cannot be")
    p.add_argument("--tenant-pinning", default=True,
                   action=argparse.BooleanOptionalAction,
                   help="sticky tenant->replica placement on a "
                        "second hash ring (default on): one tenant's "
                        "traffic lands on one replica, so the "
                        "replica-local WFQ/quota state is coherent "
                        "per tenant even without router quotas")
    p.add_argument("--session-affinity", default=True,
                   action=argparse.BooleanOptionalAction,
                   help="session KV affinity on a third hash ring "
                        "(default on): requests carrying session_id "
                        "prefer the replica holding their parked KV, "
                        "and when the pick lands elsewhere the router "
                        "moves the checkpoint over /session/export + "
                        "/session/import first (best-effort; any "
                        "failure degrades to plain re-prefill)")
    p.add_argument("--default-budget", type=int,
                   default=DEFAULT_BUDGET_ESTIMATE, metavar="N",
                   help="max-new-tokens estimate for tenant "
                        "accounting when a request does not carry "
                        "one (match the replicas' --max-new-tokens)")
    p.add_argument("--seed", type=int, default=None,
                   help="failover backoff jitter seed (chaos replay)")
    p.add_argument("--fault-spec", default=None, metavar="SPEC",
                   help="arm deterministic fault injection (chaos "
                        "testing ONLY), e.g. 'router.proxy:error:0.1'")
    p.add_argument("--flight-record-dir", default=None, metavar="DIR",
                   help="dump the flight-recorder journal on "
                        "exit/SIGTERM")
    p.add_argument("--slo", action="append", default=None,
                   metavar="CLASS=TTFT_MS[:DEADLINE_MS]",
                   help="SLO classes the fleet-level burn-rate alert "
                        "rules derive from (same grammar as the "
                        "serving flag; default interactive + batch) — "
                        "evaluated over the fleet-aggregate "
                        "tpu_router_fleet_burn_rate bridge gauge")
    p.add_argument("--alert-rules", default=None, metavar="FILE",
                   help="extra JSON alert rules ({\"rules\": [...]}) "
                        "for the router's in-process evaluator")
    p.add_argument("--alert-interval", type=float, default=5.0,
                   metavar="S",
                   help="TSDB sampling / alert evaluation tick "
                        "(seconds)")
    p.add_argument("--alert-window-scale", type=float, default=1.0,
                   metavar="X",
                   help="scale factor on the derived burn-rate rule "
                        "windows (5m/1h/6h * X)")
    p.add_argument("--incident-dir", default=None, metavar="DIR",
                   help="write alert-triggered fleet incident bundles "
                        "under this directory (env TPU_DP_INCIDENT_DIR)")
    p.add_argument("--profiler-hz", type=float, default=19.0,
                   metavar="HZ",
                   help="continuous sampling-profiler tick rate "
                        "(default 19)")
    args = p.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s")
    try:
        tenant_quotas = parse_tenant_quotas(args.tenant_quota)
    except ValueError as e:
        p.error(str(e))
    slo_policies = None
    if args.slo:
        try:
            slo_policies = obs.parse_slo_specs(args.slo)
        except ValueError as e:
            p.error(str(e))
    alert_rules = None
    if args.alert_rules:
        try:
            alert_rules = obs.load_alert_rules(args.alert_rules)
        except (OSError, ValueError) as e:
            p.error(f"--alert-rules: {e}")
    if args.alert_interval <= 0:
        p.error("--alert-interval must be > 0")
    if args.alert_window_scale <= 0:
        p.error("--alert-window-scale must be > 0")
    if args.profiler_hz <= 0:
        p.error("--profiler-hz must be > 0")
    incident_dir = (args.incident_dir
                    or os.environ.get("TPU_DP_INCIDENT_DIR") or None)
    rt = RouterServer(
        prefix_chunk=args.prefix_chunk,
        replica_ttl_s=args.replica_ttl,
        statz_interval_s=args.statz_interval,
        max_connections=args.max_connections,
        failover_attempts=args.failover_attempts,
        overload_factor=args.overload_factor,
        breaker_reset_s=args.breaker_reset,
        seed=args.seed,
        flight_record_dir=args.flight_record_dir,
        disagg=args.disagg,
        prefill_threshold=args.prefill_threshold,
        tenant_quotas=tenant_quotas,
        tenant_pinning=args.tenant_pinning,
        session_affinity=args.session_affinity,
        default_budget=args.default_budget,
        slo_policies=slo_policies,
        alert_rules=alert_rules,
        alert_interval_s=args.alert_interval,
        alert_window_scale=args.alert_window_scale,
        incident_dir=incident_dir,
        profiler_hz=args.profiler_hz)
    if args.fault_spec:
        faults.install(args.fault_spec, seed=args.seed or 0,
                       recorder=rt.recorder)
    for addr in args.replica or ():
        rt.register({"address": addr})
    rt.start(host=args.host, port=args.port)
    print(f"router on http://{args.host}:{rt.port}  "
          f"[POST /generate, /v1/completions, /register; "
          f"GET /healthz, /replicas, /metrics]", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        rt.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
