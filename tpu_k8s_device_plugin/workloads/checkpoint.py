"""Checkpoint / resume for the workload layer (orbax-backed).

The reference's daemons are stateless (SURVEY.md §5 "checkpoint/
resume: absent — state rebuilt from sysfs"), but its *workloads* are
long-running training jobs whose pods get rescheduled; a framework that
ships the workload layer natively (bench_main, the LM train steps) owes
them fault-tolerant state.  This module is that piece, shaped for how
JAX checkpoints on TPU pods:

* **whole-pytree save/restore** via orbax's PyTree handler — params,
  optimizer state, and the step counter in one atomic directory;
* **sharding-aware restore**: pass the target shardings (e.g. from
  ``transformer.lm_tree_shardings``) and every leaf is restored
  DIRECTLY onto its mesh placement — no host-memory staging of the
  full tree, which is what makes resuming an 8B model on small-host
  pods possible;
* **k8s-shaped layout**: one directory per step under a base dir (the
  pod's PVC/GCS mount), ``latest_step`` discovery, and keep-last-N
  garbage collection, so a rescheduled pod resumes from wherever its
  predecessor died.

Resume-equivalence is oracle-tested ACROSS processes: one interpreter
trains, checkpoints, and is SIGKILLed (no cleanup — a preempted pod);
a fresh interpreter restores and continues, and the loss trajectory
must match the uninterrupted run exactly.  Restore onto a different
mesh shape than the save ran on is exercised too
(tests/test_checkpoint.py, tests/ckpt_worker.py).
"""

from __future__ import annotations

import os
import re
import shutil
from typing import Any, Dict, Optional

import jax
import orbax.checkpoint as ocp

_STEP_RE = re.compile(r"^step_(\d+)$")


def _step_dir(base: str, step: int) -> str:
    return os.path.join(base, f"step_{step}")


def save_checkpoint(
    base_dir: str, step: int, state: Dict[str, Any],
    keep_last: Optional[int] = None,
) -> str:
    """Atomically save *state* (any pytree — typically
    ``{"params": ..., "opt_state": ...}``) under ``base_dir/step_<n>``.
    With *keep_last*, older step dirs beyond the newest N are removed
    after a successful save (never before)."""
    if step < 0:
        raise ValueError(f"step must be >= 0, got {step}")
    path = os.path.abspath(_step_dir(base_dir, step))
    ckpt = ocp.PyTreeCheckpointer()
    ckpt.save(path, state, force=True)
    if keep_last is not None:
        if keep_last < 1:
            raise ValueError("keep_last must be >= 1 when set")
        for old in sorted(list_steps(base_dir))[:-keep_last]:
            shutil.rmtree(_step_dir(base_dir, old), ignore_errors=True)
    return path


def list_steps(base_dir: str):
    """Completed checkpoint steps under *base_dir* (ascending)."""
    if not os.path.isdir(base_dir):
        return []
    steps = []
    for name in os.listdir(base_dir):
        m = _STEP_RE.match(name)
        if m:
            steps.append(int(m.group(1)))
    return sorted(steps)


def latest_step(base_dir: str) -> Optional[int]:
    steps = list_steps(base_dir)
    return steps[-1] if steps else None


def restore_checkpoint(
    base_dir: str,
    step: Optional[int] = None,
    template: Any = None,
    shardings: Any = None,
) -> Dict[str, Any]:
    """Restore the checkpoint at *step* (default: latest).

    ``template`` is an abstract/example pytree giving the structure and
    leaf shapes/dtypes; with ``shardings`` (a matching pytree of
    ``jax.sharding.Sharding``) each leaf restores directly onto its
    device placement — pass ``lm_tree_shardings(mesh, template)`` to
    resume a sharded training job without staging the full tree on one
    host."""
    if step is None:
        step = latest_step(base_dir)
        if step is None:
            raise FileNotFoundError(
                f"no checkpoints under {base_dir!r}")
    path = os.path.abspath(_step_dir(base_dir, step))
    if not os.path.isdir(path):
        raise FileNotFoundError(f"no checkpoint at {path!r}")
    ckpt = ocp.PyTreeCheckpointer()
    if template is None:
        return ckpt.restore(path)

    def spec(leaf, sh):
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=sh)

    if shardings is None:
        target = jax.tree_util.tree_map(lambda l: spec(l, None), template)
    else:
        target = jax.tree_util.tree_map(spec, template, shardings)
    # explicit restore args: ShapeDtypeStruct shardings alone are not
    # honored by the PyTree handler (it falls back to the saved-file
    # sharding and warns); construct_restore_args turns each target
    # leaf into an ArrayRestoreArgs carrying its sharding
    restore_args = ocp.checkpoint_utils.construct_restore_args(target)
    return ckpt.restore(path, target, restore_args=restore_args)
