"""Checkpoint / resume for the workload layer (orbax-backed).

The reference's daemons are stateless (SURVEY.md §5 "checkpoint/
resume: absent — state rebuilt from sysfs"), but its *workloads* are
long-running training jobs whose pods get rescheduled; a framework that
ships the workload layer natively (bench_main, the LM train steps) owes
them fault-tolerant state.  This module is that piece, shaped for how
JAX checkpoints on TPU pods:

* **whole-pytree save/restore** via orbax's PyTree handler — params,
  optimizer state, and the step counter in one atomic directory;
* **crash-safe saves**: every save writes into a hidden temp dir and
  commits with one ``os.replace``; a pod killed mid-save leaves a
  ``.step-tmp-<n>`` orphan (swept by the next save), never a torn
  ``step_N`` that a resume would trip over.  ``latest_step`` /
  ``restore_checkpoint`` additionally *skip* torn or partial step dirs
  (external copies, pre-atomic writers) instead of raising.  Multi-host
  sharded saves (every rank on one shared RWX volume) share one
  deterministic tmp dir per step; process 0 alone sweeps, commits, and
  garbage-collects, fenced by cross-process barriers;
* **sharding-aware restore**: pass the target shardings (e.g. from
  ``transformer.lm_tree_shardings``) and every leaf is restored
  DIRECTLY onto its mesh placement — no host-memory staging of the
  full tree, which is what makes resuming an 8B model on small-host
  pods possible;
* **k8s-shaped layout**: one directory per step under a base dir (the
  pod's PVC/GCS mount), ``latest_step`` discovery, and keep-last-N
  garbage collection, so a rescheduled pod resumes from wherever its
  predecessor died;
* **elastic-slice restarts**: :class:`ReshapeSignal` watches the slice
  membership file the device plugin maintains; when the slice reshapes
  under a running job (a member was evicted, survivors re-formed into
  a smaller generation — see docs/user-guide/resilience.md §Reshape
  runbook), the train loop checkpoints and exits with
  :data:`RESHAPE_EXIT_CODE` so the orchestrator restarts it under the
  new generation's ``TPU_WORKER_ID``/``JAX_*`` identity.  Reformation
  becomes a restart, not a loss.

Resume-equivalence is oracle-tested ACROSS processes: one interpreter
trains, checkpoints, and is SIGKILLed (no cleanup — a preempted pod);
a fresh interpreter restores and continues, and the loss trajectory
must match the uninterrupted run exactly.  Restore onto a different
mesh shape than the save ran on is exercised too
(tests/test_checkpoint.py, tests/ckpt_worker.py).
"""

from __future__ import annotations

import json
import logging
import os
import re
import shutil
import threading
from typing import Any, Dict, List, Optional

import jax
import orbax.checkpoint as ocp

from tpu_k8s_device_plugin.slice.state import Membership, load_membership
from tpu_k8s_device_plugin.types import constants

log = logging.getLogger(__name__)

_STEP_RE = re.compile(r"^step_(\d+)$")
_TMP_PREFIX = ".step-tmp-"
# orbax's own commit artifact: its metadata JSON.  A step dir missing it
# (or with an unparseable one — a truncated copy) is torn and skipped.
_ORBAX_METADATA = ("_CHECKPOINT_METADATA", "_METADATA")

# Exit code a reshape-interrupted workload leaves with after its final
# checkpoint: distinct from crash codes so supervisors/JobSets can tell
# "restart me under the new slice identity" from a real failure.
RESHAPE_EXIT_CODE = 77


def _step_dir(base: str, step: int) -> str:
    return os.path.join(base, f"step_{step}")


def _step_complete(path: str) -> bool:
    """Structural torn-dir check: the dir must hold a parseable orbax
    metadata file.  Our own saves commit atomically (tmp + rename), so
    this guards against external copies interrupted mid-transfer and
    truncated files."""
    for name in _ORBAX_METADATA:
        meta = os.path.join(path, name)
        if os.path.isfile(meta):
            try:
                with open(meta, "r", encoding="utf-8") as f:
                    json.load(f)
                return True
            except (OSError, ValueError):
                return False
    return False


def _sweep_orphans(base: str, keep: Optional[str] = None) -> None:
    """Remove temp dirs a crashed save left behind (best-effort).
    *keep* names the in-flight tmp dir of the CURRENT save, which must
    survive the sweep (another process may already be writing into it —
    multi-host saves share one deterministic tmp name)."""
    try:
        names = os.listdir(base)
    except OSError:
        return
    for name in names:
        if name.startswith(_TMP_PREFIX) and name != keep:
            shutil.rmtree(os.path.join(base, name), ignore_errors=True)


def _process_index() -> int:
    """This host's JAX process index; 0 when the distributed runtime is
    not initialized (single-process tests, plain CPU runs)."""
    try:
        return jax.process_index()
    except Exception as e:
        log.debug("jax.process_index() unavailable (%s); assuming 0", e)
        return 0


def _process_count() -> int:
    try:
        return jax.process_count()
    except Exception as e:
        log.debug("jax.process_count() unavailable (%s); assuming 1", e)
        return 1


def _barrier(name: str) -> None:
    """Cross-process sync point for multi-host saves; a no-op outside a
    multi-controller runtime."""
    if _process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def save_checkpoint(
    base_dir: str, step: int, state: Dict[str, Any],
    keep_last: Optional[int] = None,
) -> str:
    """Atomically save *state* (any pytree — typically
    ``{"params": ..., "opt_state": ...}``) under ``base_dir/step_<n>``.

    The tree is written into a hidden temp dir in the same filesystem
    and committed with one ``os.replace`` — a crash at ANY point leaves
    either no ``step_<n>`` or a whole one, never a torn directory.
    With *keep_last*, older step dirs beyond the newest N are removed
    after a successful save (never before).

    Multi-host safe: under an initialized ``jax.distributed`` runtime
    (the ``--sharded`` multihost deployment, every rank saving onto one
    shared RWX volume) orbax's sharded save is a collective, so every
    process writes into the SAME deterministic tmp dir
    (``.step-tmp-<step>``), and only process 0 sweeps orphans, renames
    the committed dir into place, and garbage-collects old steps —
    each mutation fenced by a cross-process barrier so no rank returns
    before the step dir exists."""
    if step < 0:
        raise ValueError(f"step must be >= 0, got {step}")
    base = os.path.abspath(base_dir)
    primary = _process_index() == 0
    os.makedirs(base, exist_ok=True)
    final = _step_dir(base, step)
    # deterministic, shared by every process: orbax's sharded save is a
    # collective that requires one directory slice-wide; a per-process
    # mkdtemp would tear multi-host checkpoints
    tmp = os.path.join(base, f"{_TMP_PREFIX}{step}")
    if primary:
        _sweep_orphans(base, keep=os.path.basename(tmp))
        # stale tmp of a crashed save of this same step: clear it before
        # any peer starts writing shards into it
        shutil.rmtree(tmp, ignore_errors=True)
    _barrier(f"ckpt_save_pre_{step}")
    try:
        ckpt = ocp.PyTreeCheckpointer()
        ckpt.save(tmp, state, force=True)
        # every process's shards must be durable before the commit rename
        _barrier(f"ckpt_save_written_{step}")
        if primary:
            if os.path.isdir(final):
                # overwrite semantics of the old force=True save: drop
                # the stale step before the commit rename (os.replace
                # onto a non-empty dir raises ENOTEMPTY)
                shutil.rmtree(final)
            os.replace(tmp, final)
    except BaseException:
        if primary:
            shutil.rmtree(tmp, ignore_errors=True)
        raise
    # no process may observe (or GC around) a not-yet-committed step
    _barrier(f"ckpt_save_committed_{step}")
    if keep_last is not None:
        if keep_last < 1:
            raise ValueError("keep_last must be >= 1 when set")
        if primary:
            for old in list_steps(base)[:-keep_last]:
                shutil.rmtree(_step_dir(base, old), ignore_errors=True)
    return final


def list_steps(base_dir: str) -> List[int]:
    """Completed checkpoint steps under *base_dir* (ascending).  Torn or
    partial step dirs are skipped, not raised on — a resume must come up
    from the newest WHOLE checkpoint."""
    if not os.path.isdir(base_dir):
        return []
    steps = []
    for name in os.listdir(base_dir):
        m = _STEP_RE.match(name)
        if not m:
            continue
        if not _step_complete(os.path.join(base_dir, name)):
            log.warning("skipping torn checkpoint dir %s",
                        os.path.join(base_dir, name))
            continue
        steps.append(int(m.group(1)))
    return sorted(steps)


def latest_step(base_dir: str) -> Optional[int]:
    steps = list_steps(base_dir)
    return steps[-1] if steps else None


def restore_checkpoint(
    base_dir: str,
    step: Optional[int] = None,
    template: Any = None,
    shardings: Any = None,
) -> Dict[str, Any]:
    """Restore the checkpoint at *step* (default: newest restorable).

    Without an explicit *step*, torn checkpoints are skipped: if the
    newest step dir fails to restore (truncated files under a complete-
    looking structure), the next older one is tried, so a damaged tail
    never strands a resumable job.  An explicit *step* restores exactly
    that one or raises.

    ``template`` is an abstract/example pytree giving the structure and
    leaf shapes/dtypes; with ``shardings`` (a matching pytree of
    ``jax.sharding.Sharding``) each leaf restores directly onto its
    device placement — pass ``lm_tree_shardings(mesh, template)`` to
    resume a sharded training job without staging the full tree on one
    host."""
    if step is not None:
        path = os.path.abspath(_step_dir(base_dir, step))
        if not os.path.isdir(path):
            raise FileNotFoundError(f"no checkpoint at {path!r}")
        return _restore_one(path, template, shardings)
    candidates = list_steps(base_dir)
    if not candidates:
        raise FileNotFoundError(f"no checkpoints under {base_dir!r}")
    last_err: Optional[BaseException] = None
    for cand in reversed(candidates):
        path = os.path.abspath(_step_dir(base_dir, cand))
        try:
            return _restore_one(path, template, shardings)
        except Exception as e:
            # a structurally-complete dir that still fails to load is
            # torn below the metadata (truncated array files): fall back
            # to the next older whole checkpoint
            log.warning("checkpoint %s unrestorable (%s); trying older",
                        path, e)
            last_err = e
    raise FileNotFoundError(
        f"no restorable checkpoint under {base_dir!r} "
        f"(last error: {last_err})")


def _restore_one(path: str, template: Any, shardings: Any
                 ) -> Dict[str, Any]:
    ckpt = ocp.PyTreeCheckpointer()
    if template is None:
        return ckpt.restore(path)

    def spec(leaf, sh):
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=sh)

    if shardings is None:
        target = jax.tree_util.tree_map(lambda l: spec(l, None), template)
    else:
        target = jax.tree_util.tree_map(spec, template, shardings)
    # explicit restore args: ShapeDtypeStruct shardings alone are not
    # honored by the PyTree handler (it falls back to the saved-file
    # sharding and warns); construct_restore_args turns each target
    # leaf into an ArrayRestoreArgs carrying its sharding
    restore_args = ocp.checkpoint_utils.construct_restore_args(target)
    return ckpt.restore(path, target, restore_args=restore_args)


class ReshapeSignal:
    """Cooperative elastic-slice restart hook for train loops.

    The device plugin stamps every slice-coordinated container with
    ``TPU_SLICE_GENERATION`` (the membership generation its
    ``TPU_WORKER_ID``/``JAX_*`` identity belongs to) and keeps the
    crash-safe membership file current as the slice reshapes.  A train
    loop polls :meth:`check` between steps; once the live generation
    moves past the baseline — survivors re-formed without a member, or
    an evicted member returned — the loop saves a final checkpoint and
    exits with :data:`RESHAPE_EXIT_CODE` so the orchestrator restarts
    it under the new generation's identity::

        signal = ReshapeSignal(state_path)
        for step in range(start, steps):
            params, opt_state, loss = train_step(...)
            if signal.check() is not None:
                save_checkpoint(ckpt_dir, step, state)
                raise SystemExit(RESHAPE_EXIT_CODE)

    In-process integrations (tests, single-binary harnesses) can skip
    the file watch and wire :meth:`fire` straight to
    ``SliceClient.set_reshape_callback``.
    """

    def __init__(
        self,
        state_path: str = constants.SLICE_STATE_FILE,
        generation: Optional[int] = None,
    ) -> None:
        self._path = state_path
        self._lock = threading.Lock()
        self._fired: Optional[Membership] = None
        if generation is not None:
            self.baseline = generation
        else:
            env_gen = os.environ.get(constants.ENV_TPU_SLICE_GENERATION)
            if env_gen:
                # the generation Allocate stamped this container with: the
                # authoritative baseline even if the file already moved on
                self.baseline = int(env_gen)
            else:
                m = load_membership(state_path)
                self.baseline = m.generation if m is not None else 0

    def fire(self, old: Optional[Membership], new: Membership) -> None:
        """Direct wiring for ``SliceClient.set_reshape_callback``."""
        with self._lock:
            self._fired = new

    def check(self) -> Optional[Membership]:
        """The new membership once the slice has reshaped past this
        job's baseline generation; None while the identity holds.  A
        dissolved slice (membership file gone) is NOT a reshape — the
        job keeps running on whatever devices it holds."""
        with self._lock:
            if self._fired is not None:
                return self._fired
        m = load_membership(self._path)
        if m is None or self.baseline <= 0:
            return None
        if m.generation != self.baseline:
            with self._lock:
                self._fired = m
            return m
        return None

    @property
    def triggered(self) -> bool:
        with self._lock:
            return self._fired is not None
