"""Fused flash attention as a Pallas TPU kernel.

The workload layer's hot op, written for the hardware instead of left to
XLA: the einsum attention in transformer.py materializes the [B, H, T, T]
score matrix in HBM (O(T²) memory traffic); this kernel streams K/V
blocks through VMEM and keeps the online-softmax state (running max,
normalizer, output accumulator) in registers, so HBM traffic is O(T·D)
and the two matmuls per block stay on the MXU back-to-back
(flash/blockwise attention, public technique — same math as
ring_attention._online_softmax_update, one chip instead of a ring).

Design notes, per /opt/skills/guides/pallas_guide.md:

* grid = (B, H, T/block_q): one program per query block; K/V arrive as
  whole [T, D] VMEM blocks per (batch, head) and are sliced with
  ``pl.ds`` inside the loop (T·D·2B ≤ ~0.5 MB at T=2k, D=128 — well
  inside the ~16 MB VMEM budget; block-grid K/V is the next step up).
* accumulators ride the ``fori_loop`` carry in f32; both matmuls use
  ``preferred_element_type=f32`` (pitfall #5).
* causal masking skips entirely-future K blocks by bounding the loop at
  the query block's diagonal — the FLOP skipping that makes causal
  flash ~2x the naive masked form; the diagonal block itself is masked
  with 2D ``broadcasted_iota`` (pitfall #4).
* backward is Pallas too: the forward saves (q, k, v, o, logsumexp),
  and two kernels rebuild probabilities blockwise from the logsumexp —
  ``_dq_kernel`` (grid over query blocks, streams K/V) and
  ``_dkv_kernel`` (grid over key blocks, streams Q/dO) — so the
  backward never materializes the [T, T] score matrix either.  The
  per-row correction term delta = Σ_d dO·O is one cheap XLA
  elementwise pass.  Causal FLOP skipping mirrors the forward: dq
  bounds its K loop at the diagonal, dkv *starts* its Q loop there.
  Wired through ``jax.custom_vjp`` (guide "Patterns: Custom VJP").

Layout is [B, T, H, D] to match the rest of the workload layer; the
kernel itself runs [B, H, T, D] (transposes fuse into neighbours).  On
non-TPU backends the kernel runs in interpreter mode automatically, so
the CPU test mesh exercises the identical code path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU memory spaces; absent on some non-TPU installs
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    pltpu = None
    _VMEM = None

_NEG_INF = float("-inf")

# Per-row residuals (logsumexp, delta) are stored lane-broadcast as
# [..., T, _ROW_LANES]: Mosaic requires the last two block dims to be
# (8, 128)-aligned or whole-array, so a bare [T] row vector cannot be a
# kernel output; 128 lanes is the minimum aligned tile (same layout as
# jax.experimental.pallas.ops.tpu.flash_attention's l/m residuals).
_ROW_LANES = 128


def _fit_block(T: int, want: int) -> int:
    """Largest divisor of T at or below *want* (trace-time Python ints;
    hardware-aligned when T is a multiple of the requested block)."""
    b = min(want, T)
    while T % b:
        b -= 1
    return b


def _block_spec(shape, index_map):
    """BlockSpec pinned to VMEM (guide pitfall #1) when the TPU memory
    spaces are importable; plain spec otherwise (interpreter fallback)."""
    if _VMEM is not None:
        return pl.BlockSpec(shape, index_map, memory_space=_VMEM)
    return pl.BlockSpec(shape, index_map)


def _causal_mask(s, q_start, k_start):
    """Mask score block *s* to the causal lower triangle: entry (a, b)
    survives iff global row q_start+a >= global column k_start+b
    (2D ``broadcasted_iota`` — guide pitfall #4)."""
    bq, bk = s.shape
    q_pos = q_start + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = k_start + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(q_pos >= k_pos, s, _NEG_INF)


def _causal_hi(qi, block_q: int, block_k: int):
    """First K block strictly past query block *qi*'s diagonal — the
    exclusive upper bound of the visible K range: ceil((qi+1)·bq / bk)."""
    return lax.div(qi * block_q + block_q + block_k - 1, block_k)


def _attn_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref=None, *, block_k: int,
    causal: bool, scale: float,
):
    """One query block vs all (visible) key blocks, online softmax.

    ``lse_ref`` is only bound when the caller asked for residuals (the
    custom-VJP forward); the inference path has a single output and
    skips the logsumexp write entirely."""
    qi = pl.program_id(2)
    block_q, head_dim = q_ref.shape[-2], q_ref.shape[-1]
    seq_len = k_ref.shape[-2]
    n_kblocks = seq_len // block_k

    q = q_ref[0, 0]  # [bq, D], input dtype — bf16 feeds the MXU at
    # full rate; both dots accumulate in f32 via preferred_element_type

    o0 = jnp.zeros((block_q, head_dim), jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)

    hi = _causal_hi(qi, block_q, block_k) if causal else n_kblocks

    def body(j, carry):
        o, l, m = carry
        k_blk = k_ref[0, 0, pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk] f32
        if causal:
            s = _causal_mask(s, qi * block_q, j * block_k)
        blk_max = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, blk_max)
        # fully-masked rows keep m=-inf; guard the exp like the ring path
        safe_m = jnp.where(jnp.isinf(m_new), 0.0, m_new)
        p = jnp.exp(s - safe_m[:, None])
        corr = jnp.where(
            jnp.isinf(m), jnp.where(jnp.isinf(m_new), 1.0, 0.0),
            jnp.exp(m - safe_m),
        )
        l = l * corr + jnp.sum(p, axis=-1)
        o = o * corr[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return o, l, m_new

    o, l, m = lax.fori_loop(0, hi, body, (o0, l0, m0))
    denom = jnp.where(l == 0.0, 1.0, l)
    o_ref[0, 0] = (o / denom[:, None]).astype(o_ref.dtype)
    if lse_ref is not None:
        # logsumexp residual for the Pallas backward: P = exp(S - lse)
        lse = jnp.where(l == 0.0, _NEG_INF, m + jnp.log(denom))
        lse_ref[0, 0] = jnp.broadcast_to(
            lse[:, None], (block_q, _ROW_LANES)
        )


def _flash_fwd_bhtd(
    q, k, v, causal: bool, block_q: int, block_k: int,
    interpret: bool, save_residuals: bool = False,
):
    """Forward on [B, H, Tq, D] × [B, H, Tk, D] (Tq may differ from Tk
    for unmasked cross-block tiles; ``causal`` requires Tq == Tk since
    the mask is storage-order-aligned).

    Returns ``out [B, H, Tq, D]``, or ``(out, lse)`` when
    ``save_residuals`` — lse is the per-row logsumexp stored
    lane-broadcast as ``[B, H, Tq, _ROW_LANES]`` f32 (see the
    ``_ROW_LANES`` note; consumers read lane 0).  The inference path
    leaves residuals off so no lse HBM write is paid.
    """
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    if causal and Tq != Tk:
        raise ValueError("causal flash requires Tq == Tk")
    scale = 1.0 / (D ** 0.5)
    grid = (B, H, Tq // block_q)
    q_spec = _block_spec(
        (1, 1, block_q, D), lambda b, h, i: (b, h, i, 0)
    )
    kv_spec = _block_spec((1, 1, Tk, D), lambda b, h, i: (b, h, 0, 0))
    kernel = functools.partial(
        _attn_kernel, block_k=block_k, causal=causal, scale=scale
    )
    out_specs = [q_spec]
    out_shape = [jax.ShapeDtypeStruct(q.shape, q.dtype)]
    if save_residuals:
        out_specs.append(_block_spec(
            (1, 1, block_q, _ROW_LANES), lambda b, h, i: (b, h, i, 0)
        ))
        out_shape.append(
            jax.ShapeDtypeStruct((B, H, Tq, _ROW_LANES), jnp.float32)
        )
    result = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(q, k, v)
    return tuple(result) if save_residuals else result[0]


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
    *, block_k: int, causal: bool, scale: float,
):
    """dQ for one query block: stream K/V blocks, rebuild P from lse.

    dS = P ∘ (dO·Vᵀ − delta); dQ = scale · dS·K.  Same causal loop
    bound as the forward (K blocks past the diagonal contribute 0).
    """
    qi = pl.program_id(2)
    block_q, head_dim = q_ref.shape[-2], q_ref.shape[-1]
    seq_len = k_ref.shape[-2]
    n_kblocks = seq_len // block_k

    q = q_ref[0, 0]
    do = do_ref[0, 0]
    lse = lse_ref[0, 0][:, :1]      # [bq, 1] f32 (lane-broadcast store)
    delta = delta_ref[0, 0][:, :1]  # [bq, 1] f32
    hi = _causal_hi(qi, block_q, block_k) if causal else n_kblocks

    def body(j, dq):
        k_blk = k_ref[0, 0, pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            s = _causal_mask(s, qi * block_q, j * block_k)
        p = jnp.exp(s - lse)  # masked/-inf rows → exactly 0
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)
        return dq + jax.lax.dot_general(
            ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    dq = lax.fori_loop(
        0, hi, body, jnp.zeros((block_q, head_dim), jnp.float32)
    )
    dq_ref[0, 0] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    *, block_q: int, block_k: int, causal: bool, scale: float,
):
    """dK/dV for one key block, one query block per grid step.

    dV = P^T.dO; dK = scale * dS^T.Q.  The query blocks are the innermost
    (sequential) grid dimension and dk/dv accumulate in f32 directly in
    the output refs, which stay VMEM-resident across the revisits
    because their index map ignores that dimension - so VMEM holds one
    (Q, K, V, dO) block tuple at a time regardless of T.  The causal
    lower bound mirrors the forward's upper bound: the first query
    block whose last row reaches this key block is
    floor(kj*block_k / block_q); earlier query blocks skip the matmuls
    via ``pl.when`` (FLOPs only — the pipeline still DMAs their Q/dO
    blocks in; remapping the grid to start at the diagonal would also
    skip the fetches).
    """
    kj, i = pl.program_id(2), pl.program_id(3)
    block_ksz, head_dim = k_ref.shape[-2], k_ref.shape[-1]

    @pl.when(i == 0)
    def _init():
        dk_ref[0, 0] = jnp.zeros((block_ksz, head_dim), jnp.float32)
        dv_ref[0, 0] = jnp.zeros((block_ksz, head_dim), jnp.float32)

    lo = lax.div(kj * block_k, block_q) if causal else 0

    @pl.when(i >= lo)
    def _accum():
        k_blk = k_ref[0, 0]
        v_blk = v_ref[0, 0]
        q_blk = q_ref[0, 0]
        do_blk = do_ref[0, 0]
        lse = lse_ref[0, 0][:, :1]      # [bq, 1] f32 (lane-broadcast)
        delta = delta_ref[0, 0][:, :1]  # [bq, 1] f32
        s = jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            s = _causal_mask(s, i * block_q, kj * block_k)
        p = jnp.exp(s - lse)
        dv_ref[0, 0] += jax.lax.dot_general(
            p.astype(do_blk.dtype), do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)
        dk_ref[0, 0] += scale * jax.lax.dot_general(
            ds.astype(q_blk.dtype), q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )


def _flash_bwd_bhtd(
    q, k, v, lse, delta, g, causal: bool, block_q: int, block_k: int,
    interpret: bool, keep_f32: bool = False,
):
    """Pallas backward on [B, H, Tq, D] x [B, H, Tk, D]: one dq pass
    (grid over query blocks) + one fused dk/dv pass (grid over key
    blocks).

    ``lse``/``delta`` are the per-row logsumexp and Σ_d dO·O in the
    lane-broadcast [B, H, T, _ROW_LANES] layout.  They need not come
    from *this* q/k/v — ring attention passes the GLOBAL lse/delta with
    per-ring-step blocks, which decomposes the exact backward blockwise.

    ``keep_f32`` returns all three gradients in f32 (for callers that
    accumulate partials, like the ring) instead of the input dtypes.
    """
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    if causal and Tq != Tk:
        raise ValueError("causal flash requires Tq == Tk")
    scale = 1.0 / (D ** 0.5)

    blk_spec = lambda bs: _block_spec(  # noqa: E731
        (1, 1, bs, D), lambda b, h, i: (b, h, i, 0)
    )
    full_spec = _block_spec((1, 1, Tk, D), lambda b, h, i: (b, h, 0, 0))
    row_blk = lambda bs: _block_spec(  # noqa: E731
        (1, 1, bs, _ROW_LANES), lambda b, h, i: (b, h, i, 0)
    )

    # dimension_semantics lets Mosaic split "parallel" grid dims across
    # TensorCores on megacore parts; dkv's innermost (query-block) dim
    # must stay sequential ("arbitrary") because dk/dv accumulate
    # across it.  compiler_params stays None in interpreter mode.
    def _semantics(*dims):
        if pltpu is None or interpret:
            return None
        return pltpu.CompilerParams(dimension_semantics=dims)

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, block_k=block_k, causal=causal, scale=scale
        ),
        grid=(B, H, Tq // block_q),
        in_specs=[
            blk_spec(block_q), full_spec, full_spec, blk_spec(block_q),
            row_blk(block_q), row_blk(block_q),
        ],
        out_specs=blk_spec(block_q),
        out_shape=jax.ShapeDtypeStruct(
            q.shape, jnp.float32 if keep_f32 else q.dtype
        ),
        interpret=interpret,
        compiler_params=_semantics("parallel", "parallel", "parallel"),
    )(q, k, v, g, lse, delta)

    kblk4 = _block_spec(
        (1, 1, block_k, D), lambda b, h, kj, i: (b, h, kj, 0)
    )
    qblk4 = _block_spec(
        (1, 1, block_q, D), lambda b, h, kj, i: (b, h, i, 0)
    )
    row4 = _block_spec(
        (1, 1, block_q, _ROW_LANES), lambda b, h, kj, i: (b, h, i, 0)
    )
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, block_q=block_q, block_k=block_k,
            causal=causal, scale=scale,
        ),
        grid=(B, H, Tk // block_k, Tq // block_q),
        in_specs=[qblk4, kblk4, kblk4, qblk4, row4, row4],
        out_specs=[kblk4, kblk4],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, jnp.float32),
            jax.ShapeDtypeStruct(v.shape, jnp.float32),
        ],
        interpret=interpret,
        compiler_params=_semantics(
            "parallel", "parallel", "parallel", "arbitrary"
        ),
    )(q, k, v, g, lse, delta)
    if keep_f32:
        return dq, dk, dv
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_bhtd(q, k, v, causal, block_q, block_k, interpret):
    # primal (inference) path: no residuals, no lse HBM write
    return _flash_fwd_bhtd(q, k, v, causal, block_q, block_k, interpret)


def _flash_bhtd_fwd(q, k, v, causal, block_q, block_k, interpret):
    o, lse = _flash_fwd_bhtd(
        q, k, v, causal, block_q, block_k, interpret, save_residuals=True
    )
    return o, (q, k, v, o, lse)


def _flash_bhtd_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, o, lse = res
    B, H, T, _ = q.shape
    # delta_i = Σ_d dO·O per row — one elementwise HBM pass, f32;
    # stored lane-broadcast like lse so both feed the kernels directly
    delta = jnp.broadcast_to(
        jnp.sum(
            g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
        )[..., None],
        (B, H, T, _ROW_LANES),
    )
    return _flash_bwd_bhtd(
        q, k, v, lse, delta, g, causal, block_q, block_k, interpret
    )


_flash_bhtd.defvjp(_flash_bhtd_fwd, _flash_bhtd_bwd)


def flash_attention(
    q: jax.Array,  # [B, T, H, D]
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    block_q: int = 256,
    block_k: int = 512,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused attention on [B, T, H, D]; drop-in for
    ``transformer.local_causal_attention``'s math (natural token order —
    causality is storage-order-driven here, so zig-zag-permuted layouts
    must keep using the ring path).

    Block sizes degrade to the largest divisor of T at or below the
    requested size (T=384 with the 256 default runs at block 192), so
    any sequence length works; pick power-of-two T for the aligned fast
    path.  ``interpret`` defaults to "compiled on TPU, interpreter
    elsewhere", so CPU test meshes run the identical kernel.
    """
    B, T, H, D = q.shape
    block_q = _fit_block(T, block_q)
    block_k = _fit_block(T, block_k)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    out = _flash_bhtd(qt, kt, vt, causal, block_q, block_k, interpret)
    return out.transpose(0, 2, 1, 3)


def flash_causal_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, positions: jax.Array
) -> jax.Array:
    """``transformer.AttnFn``-shaped causal adapter: positions must be
    the natural 0..T-1 order (flash causality is storage-order-driven);
    use ring attention for permuted layouts.  Grouped K/V (GQA)
    expand to the query head count before the kernel."""
    del positions
    from .transformer import repeat_kv

    return flash_attention(
        q, repeat_kv(k, q.shape[2]), repeat_kv(v, q.shape[2]),
        causal=True,
    )


# ---------------------------------------------------------------------------
# Block-level building blocks for ring attention (``ring_attention.py``
# ``impl="flash"``).  Ring attention composes attention over rotating K/V
# blocks; these expose the kernels in the composable form: the forward
# returns the per-block (normalized output, logsumexp) pair that the ring
# merges across steps, and the backward takes the ring's GLOBAL
# lse/delta, under which the exact gradient decomposes blockwise.
# They are not differentiable themselves — the ring wraps the whole
# rotation in one ``jax.custom_vjp``.
# ---------------------------------------------------------------------------


def _prep_blocks(Tq: int, Tk: int, block_q: int, block_k: int,
                 interpret: Optional[bool]):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _fit_block(Tq, block_q), _fit_block(Tk, block_k), interpret


def flash_block_forward(
    q: jax.Array,  # [B, T, H, D]
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    block_q: int = 256,
    block_k: int = 512,
    interpret: Optional[bool] = None,
):
    """One attention block pair, [B, Tq, H, D] x [B, Tk, H, D]
    (Tq != Tk allowed for unmasked tiles; causal needs Tq == Tk): returns
    ``(o, lse)`` where *o* is normalized over *this* K/V block only and
    *lse* is the per-row logsumexp ``[B, T, H]`` f32 (−inf for rows with
    no visible keys).  Partials with these semantics merge exactly:
    ``o = Σ_s exp(lse_s − lse_tot)·o_s``, ``lse_tot = logaddexp_s``.
    """
    bq, bk, interpret = _prep_blocks(
        q.shape[1], k.shape[1], block_q, block_k, interpret
    )
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    o, lse = _flash_fwd_bhtd(
        qt, kt, vt, causal, bq, bk, interpret, save_residuals=True
    )
    return o.transpose(0, 2, 1, 3), lse[..., 0].transpose(0, 2, 1)


def flash_block_grads(
    q: jax.Array,   # [B, T, H, D]
    k: jax.Array,
    v: jax.Array,
    do: jax.Array,  # [B, T, H, D] upstream gradient
    lse: jax.Array,    # [B, T, H] f32 — GLOBAL logsumexp
    delta: jax.Array,  # [B, T, H] f32 — GLOBAL Σ_d dO·O per row
    causal: bool = False,
    block_q: int = 256,
    block_k: int = 512,
    interpret: Optional[bool] = None,
):
    """Gradient contributions of one block pair given the global
    softmax statistics: returns ``(dq, dk, dv)`` on [B, T, H, D] in
    **f32** (callers accumulate partials across blocks — one downcast
    at the end beats n per-block roundings) — the exact per-block terms
    of the full backward, so summing dq over K/V blocks and dk/dv over
    query blocks reproduces the dense gradient.
    """
    bq, bk, interpret = _prep_blocks(
        q.shape[1], k.shape[1], block_q, block_k, interpret
    )
    qt, kt, vt, dot = (x.transpose(0, 2, 1, 3) for x in (q, k, v, do))
    lane = lambda r: jnp.broadcast_to(  # noqa: E731 — [B,T,H]→[B,H,T,L]
        r.transpose(0, 2, 1)[..., None].astype(jnp.float32),
        (*r.transpose(0, 2, 1).shape, _ROW_LANES),
    )
    dq, dk, dv = _flash_bwd_bhtd(
        qt, kt, vt, lane(lse), lane(delta), dot, causal, bq, bk,
        interpret, keep_f32=True,
    )
    return tuple(x.transpose(0, 2, 1, 3) for x in (dq, dk, dv))
