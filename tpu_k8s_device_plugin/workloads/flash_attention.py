"""Fused flash attention as a Pallas TPU kernel.

The workload layer's hot op, written for the hardware instead of left to
XLA: the einsum attention in transformer.py materializes the [B, H, T, T]
score matrix in HBM (O(T²) memory traffic); this kernel streams K/V
blocks through VMEM and keeps the online-softmax state (running max,
normalizer, output accumulator) in registers, so HBM traffic is O(T·D)
and the two matmuls per block stay on the MXU back-to-back
(flash/blockwise attention, public technique — same math as
ring_attention._online_softmax_update, one chip instead of a ring).

Design notes, per /opt/skills/guides/pallas_guide.md:

* grid = (B, H, T/block_q): one program per query block; K/V arrive as
  whole [T, D] VMEM blocks per (batch, head) and are sliced with
  ``pl.ds`` inside the loop (T·D·2B ≤ ~0.5 MB at T=2k, D=128 — well
  inside the ~16 MB VMEM budget; block-grid K/V is the next step up).
* accumulators ride the ``fori_loop`` carry in f32; both matmuls use
  ``preferred_element_type=f32`` (pitfall #5).
* causal masking skips entirely-future K blocks by bounding the loop at
  the query block's diagonal — the FLOP skipping that makes causal
  flash ~2x the naive masked form; the diagonal block itself is masked
  with 2D ``broadcasted_iota`` (pitfall #4).
* backward is recompute-based XLA math: the saved residuals are
  (q, k, v, o) and ``_reference_bwd`` rebuilds the full softmax from
  them (the einsum memory profile), wired through ``jax.custom_vjp``
  (guide "Patterns: Custom VJP"); a Pallas backward kernel working from
  a saved logsumexp is the next increment.

Layout is [B, T, H, D] to match the rest of the workload layer; the
kernel itself runs [B, H, T, D] (transposes fuse into neighbours).  On
non-TPU backends the kernel runs in interpreter mode automatically, so
the CPU test mesh exercises the identical code path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU memory spaces; absent on some non-TPU installs
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    pltpu = None
    _VMEM = None

_NEG_INF = float("-inf")


def _fit_block(T: int, want: int) -> int:
    """Largest divisor of T at or below *want* (trace-time Python ints;
    hardware-aligned when T is a multiple of the requested block)."""
    b = min(want, T)
    while T % b:
        b -= 1
    return b


def _block_spec(shape, index_map):
    """BlockSpec pinned to VMEM (guide pitfall #1) when the TPU memory
    spaces are importable; plain spec otherwise (interpreter fallback)."""
    if _VMEM is not None:
        return pl.BlockSpec(shape, index_map, memory_space=_VMEM)
    return pl.BlockSpec(shape, index_map)


def _attn_kernel(
    q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool, scale: float
):
    """One query block vs all (visible) key blocks, online softmax."""
    qi = pl.program_id(2)
    block_q, head_dim = q_ref.shape[-2], q_ref.shape[-1]
    seq_len = k_ref.shape[-2]
    n_kblocks = seq_len // block_k

    q = q_ref[0, 0]  # [bq, D], input dtype — bf16 feeds the MXU at
    # full rate; both dots accumulate in f32 via preferred_element_type

    o0 = jnp.zeros((block_q, head_dim), jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)

    if causal:
        # visible K blocks: all with start <= this q block's last row
        hi = lax.div(qi * block_q + block_q + block_k - 1, block_k)
    else:
        hi = n_kblocks

    def body(j, carry):
        o, l, m = carry
        k_blk = k_ref[0, 0, pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk] f32
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        blk_max = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, blk_max)
        # fully-masked rows keep m=-inf; guard the exp like the ring path
        safe_m = jnp.where(jnp.isinf(m_new), 0.0, m_new)
        p = jnp.exp(s - safe_m[:, None])
        corr = jnp.where(
            jnp.isinf(m), jnp.where(jnp.isinf(m_new), 1.0, 0.0),
            jnp.exp(m - safe_m),
        )
        l = l * corr + jnp.sum(p, axis=-1)
        o = o * corr[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return o, l, m_new

    o, l, m = lax.fori_loop(0, hi, body, (o0, l0, m0))
    denom = jnp.where(l == 0.0, 1.0, l)
    o_ref[0, 0] = (o / denom[:, None]).astype(o_ref.dtype)


def _flash_fwd_bhtd(
    q, k, v, causal: bool, block_q: int, block_k: int,
    interpret: bool,
):
    """Forward on [B, H, T, D] layout; returns [B, H, T, D]."""
    B, H, T, D = q.shape
    scale = 1.0 / (D ** 0.5)
    grid = (B, H, T // block_q)
    q_spec = _block_spec(
        (1, 1, block_q, D), lambda b, h, i: (b, h, i, 0)
    )
    kv_spec = _block_spec((1, 1, T, D), lambda b, h, i: (b, h, 0, 0))
    kernel = functools.partial(
        _attn_kernel, block_k=block_k, causal=causal, scale=scale
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v)


def _reference_bwd(q, k, v, o, g, causal: bool):
    """Standard flash backward from recomputed scores, full-matrix XLA
    math in f32 (the einsum attention's memory profile — a Pallas
    backward kernel is the planned next increment)."""
    qf, kf, vf, of, gf = (
        t.astype(jnp.float32) for t in (q, k, v, o, g)
    )
    D = q.shape[-1]
    scale = 1.0 / (D ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
    if causal:
        T, S = s.shape[-2], s.shape[-1]
        mask = (
            lax.broadcasted_iota(jnp.int32, (T, S), 0)
            >= lax.broadcasted_iota(jnp.int32, (T, S), 1)
        )
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, gf)
    dp = jnp.einsum("bhqd,bhkd->bhqk", gf, vf)
    delta = jnp.sum(gf * of, axis=-1, keepdims=True)  # [B,H,T,1]
    ds = p * (dp - delta)
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, kf) * scale
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf) * scale
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_bhtd(q, k, v, causal, block_q, block_k, interpret):
    return _flash_fwd_bhtd(q, k, v, causal, block_q, block_k, interpret)


def _flash_bhtd_fwd(q, k, v, causal, block_q, block_k, interpret):
    o = _flash_fwd_bhtd(q, k, v, causal, block_q, block_k, interpret)
    return o, (q, k, v, o)


def _flash_bhtd_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, o = res
    return _reference_bwd(q, k, v, o, g, causal)


_flash_bhtd.defvjp(_flash_bhtd_fwd, _flash_bhtd_bwd)


def flash_attention(
    q: jax.Array,  # [B, T, H, D]
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    block_q: int = 256,
    block_k: int = 512,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused attention on [B, T, H, D]; drop-in for
    ``transformer.local_causal_attention``'s math (natural token order —
    causality is storage-order-driven here, so zig-zag-permuted layouts
    must keep using the ring path).

    Block sizes degrade to the largest divisor of T at or below the
    requested size (T=384 with the 256 default runs at block 192), so
    any sequence length works; pick power-of-two T for the aligned fast
    path.  ``interpret`` defaults to "compiled on TPU, interpreter
    elsewhere", so CPU test meshes run the identical kernel.
    """
    B, T, H, D = q.shape
    block_q = _fit_block(T, block_q)
    block_k = _fit_block(T, block_k)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    out = _flash_bhtd(qt, kt, vt, causal, block_q, block_k, interpret)
    return out.transpose(0, 2, 1, 3)


def flash_causal_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, positions: jax.Array
) -> jax.Array:
    """``transformer.AttnFn``-shaped causal adapter: positions must be
    the natural 0..T-1 order (flash causality is storage-order-driven);
    use ring attention for permuted layouts."""
    del positions
    return flash_attention(q, k, v, causal=True)
