"""Grammar-constrained decoding support (vLLM's guided decoding).

The TPU-shaped design: a grammar is compiled AHEAD of decoding into a
token-level DFA — ``table[state, token] -> next state`` (-1 rejects)
and a ``mask[state, token]`` additive logit mask (0 / -1e9) — and the
DFA state rides the decode scan's carry.  Constrained generation then
costs one gather and one add per step inside the SAME compiled
``lax.scan`` as unconstrained decoding: no per-token host round-trip,
no Python in the loop (the xgrammar/outlines token-bitmask idea,
expressed as jit-friendly arrays).

Pipeline:

1. ``regex_to_dfa(pattern)`` — a small regex subset (literals, ``|``,
   ``*`` ``+`` ``?``, ``(...)``, ``[a-z]`` classes, ``.``) compiled
   via Thompson NFA + subset construction over the byte alphabet.
2. ``token_dfa(dfa, token_bytes, eos_id)`` — the char DFA is closed
   over the tokenizer's vocabulary: walking each token's bytes from
   each state yields the token-level table; ``eos`` is allowed exactly
   in ACCEPTING states (structural completion gates the stop).

Engines take the result as ``ServingEngine(grammar=...)`` and requests
opt in with ``admit(grammar=True)``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, FrozenSet, List, Tuple

import numpy as np

_REJECT = -1


# -- char-level regex -> DFA -------------------------------------------------

@dataclass(frozen=True)
class CharDfa:
    """Byte-alphabet DFA: table [n_states, 256] int32 (-1 = reject),
    state 0 initial, ``accepting`` a bool per state."""

    table: np.ndarray
    accepting: np.ndarray


class _Nfa:
    """Thompson construction: states are ints, transitions are
    (state, byte) -> set[state], plus epsilon edges."""

    def __init__(self):
        self.eps: Dict[int, set] = {}
        self.edges: Dict[Tuple[int, int], set] = {}
        self.n = 0

    def new(self) -> int:
        s = self.n
        self.n += 1
        return s

    def add_eps(self, a: int, b: int) -> None:
        self.eps.setdefault(a, set()).add(b)

    def add(self, a: int, byte: int, b: int) -> None:
        self.edges.setdefault((a, byte), set()).add(b)


def _parse(pattern: str):
    """Recursive-descent parse into an AST of
    ('lit', bytes) | ('class', frozenset) | ('cat', [..]) |
    ('alt', [..]) | ('star'|'plus'|'opt', node)."""
    pos = 0

    def error(msg):
        raise ValueError(f"regex error at {pos}: {msg} in {pattern!r}")

    def parse_alt():
        nonlocal pos
        branches = [parse_cat()]
        while pos < len(pattern) and pattern[pos] == "|":
            pos += 1
            branches.append(parse_cat())
        return branches[0] if len(branches) == 1 else ("alt", branches)

    def parse_cat():
        nonlocal pos
        items = []
        while pos < len(pattern) and pattern[pos] not in "|)":
            items.append(parse_repeat())
        return ("cat", items)

    def parse_repeat():
        nonlocal pos
        atom = parse_atom()
        while pos < len(pattern) and pattern[pos] in "*+?":
            op = {"*": "star", "+": "plus", "?": "opt"}[pattern[pos]]
            pos += 1
            atom = (op, atom)
        return atom

    def parse_atom():
        nonlocal pos
        c = pattern[pos]
        if c == "(":
            pos += 1
            inner = parse_alt()
            if pos >= len(pattern) or pattern[pos] != ")":
                error("unclosed group")
            pos += 1
            return inner
        if c == "[":
            pos += 1
            negate = pos < len(pattern) and pattern[pos] == "^"
            if negate:
                pos += 1
            chars = set()
            while pos < len(pattern) and pattern[pos] != "]":
                ch = pattern[pos]
                if ch == "\\":
                    pos += 1
                    ch = pattern[pos]
                if (pos + 2 < len(pattern) and pattern[pos + 1] == "-"
                        and pattern[pos + 2] != "]"):
                    lo, hi = ord(ch), ord(pattern[pos + 2])
                    chars.update(range(lo, hi + 1))
                    pos += 3
                else:
                    chars.add(ord(ch))
                    pos += 1
            if pos >= len(pattern):
                error("unclosed class")
            pos += 1
            if negate:
                chars = set(range(256)) - chars
            return ("class", frozenset(chars))
        if c == ".":
            pos += 1
            return ("class", frozenset(range(256)))
        if c == "\\":
            pos += 1
            if pos >= len(pattern):
                error("trailing backslash")
            ch = pattern[pos]
            pos += 1
            table = {"n": 10, "t": 9, "r": 13, "d": None, "s": None}
            if ch == "d":
                return ("class", frozenset(range(48, 58)))
            if ch == "s":
                return ("class", frozenset({9, 10, 13, 32}))
            return ("lit", bytes([table.get(ch) or ord(ch)]))
        if c in "*+?|)":
            error(f"unexpected {c!r}")
        pos += 1
        return ("lit", c.encode("utf-8"))

    ast = parse_alt()
    if pos != len(pattern):
        error("trailing input")
    return ast


def _build_nfa(node, nfa: _Nfa) -> Tuple[int, int]:
    """Returns (entry, exit) state pair for *node*."""
    kind = node[0]
    if kind == "lit":
        prev = nfa.new()
        entry = prev
        for b in node[1]:
            nxt = nfa.new()
            nfa.add(prev, b, nxt)
            prev = nxt
        return entry, prev
    if kind == "class":
        a, b = nfa.new(), nfa.new()
        for byte in node[1]:
            nfa.add(a, byte, b)
        return a, b
    if kind == "cat":
        if not node[1]:
            s = nfa.new()
            return s, s
        entry, cur = _build_nfa(node[1][0], nfa)
        for item in node[1][1:]:
            a, b = _build_nfa(item, nfa)
            nfa.add_eps(cur, a)
            cur = b
        return entry, cur
    if kind == "alt":
        entry, exit_ = nfa.new(), nfa.new()
        for br in node[1]:
            a, b = _build_nfa(br, nfa)
            nfa.add_eps(entry, a)
            nfa.add_eps(b, exit_)
        return entry, exit_
    if kind in ("star", "plus", "opt"):
        a, b = _build_nfa(node[1], nfa)
        entry, exit_ = nfa.new(), nfa.new()
        nfa.add_eps(entry, a)
        nfa.add_eps(b, exit_)
        if kind in ("star", "opt"):
            nfa.add_eps(entry, exit_)
        if kind in ("star", "plus"):
            nfa.add_eps(b, a)
        return entry, exit_
    raise AssertionError(kind)


def regex_to_dfa(pattern: str) -> CharDfa:
    """Compile the regex subset into a byte-alphabet DFA (full-match
    semantics: accepting states mean the WHOLE input so far matches)."""
    nfa = _Nfa()
    entry, exit_ = _build_nfa(_parse(pattern), nfa)

    def closure(states: FrozenSet[int]) -> FrozenSet[int]:
        out = set(states)
        work = list(states)
        while work:
            s = work.pop()
            for t in nfa.eps.get(s, ()):
                if t not in out:
                    out.add(t)
                    work.append(t)
        return frozenset(out)

    start = closure(frozenset({entry}))
    ids: Dict[FrozenSet[int], int] = {start: 0}
    rows: List[np.ndarray] = []
    accepting: List[bool] = []
    work = [start]
    while work:
        cur = work.pop()
        i = ids[cur]
        while len(rows) <= i:
            rows.append(np.full(256, _REJECT, np.int32))
            accepting.append(False)
        accepting[i] = exit_ in cur
        row = rows[i]
        for byte in range(256):
            tgt = set()
            for s in cur:
                tgt.update(nfa.edges.get((s, byte), ()))
            if not tgt:
                continue
            nxt = closure(frozenset(tgt))
            if nxt not in ids:
                ids[nxt] = len(ids)
                work.append(nxt)
            row[byte] = ids[nxt]
    table = np.stack([rows[i] for i in range(len(ids))])
    acc = np.asarray([accepting[i] for i in range(len(ids))], bool)
    return CharDfa(table=table, accepting=acc)


# -- char DFA -> token DFA ---------------------------------------------------

@dataclass(frozen=True)
class TokenDfa:
    """Token-level automaton for an engine: ``table [N, V]`` int32
    next-state (-1 = token rejected in that state), ``mask [N, V]``
    float32 additive logit mask (0 allowed / -1e9 rejected), start
    state 0.  ``eos`` is allowed exactly in accepting states."""

    table: np.ndarray
    mask: np.ndarray
    start: int = 0


def token_dfa(dfa: CharDfa, token_bytes: List[bytes],
              eos_id: int) -> TokenDfa:
    """Close the char DFA over the vocabulary: token t from state s
    lands where walking t's bytes lands (or rejects).  Tokens mapping
    to b"" (special ids) are rejected everywhere except ``eos``, which
    is allowed exactly in accepting states."""
    n_states = len(dfa.table)
    V = len(token_bytes)
    table = np.full((n_states, V), _REJECT, np.int32)
    for t, bs in enumerate(token_bytes):
        if t == eos_id or not bs:
            continue
        for s in range(n_states):
            cur = s
            for b in bs:
                cur = int(dfa.table[cur, b])
                if cur == _REJECT:
                    break
            if cur != _REJECT:
                table[s, t] = cur
    mask = np.where(table >= 0, 0.0, -1e9).astype(np.float32)
    if 0 <= eos_id < V:
        for s in np.flatnonzero(dfa.accepting):
            mask[s, eos_id] = 0.0
            table[s, eos_id] = s  # self-loop; generation retires at eos
    # dead-end guard: a reachable state where nothing (incl. eos) is
    # allowed would force garbage tokens through the mask
    dead = (mask <= -1e9 / 2).all(axis=1)
    if dead.any():
        raise ValueError(
            f"grammar has dead-end states {np.flatnonzero(dead).tolist()}"
            " (no token or eos allowed); widen the pattern or the "
            "vocabulary")
    return TokenDfa(table=table, mask=mask, start=0)
