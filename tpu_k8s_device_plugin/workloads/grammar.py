"""Grammar-constrained decoding support (vLLM's guided decoding).

The TPU-shaped design: a grammar is compiled AHEAD of decoding into a
token-level DFA — ``table[state, token] -> next state`` (-1 rejects;
the additive logit mask is DERIVED from reject entries, never stored)
— and the DFA state rides the decode scan's carry.  Constrained
generation then costs one ``[S, V]`` row gather per step inside the
SAME compiled ``lax.scan`` as unconstrained decoding: no per-token
host round-trip, no Python in the loop (the xgrammar/outlines
token-bitmask idea, expressed as jit-friendly arrays).  Runs the DFA
*forces* (single legal continuation) commit through the engine's
structural jump-ahead (``ServingEngine.jump_round``) in one
multi-token extend.

Pipeline:

1. ``regex_to_dfa(pattern)`` — a small regex subset (literals, ``|``,
   ``*`` ``+`` ``?``, ``(...)``, ``[a-z]`` classes, ``.``) compiled
   via Thompson NFA + subset construction over the byte alphabet.
   ``json_value_regex`` / ``json_object_regex`` / ``schema_to_regex``
   lower JSON constraints (RFC 8259-strict; compact output for
   schemas) into the subset; ``token_bytes_of`` maps a tokenizer's
   vocabulary to byte strings.
2. ``token_dfa(dfa, token_bytes, eos_id)`` — the char DFA is closed
   over the vocabulary (vectorized [N, V] walks), trimmed to
   co-accessible states, and dead-end-checked; ``eos`` is allowed
   exactly in ACCEPTING states (structural completion gates the
   stop).

Engines hold a REGISTRY of these (``ServingEngine(grammar=...)`` or
``register_grammar()``); requests opt in with ``admit(grammar=gid)``
(``True`` = grammar 0).  The HTTP front door (server.py) lowers
per-request ``guided_regex`` / ``guided_json`` / ``guided_choice`` /
OpenAI ``response_format`` through this module.
"""

from __future__ import annotations

import re as _re
from dataclasses import dataclass
from typing import Dict, FrozenSet, List, Optional, Tuple

import numpy as np

_REJECT = -1


# -- char-level regex -> DFA -------------------------------------------------

@dataclass(frozen=True)
class CharDfa:
    """Byte-alphabet DFA: table [n_states, 256] int32 (-1 = reject),
    state 0 initial, ``accepting`` a bool per state."""

    table: np.ndarray
    accepting: np.ndarray


class _Nfa:
    """Thompson construction: states are ints, transitions are
    (state, byte) -> set[state], plus epsilon edges."""

    def __init__(self):
        self.eps: Dict[int, set] = {}
        self.edges: Dict[Tuple[int, int], set] = {}
        self.n = 0

    def new(self) -> int:
        s = self.n
        self.n += 1
        return s

    def add_eps(self, a: int, b: int) -> None:
        self.eps.setdefault(a, set()).add(b)

    def add(self, a: int, byte: int, b: int) -> None:
        self.edges.setdefault((a, byte), set()).add(b)


def _parse(pattern: str):
    """Recursive-descent parse into an AST of
    ('lit', bytes) | ('class', frozenset) | ('cat', [..]) |
    ('alt', [..]) | ('star'|'plus'|'opt', node)."""
    pos = 0

    def error(msg):
        raise ValueError(f"regex error at {pos}: {msg} in {pattern!r}")

    def parse_alt():
        nonlocal pos
        branches = [parse_cat()]
        while pos < len(pattern) and pattern[pos] == "|":
            pos += 1
            branches.append(parse_cat())
        return branches[0] if len(branches) == 1 else ("alt", branches)

    def parse_cat():
        nonlocal pos
        items = []
        while pos < len(pattern) and pattern[pos] not in "|)":
            items.append(parse_repeat())
        return ("cat", items)

    def parse_repeat():
        nonlocal pos
        atom = parse_atom()
        while pos < len(pattern) and pattern[pos] in "*+?":
            op = {"*": "star", "+": "plus", "?": "opt"}[pattern[pos]]
            pos += 1
            atom = (op, atom)
        return atom

    def parse_atom():
        nonlocal pos
        c = pattern[pos]
        if c == "(":
            pos += 1
            inner = parse_alt()
            if pos >= len(pattern) or pattern[pos] != ")":
                error("unclosed group")
            pos += 1
            return inner
        if c == "[":
            pos += 1
            negate = pos < len(pattern) and pattern[pos] == "^"
            if negate:
                pos += 1
            chars = set()
            while pos < len(pattern) and pattern[pos] != "]":
                ch = pattern[pos]
                if ch == "\\":
                    pos += 1
                    ch = pattern[pos]
                if (pos + 2 < len(pattern) and pattern[pos + 1] == "-"
                        and pattern[pos + 2] != "]"):
                    lo, hi = ord(ch), ord(pattern[pos + 2])
                    chars.update(range(lo, hi + 1))
                    pos += 3
                else:
                    chars.add(ord(ch))
                    pos += 1
            if pos >= len(pattern):
                error("unclosed class")
            pos += 1
            if negate:
                chars = set(range(256)) - chars
            return ("class", frozenset(chars))
        if c == ".":
            pos += 1
            return ("class", frozenset(range(256)))
        if c == "\\":
            pos += 1
            if pos >= len(pattern):
                error("trailing backslash")
            ch = pattern[pos]
            pos += 1
            table = {"n": 10, "t": 9, "r": 13, "d": None, "s": None}
            if ch == "d":
                return ("class", frozenset(range(48, 58)))
            if ch == "s":
                return ("class", frozenset({9, 10, 13, 32}))
            return ("lit", bytes([table.get(ch) or ord(ch)]))
        if c in "*+?|)":
            error(f"unexpected {c!r}")
        pos += 1
        return ("lit", c.encode("utf-8"))

    ast = parse_alt()
    if pos != len(pattern):
        error("trailing input")
    return ast


def _build_nfa(node, nfa: _Nfa) -> Tuple[int, int]:
    """Returns (entry, exit) state pair for *node*."""
    kind = node[0]
    if kind == "lit":
        prev = nfa.new()
        entry = prev
        for b in node[1]:
            nxt = nfa.new()
            nfa.add(prev, b, nxt)
            prev = nxt
        return entry, prev
    if kind == "class":
        a, b = nfa.new(), nfa.new()
        for byte in node[1]:
            nfa.add(a, byte, b)
        return a, b
    if kind == "cat":
        if not node[1]:
            s = nfa.new()
            return s, s
        entry, cur = _build_nfa(node[1][0], nfa)
        for item in node[1][1:]:
            a, b = _build_nfa(item, nfa)
            nfa.add_eps(cur, a)
            cur = b
        return entry, cur
    if kind == "alt":
        entry, exit_ = nfa.new(), nfa.new()
        for br in node[1]:
            a, b = _build_nfa(br, nfa)
            nfa.add_eps(entry, a)
            nfa.add_eps(b, exit_)
        return entry, exit_
    if kind in ("star", "plus", "opt"):
        a, b = _build_nfa(node[1], nfa)
        entry, exit_ = nfa.new(), nfa.new()
        nfa.add_eps(entry, a)
        nfa.add_eps(b, exit_)
        if kind in ("star", "opt"):
            nfa.add_eps(entry, exit_)
        if kind in ("star", "plus"):
            nfa.add_eps(b, a)
        return entry, exit_
    raise AssertionError(kind)


def regex_to_dfa(pattern: str) -> CharDfa:
    """Compile the regex subset into a byte-alphabet DFA (full-match
    semantics: accepting states mean the WHOLE input so far matches)."""
    nfa = _Nfa()
    entry, exit_ = _build_nfa(_parse(pattern), nfa)

    def closure(states: FrozenSet[int]) -> FrozenSet[int]:
        out = set(states)
        work = list(states)
        while work:
            s = work.pop()
            for t in nfa.eps.get(s, ()):
                if t not in out:
                    out.add(t)
                    work.append(t)
        return frozenset(out)

    start = closure(frozenset({entry}))
    ids: Dict[FrozenSet[int], int] = {start: 0}
    rows: List[np.ndarray] = []
    accepting: List[bool] = []
    work = [start]
    while work:
        cur = work.pop()
        i = ids[cur]
        while len(rows) <= i:
            rows.append(np.full(256, _REJECT, np.int32))
            accepting.append(False)
        accepting[i] = exit_ in cur
        row = rows[i]
        for byte in range(256):
            tgt = set()
            for s in cur:
                tgt.update(nfa.edges.get((s, byte), ()))
            if not tgt:
                continue
            nxt = closure(frozenset(tgt))
            if nxt not in ids:
                ids[nxt] = len(ids)
                work.append(nxt)
            row[byte] = ids[nxt]
    table = np.stack([rows[i] for i in range(len(ids))])
    acc = np.asarray([accepting[i] for i in range(len(ids))], bool)
    return CharDfa(table=table, accepting=acc)


# -- char DFA -> token DFA ---------------------------------------------------

@dataclass(frozen=True)
class TokenDfa:
    """Token-level automaton for an engine: ``table [N, V]`` int32
    next-state (-1 = token rejected in that state), start state 0.
    ``eos`` is allowed exactly in accepting states (a self-loop, so
    its entry is >= 0).  The table is the ONLY stored array — the
    additive logit mask is fully derived from reject entries, and
    storing it would double the footprint (~1.4 GB for a JSON grammar
    at a 128k vocab) per cached pattern."""

    table: np.ndarray
    start: int = 0

    @property
    def mask(self) -> np.ndarray:
        """[N, V] float32 additive logit mask (0 allowed / -1e9
        rejected), derived on demand — diagnostics and tests only;
        the engine derives the same mask in-step from the table."""
        return np.where(self.table >= 0, 0.0, -1e9).astype(np.float32)


def token_dfa(dfa: CharDfa, token_bytes: List[bytes],
              eos_id: int) -> TokenDfa:
    """Close the char DFA over the vocabulary: token t from state s
    lands where walking t's bytes lands (or rejects).  Tokens mapping
    to b"" (special ids) are rejected everywhere except ``eos``, which
    is allowed exactly in accepting states."""
    n_states = len(dfa.table)
    V = len(token_bytes)
    # vectorized closure: walk EVERY (state, token) pair one byte
    # position at a time with [N, V] gathers — max-token-length numpy
    # passes instead of an O(N * V * len) Python loop (decisive for
    # real 100k+ vocabs against a few-thousand-state JSON grammar)
    max_b = max((len(bs) for bs in token_bytes), default=0)
    bytes_mat = np.full((V, max(max_b, 1)), -1, np.int64)
    for t, bs in enumerate(token_bytes):
        if t == eos_id or not bs:
            continue  # specials/eos reject everywhere (masked below)
        bytes_mat[t, :len(bs)] = list(bs)
    cur = np.tile(np.arange(n_states, dtype=np.int32)[:, None], (1, V))
    for p in range(max_b):
        bp = bytes_mat[:, p]
        has = (bp >= 0)[None, :]
        step = dfa.table[np.maximum(cur, 0),
                         np.maximum(bp, 0)[None, :]]
        cur = np.where(has, np.where(cur >= 0, step, _REJECT), cur)
    cur[:, bytes_mat[:, 0] < 0] = _REJECT
    table = np.ascontiguousarray(cur.astype(np.int32))
    if 0 <= eos_id < V:
        for s in np.flatnonzero(dfa.accepting):
            table[s, eos_id] = s  # self-loop; generation retires at eos
    # trim to co-accessible states: a token step into a state from
    # which NO accepting state is token-reachable would trap the
    # generation (decoding forever with eos masked, or hitting a
    # dead end later) — reject those transitions up front, exactly
    # like outlines' FSM reduction
    # reverse-adjacency BFS (one O(N*V) edge collection + O(edges)
    # walk) instead of a forward fixed point, whose iteration count is
    # the DFA diameter — quadratic for chain grammars like long
    # literal enums
    rev: List[List[int]] = [[] for _ in range(n_states)]
    for s in range(n_states):
        row = table[s]
        for t in np.unique(row[row >= 0]):
            rev[int(t)].append(s)
    live = dfa.accepting.copy()
    work = [int(s) for s in np.flatnonzero(live)]
    while work:
        t = work.pop()
        for s in rev[t]:
            if not live[s]:
                live[s] = True
                work.append(s)
    trap = (table >= 0) & ~live[np.maximum(table, 0)]
    table[trap] = _REJECT
    # dead-end guard over states actually REACHABLE from the start
    # (unreachable char-DFA states legitimately have no token cover):
    # a reachable state where nothing (incl. eos) is allowed would
    # force garbage tokens through the mask
    reach = np.zeros(n_states, bool)
    reach[0] = True
    work = [0]
    while work:
        s = work.pop()
        row = table[s]
        for t in np.unique(row[row >= 0]):
            if not reach[t]:
                reach[t] = True
                work.append(int(t))
    dead = (table < 0).all(axis=1) & reach
    if dead.any():
        raise ValueError(
            f"grammar has dead-end states {np.flatnonzero(dead).tolist()}"
            " (no token or eos allowed); widen the pattern or the "
            "vocabulary")
    return TokenDfa(table=table, start=0)


# -- served-grammar helpers --------------------------------------------------
#
# The front door (server.py) compiles per-request constraints through
# these: a `guided_regex` pattern is used verbatim; `guided_json` /
# OpenAI `response_format` lowers to a bounded-depth JSON regex (a
# regular-language approximation of JSON — the standard trick for
# DFA-based guided decoding, since true JSON nesting is not regular).

_JSON_WS = r"\s*"
# RFC 8259-strict lowering (under-constraining would let "guided JSON"
# emit unparseable output): string chars exclude raw control bytes,
# escapes are the legal set only, integers forbid leading zeros
_JSON_CTRL = "".join(chr(c) for c in range(0x20))
_JSON_HEX = "[0-9a-fA-F]"
_JSON_STRING = ('"([^"\\\\' + _JSON_CTRL + ']|\\\\(["\\\\/bfnrt]'
                f"|u{_JSON_HEX}{_JSON_HEX}{_JSON_HEX}{_JSON_HEX}))*\"")
_JSON_NUMBER = r"-?(0|[1-9]\d*)(\.\d+)?([eE][+-]?\d+)?"
_JSON_SCALAR = (f"({_JSON_STRING}|{_JSON_NUMBER}"
                "|true|false|null)")


def json_value_regex(depth: int = 3) -> str:
    """Regex for a JSON value with nesting bounded at *depth* (0 =
    scalars only).  OpenAI ``response_format={"type": "json_object"}``
    maps here: the model may emit any JSON object up to the depth
    bound."""
    if depth < 0:
        raise ValueError("depth must be >= 0")
    val = _JSON_SCALAR
    for _ in range(depth):
        pair = f"{_JSON_STRING}{_JSON_WS}:{_JSON_WS}{val}"
        obj = (f"\\{{{_JSON_WS}({pair}({_JSON_WS},{_JSON_WS}{pair})*)?"
               f"{_JSON_WS}\\}}")
        arr = (f"\\[{_JSON_WS}({val}({_JSON_WS},{_JSON_WS}{val})*)?"
               f"{_JSON_WS}\\]")
        val = f"({_JSON_SCALAR}|{obj}|{arr})"
    return val


def json_object_regex(depth: int = 3) -> str:
    """Regex for a JSON OBJECT (not a bare scalar/array) with member
    values nested up to ``depth - 1`` — the OpenAI
    ``response_format={"type": "json_object"}`` contract, which
    promises an object, not any JSON value."""
    if depth < 1:
        raise ValueError("depth must be >= 1")
    val = json_value_regex(depth - 1)
    pair = f"{_JSON_STRING}{_JSON_WS}:{_JSON_WS}{val}"
    return (f"\\{{{_JSON_WS}({pair}({_JSON_WS},{_JSON_WS}{pair})*)?"
            f"{_JSON_WS}\\}}")


def _regex_escape(text: str) -> str:
    """Escape *text* for the module's regex subset (literal match)."""
    return "".join(
        "\\" + c if c in "\\()[]{}*+?|." else c for c in text)


def schema_to_regex(schema: dict, depth: int = 3,
                    ws: str = "") -> str:
    """Lower a JSON-schema SUBSET to a regex: ``type`` of string /
    integer / number / boolean / null, ``enum`` of scalars, ``array``
    with ``items``, and ``object`` with ``properties`` (all properties
    required, emitted in declaration order — the shape constrained
    decoding guarantees, mirroring vLLM's guided_json ordering).
    Unsupported keywords raise ValueError so callers 400 instead of
    silently under-constraining.

    *ws* is the separator-whitespace regex fragment.  The default is
    COMPACT output (no whitespace — OpenAI structured-output style):
    compactness makes the schema's literal skeleton (braces, keys,
    colons, commas) single-choice at every DFA state, which is exactly
    what the engine's structural jump-ahead (``jump_round``) commits
    in one extend; pass ``ws=r"\\s*"`` for lenient spacing."""
    if not isinstance(schema, dict):
        raise ValueError("schema must be a JSON object")
    # reject keywords whose absence from the lowering could make the
    # OUTPUT violate the schema (minimum, pattern, maxLength, ...):
    # silent under-constraining is exactly what the 400 path exists to
    # prevent.  Keys that only ever OVER-constrain relative to our
    # all-properties/declaration-order contract (required,
    # additionalProperties) or are annotations are safe to ignore.
    unsafe = set(schema) - {
        "type", "enum", "items", "properties", "required",
        "additionalProperties", "title", "description", "default",
        "$schema", "examples",
    }
    if unsafe:
        raise ValueError(
            f"unsupported schema keywords {sorted(unsafe)}: the "
            "served subset cannot enforce them, and ignoring them "
            "would silently under-constrain the output")
    if "enum" in schema:
        import json as _json

        opts = []
        for v in schema["enum"]:
            if v is None or isinstance(v, (bool, str, int, float)):
                # JSON-encode FIRST (quotes/backslashes in strings
                # must come out as \" / \\ escape sequences, or the
                # DFA would force unparseable output), then escape
                # the encoding for the regex subset
                opts.append(_regex_escape(_json.dumps(v)))
            else:
                raise ValueError(f"unsupported enum value {v!r}")
        return "(" + "|".join(opts) + ")"
    t = schema.get("type")
    if t == "string":
        return _JSON_STRING
    if t == "integer":
        return r"-?(0|[1-9]\d*)"  # RFC 8259: no leading zeros
    if t == "number":
        return _JSON_NUMBER
    if t == "boolean":
        return "(true|false)"
    if t == "null":
        return "null"
    if t == "array":
        item = (schema_to_regex(schema["items"], depth, ws)
                if "items" in schema else json_value_regex(depth))
        return (f"\\[{ws}({item}({ws},{ws}{item})*)?"
                f"{ws}\\]")
    if t == "object":
        props = schema.get("properties")
        if not props:
            if schema.get("additionalProperties") is False:
                # no properties + additionalProperties false admits
                # ONLY the empty object; falling through to
                # json_object_regex would permit arbitrary members —
                # exactly the silent under-constraining the unsafe-
                # keyword 400 path exists to prevent (ADVICE r5)
                return f"\\{{{ws}\\}}"
            # a schemaless object is still an OBJECT, never a scalar
            return json_object_regex(max(depth, 1))
        import json as _json

        pairs = []
        for name, sub in props.items():
            key = _regex_escape(_json.dumps(name))
            pairs.append(
                f"{key}{ws}:{ws}"
                + schema_to_regex(sub, depth, ws))
        body = f"{ws},{ws}".join(pairs)
        return f"\\{{{ws}{body}{ws}\\}}"
    raise ValueError(
        f"unsupported schema {schema!r}: the served subset covers "
        "type string/integer/number/boolean/null/array/object and "
        "scalar enum")


def _gpt2_byte_decoder() -> Dict[str, int]:
    """The GPT-2 byte-level BPE printable-unicode <-> byte table
    (public algorithm from the GPT-2 tokenizer; every byte-level
    tokenizer since reuses it)."""
    bs = (list(range(33, 127)) + list(range(161, 173))
          + list(range(174, 256)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {chr(c): b for b, c in zip(bs, cs)}


def token_bytes_of(tokenizer, vocab_size: Optional[int] = None
                   ) -> List[bytes]:
    """Best-effort per-token byte strings for *tokenizer* (the input
    ``token_dfa`` needs): handles sentencepiece ``▁``-space and
    ``<0xHH>`` byte-fallback tokens, GPT-2-style byte-level BPE
    surface forms, and plain vocab entries; special tokens (and ids
    past the tokenizer's size, for padded model vocabs) map to ``b""``
    so the DFA rejects them everywhere.  This is the same
    token-to-bytes dance outlines/xgrammar do for vLLM's guided
    decoding."""
    try:
        size = len(tokenizer)
    except TypeError:
        size = None  # minimal tokenizers (test fakes) are unsized
    V = vocab_size if vocab_size is not None else size
    if V is None:
        raise ValueError(
            "tokenizer has no __len__; pass vocab_size explicitly")
    specials = set(getattr(tokenizer, "all_special_ids", None) or ())
    convert = getattr(tokenizer, "convert_ids_to_tokens", None)
    byte_dec = _gpt2_byte_decoder()
    out: List[bytes] = []
    for i in range(V):
        if i in specials or (size is not None and i >= size):
            out.append(b"")
            continue
        s = convert(i) if convert is not None else None
        if not isinstance(s, str):
            out.append(tokenizer.decode([i]).encode("utf-8"))
            continue
        m = _re.fullmatch(r"<0x([0-9A-Fa-f]{2})>", s)
        if m:
            out.append(bytes([int(m.group(1), 16)]))
        elif "▁" in s:  # sentencepiece's ▁ word-boundary space
            out.append(s.replace("▁", " ").encode("utf-8"))
        elif all(c in byte_dec for c in s):
            out.append(bytes(byte_dec[c] for c in s))
        else:
            out.append(s.encode("utf-8"))
    return out
