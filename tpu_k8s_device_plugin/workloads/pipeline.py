"""Pipeline parallelism: GPipe-style microbatch pipeline over a mesh axis.

The last member of the workload layer's parallelism set (SURVEY.md §2.4 —
the reference delegates DP/TP/PP/SP/EP wholesale to its workload images;
this build ships them natively).  TPU-first shape:

* **One SPMD program, no per-stage processes.**  The pipeline is a
  ``shard_map`` over a ``pipe`` mesh axis: layer parameters are stacked
  on a leading layer axis and sharded ``P("pipe", …)``, so stage *s*
  physically holds only its ``L/S`` layers; activations hop stages with
  ``lax.ppermute`` — a neighbour ICI transfer, exactly like the ring
  attention's K/V rotation.
* **Static schedule.**  The classic GPipe fill/steady/drain schedule is
  a single ``lax.scan`` over ``n_micro + n_stages - 1`` ticks; every
  tick does the same work on every rank (inject → local layers →
  record → shift), so XLA sees one compiled body with no data-dependent
  control flow.
* **Backward for free.**  The schedule is written forward-only;
  ``jax.grad`` transposes it — ``ppermute`` reverses direction, the scan
  runs backward — into the mirror-image backward pipeline, no hand-rolled
  schedule needed.

Composition: the batch dimension of the microbatches can stay sharded on
other mesh axes (``data``), giving DP×PP from one jit; the layer body is
an arbitrary ``layer_fn`` so TP/MoE layers nest inside stages.
"""

from __future__ import annotations

import functools
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .ring_attention import _shard_map

# layer_fn: (single layer's params pytree, activations) -> activations
LayerFn = Callable

_DEFAULT_BATCH_AXES = object()  # sentinel: only the true default degrades


def stack_layer_params(per_layer_params: Sequence) -> object:
    """Stack per-layer parameter pytrees along a new leading layer axis
    (the axis the ``pipe`` mesh dimension shards)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_layer_params
    )


def _apply_local_layers(layer_fn: LayerFn, params_local, x):
    """Run the stage's local layer stack sequentially (scan over the
    leading layer axis of every params leaf)."""
    def body(h, layer_params):
        return layer_fn(layer_params, h), None

    out, _ = lax.scan(body, x, params_local)
    return out


def _pipeline_shard(
    params_local,
    inputs,  # [n_micro, mb, ...] local block (batch dims may be sharded)
    *,
    layer_fn: LayerFn,
    axis_name: str,
    n_stages: int,
):
    """Per-rank GPipe schedule: n_micro + n_stages - 1 ticks of
    inject → local layers → record → ppermute."""
    n_micro = inputs.shape[0]  # static at trace time — no way to drift
    stage = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    state0 = jnp.zeros(inputs.shape[1:], inputs.dtype)
    outputs0 = jnp.zeros_like(inputs)

    def tick(carry, t):
        state, outputs = carry
        # stage 0 ingests microbatch t (clamped re-reads past the end are
        # processed but never recorded — drain-phase bubbles)
        inject = lax.dynamic_index_in_dim(
            inputs, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False
        )
        state = jnp.where(stage == 0, inject, state)
        state = _apply_local_layers(layer_fn, params_local, state)
        # the last stage finishes microbatch t-(n_stages-1) at tick t
        out_idx = t - (n_stages - 1)
        recorded = lax.dynamic_update_index_in_dim(
            outputs, state, jnp.clip(out_idx, 0, n_micro - 1), 0
        )
        write = jnp.logical_and(stage == n_stages - 1, out_idx >= 0)
        outputs = jnp.where(write, recorded, outputs)
        state = lax.ppermute(state, axis_name, perm)
        return (state, outputs), None

    (_, outputs), _ = lax.scan(
        tick, (state0, outputs0), jnp.arange(n_micro + n_stages - 1)
    )
    # only the last stage holds real outputs; broadcast them to every
    # rank (psum of a one-hot-by-stage tensor), which also gives the
    # backward pass its entry point on the last stage
    return lax.psum(
        jnp.where(stage == n_stages - 1, outputs, jnp.zeros_like(outputs)),
        axis_name,
    )


def make_pipeline(
    mesh: Mesh,
    layer_fn: LayerFn,
    stacked_params,
    pipe_axis: str = "pipe",
    batch_axes=_DEFAULT_BATCH_AXES,
):
    """Build a pipelined forward: ``apply(stacked_params, microbatches)``.

    *stacked_params* — pytree with a leading layer axis on every leaf
    (see :func:`stack_layer_params`); the layer count must divide evenly
    by ``mesh.shape[pipe_axis]``.  *microbatches* — ``[n_micro, mb, …]``
    (the microbatch count is read off the input's leading dim at trace
    time); dimension 1 may additionally be sharded on *batch_axes*
    (DP×PP).  The default ``"data"`` degrades to replication on meshes
    without a data axis; an explicitly passed axis must exist.

    Returns ``(apply, params_sharded, in_sharding)`` where ``apply`` is
    jit-compiled with the stage sharding baked in.
    """
    n_stages = mesh.shape[pipe_axis]
    n_layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_layers % n_stages:
        raise ValueError(
            f"{n_layers} layers not divisible by {n_stages} pipeline stages"
        )

    param_specs = jax.tree_util.tree_map(
        lambda leaf: P(pipe_axis, *([None] * (leaf.ndim - 1))),
        stacked_params,
    )
    if batch_axes is _DEFAULT_BATCH_AXES:
        batch_axes = "data" if "data" in mesh.axis_names else None
    elif batch_axes is not None and batch_axes not in mesh.axis_names:
        raise ValueError(
            f"batch_axes {batch_axes!r} is not a mesh axis "
            f"{tuple(mesh.axis_names)}"
        )
    in_spec = P(None, batch_axes)
    body = _shard_map(
        functools.partial(
            _pipeline_shard, layer_fn=layer_fn, axis_name=pipe_axis,
            n_stages=n_stages,
        ),
        mesh,
        in_specs=(param_specs, in_spec),
        out_specs=in_spec,
    )
    params_sharded = jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        stacked_params, param_specs,
    )
    return jax.jit(body), params_sharded, NamedSharding(mesh, in_spec)
