"""Autoregressive inference with a KV cache: the serving-side workload.

The reference proves multi-chip serving with an opaque vLLM image
(/root/reference/example/vllm-serve/deployment.yaml:19-38); this module
is the native counterpart — a decode engine for the training stack's
``TransformerLM`` shaped for how TPUs serve:

* **static shapes end-to-end**: the KV cache is a fixed ``[B, T_max, H,
  Dh]`` buffer per layer written with ``lax.dynamic_update_slice``; one
  compiled prefill and one compiled decode step serve any request
  length, so XLA never recompiles as sequences grow.
* **prefill ≠ decode**: prefill is the MXU-bound pass (whole prompt,
  causal attention — the same math the train step runs) and fills the
  cache in one shot; decode is the HBM-bound matvec pass (one token
  against the cache) driven by ``lax.scan``, so the whole generation
  loop is a single jit with no host round-trips per token.
* **same parameters, same math**: the decode graph mirrors
  ``transformer.TransformerLM``'s module tree name-for-name, so trained
  params drop in unchanged; the equality is oracle-tested (prefill
  logits vs the training model, cached greedy decode vs the naive
  recompute-everything loop) in tests/test_inference.py.
* **tensor parallelism by sharding**: params shard with the training
  side's ``lm_tree_shardings`` (Megatron-style splits on the mesh's
  ``model`` axis); the cache shards on the head axis alongside them.
  No collectives are written here — XLA places them (SURVEY.md §5
  "distributed communication backend").

MoE configs serve too: ``CachedBlock`` swaps its MLP for the training
stack's ``MoEFFN`` when ``n_experts > 0`` (same expert stacks, same
router).  One semantic note — every extend (T=1 decode, chunked
prefill, speculative verify) routes with per-expert capacity pinned to
T, which is always dropless, so all extend shapes produce identical
tokens (dropless serving, the standard MoE inference behavior);
training configs with tight capacity factors can drop tokens the
serving path keeps.  Use a dropless capacity factor
(``cf >= n_experts / k``) when exact training/serving routing parity
matters (the oracle tests do).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn
from jax import lax

from .transformer import (
    COMPUTE_DTYPE,
    _validate_attn_ffn,
    apply_rope,
    local_causal_attention,
    repeat_kv,
    split_qkv_heads,
)

# prompts at or above this length prefill through the Pallas flash
# kernel (no [T, T] score materialization); shorter ones use the einsum
_FLASH_PREFILL_MIN_T = 512


class QuantDense(nn.Dense):
    """Weight-only int8 Dense: the kernel is stored as int8 with a
    per-output-channel f32 scale and dequantized inside the matmul
    (XLA fuses the convert+scale into the dot's operand load, so HBM
    reads are int8 — the point: decode is weight-bandwidth-bound, and
    int8 halves the bytes per token vs bf16).

    Subclasses ``nn.Dense`` so construction sites stay identical; only
    the parameter layout and the matmul change.  Quantize a trained
    bf16/f32 tree with :func:`quantize_lm_params`.
    """

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        if self.use_bias:
            raise NotImplementedError(
                "QuantDense is weight-only (no bias) - the LM's "
                "projections are all use_bias=False"
            )
        kernel_q = self.param(
            "kernel_int8",
            lambda rng, shape: jnp.zeros(shape, jnp.int8),
            (x.shape[-1], self.features),
        )
        scale = self.param(
            "scale",
            lambda rng, shape: jnp.ones(shape, jnp.float32),
            (self.features,),
        )
        # scale on the dot OUTPUT, not the kernel: exact f32 per-channel
        # scaling (no bf16 rounding of the scales), F multiplies instead
        # of D·F, and HBM still reads int8
        out = jnp.dot(x.astype(self.dtype), kernel_q.astype(self.dtype))
        return (out * scale).astype(self.dtype)


# int4 scale-group size along the INPUT dim: 4-bit needs finer scale
# granularity than a whole column (the max over 4096 weights is ~1.5x
# the max over 64, and the quantization error scales with it) — the
# standard GPTQ/AWQ-style recipe
_INT4_GROUP = 64


class Quant4Dense(nn.Dense):
    """Weight-only int4 Dense: two 4-bit values per stored int8 byte
    (adjacent output channels share a byte — low nibble = even channel,
    high nibble = odd), GROUP-WISE f32 scales (one per
    ``_INT4_GROUP``-sized input-dim group per output channel, symmetric
    range [-7, 7]).  Halves the weight bytes per token AGAIN vs int8 —
    decode is weight-bandwidth-bound, so this is the next rung of the
    same ladder (and what fits Llama-3-8B kernels in ~4 GB).  Because
    the scales vary along the contraction dim they cannot move to the
    dot output; the matmul runs as a per-group batched einsum with the
    group scales applied to the per-group partial sums — weights still
    stream as int8 bytes PROVIDED XLA fuses the nibble unpack into the
    einsum's operand load instead of materializing the [D, F] bf16
    kernel (numerics are oracle-tested either way; the bandwidth win
    is a fusion property that must be confirmed from the measured
    bytes/token on real TPU — see BASELINE.md's int4 measurement
    backlog)."""

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        if self.use_bias:
            raise NotImplementedError(
                "Quant4Dense is weight-only (no bias)")
        if self.features % 2:
            raise ValueError(
                f"int4 packing needs an even output dim, got "
                f"{self.features}")
        din = x.shape[-1]
        g = _int4_group(din)
        packed = self.param(
            "kernel_int4",
            lambda rng, shape: jnp.zeros(shape, jnp.int8),
            (din, self.features // 2),
        )
        scale = self.param(
            "scale",
            lambda rng, shape: jnp.ones(shape, jnp.float32),
            (din // g, self.features),
        )
        w4 = unpack_int4(packed).astype(self.dtype)  # [D, F]
        n_g = din // g
        lead = x.shape[:-1]
        xg = x.astype(self.dtype).reshape(lead + (n_g, g))
        wg = w4.reshape(n_g, g, self.features)
        partial = jnp.einsum("...gd,gdf->...gf", xg, wg)
        out = jnp.einsum(
            "...gf,gf->...f", partial.astype(jnp.float32), scale
        )
        return out.astype(self.dtype)


def _dense_cls(quantized):
    """False -> nn.Dense, truthy -> int8, "int4" -> packed 4-bit."""
    if quantized == "int4":
        return Quant4Dense
    return QuantDense if quantized else nn.Dense


def _int4_group(din: int) -> int:
    """Largest divisor of the input dim at or below _INT4_GROUP."""
    g = min(_INT4_GROUP, din)
    while din % g:
        g -= 1
    return g


def pack_int4(w4: jax.Array) -> jax.Array:
    """[D, F] int8 values in [-8, 7] → [D, F//2] bytes: low nibble =
    even column, high nibble = odd column."""
    lo = w4[:, 0::2] & 0x0F
    hi = w4[:, 1::2] & 0x0F
    return (lo | (hi << 4)).astype(jnp.int8)


def unpack_int4(packed: jax.Array) -> jax.Array:
    """[D, P] bytes → [D, 2P] sign-extended int8 values (inverse of
    :func:`pack_int4`; arithmetic shifts do the sign extension)."""
    lo = ((packed << 4).astype(jnp.int8) >> 4).astype(jnp.int8)
    hi = (packed >> 4).astype(jnp.int8)
    d, p_cols = packed.shape
    return jnp.stack([lo, hi], axis=-1).reshape(d, 2 * p_cols)


# the projection names every quantizer targets
_QUANT_NAMES = (
    "qkv", "out_proj", "mlp_up", "mlp_gate", "mlp_down", "lm_head"
)


def _quantize_tree(params, kernel_fn, experts_fn):
    """Shared tree walk for the weight-only quantizers: each projection
    ``kernel`` under a _QUANT_NAMES scope is replaced by
    ``kernel_fn(w) -> {new leaves}``; MoE expert stacks go through
    ``experts_fn(name, w) -> {new leaves}``."""

    def convert(tree, under_quant):
        out = {}
        for name, sub in tree.items():
            if isinstance(sub, dict):
                out[name] = convert(sub, name in _QUANT_NAMES)
            elif under_quant and name == "kernel":
                out.update(kernel_fn(sub))
            elif name in ("experts_up", "experts_down"):
                out.update(experts_fn(name, sub))
            else:
                out[name] = sub
        return out

    return convert(params, False)


def quantize_lm_params_int4(params):
    """Weight-only int4 conversion of a trained LM tree (projections
    only — MoE expert stacks stay unsupported here; use int8 for MoE).
    Each projection ``kernel`` becomes ``{kernel_int4, scale}`` with
    symmetric GROUP-WISE scales ([D/group, F], group along the input
    dim) over the [-7, 7] grid."""

    def quant(w):
        w = jnp.asarray(w, jnp.float32)
        din, dout = w.shape
        g = _int4_group(din)
        wg = w.reshape(din // g, g, dout)
        scale = jnp.max(jnp.abs(wg), axis=1) / 7.0  # [D/g, F]
        scale = jnp.where(scale == 0.0, 1.0, scale)
        wq = jnp.clip(
            jnp.round(wg / scale[:, None, :]), -7, 7
        ).astype(jnp.int8).reshape(din, dout)
        return {"kernel_int4": pack_int4(wq), "scale": scale}

    def experts(name, w):
        raise NotImplementedError(
            "int4 MoE expert stacks not supported; quantize "
            "MoE configs with quantize_lm_params (int8)")

    return _quantize_tree(params, quant, experts)


def quantize_lm_params(params, dtype=jnp.int8):
    """Convert a trained LM param tree to the weight-only integer layout
    the quantized decode model consumes: every projection ``kernel``
    (qkv, out_proj, mlp_up, mlp_gate, mlp_down, lm_head) becomes
    ``{kernel_int8, scale}`` and MoE expert stacks become
    ``{experts_*_int8, experts_*_scale}``, all with symmetric
    per-output-channel scales (``scale = max|w| / qmax``, qmax from
    ``jnp.iinfo(dtype)``; expert scales are per (expert, out-channel)).
    Embeddings, norms, and the router stay as-is (lookups and tiny
    vectors — not where the bandwidth goes)."""
    qmax = float(jnp.iinfo(dtype).max)

    def quant(w, reduce_axis):
        w = jnp.asarray(w, jnp.float32)
        scale = jnp.max(jnp.abs(w), axis=reduce_axis) / qmax
        scale = jnp.where(scale == 0.0, 1.0, scale)
        wq = jnp.round(
            w / jnp.expand_dims(scale, reduce_axis)
        ).astype(dtype)
        return wq, scale

    def kernel_fn(w):
        wq, scale = quant(w, 0)
        return {"kernel_int8": wq, "scale": scale}

    def experts_fn(name, w):
        # [E, D, F] / [E, F, D]: contraction axis is 1, so the
        # per-(expert, out-channel) scale reduces over it
        wq, scale = quant(w, 1)
        return {f"{name}_int8": wq, f"{name}_scale": scale}

    return _quantize_tree(params, kernel_fn, experts_fn)


class CachedBlock(nn.Module):
    """Transformer block with a decode-mode KV cache.

    Parameter tree is name-identical to ``transformer.Block`` (dense or
    MoE FFN — the MoE branch reuses the training ``MoEFFN`` under the
    same ``moe`` scope) so trained params load unchanged.  The cache
    lives in the flax ``cache`` collection: ``cached_k``/``cached_v``
    ``[B, T_max, Hkv, Dh]`` (the GROUPED head count — with GQA the
    cache is n_heads/n_kv_heads smaller than the query head count)
    plus per-sequence ``cache_lens [B]`` (valid positions per slot —
    a vector, not a scalar, so every batch slot can sit at a different
    depth: that is what makes continuous batching possible).

    Modes:
      * prefill (``decode=False``): full-prompt causal attention; writes
        the prompt's K/V into the cache head and sets every slot's
        length to T.
      * extend (``decode=True``, any T ≥ 1): appends this call's K/V at
        each slot's own ``cache_lens[b]`` and attends banded-causally —
        query t of slot b sees cache positions < lens[b] + t + 1.
        T == 1 is classic token decode; T > 1 is a chunked-prefill /
        speculative-verify step.

    Paged mode (``kv_page_size > 0``, extend only — the vLLM
    PagedAttention layout on XLA gathers instead of a custom kernel):
    the ``cache`` collection stores a PHYSICAL POOL
    ``[P+1, page_size, Hkv, Dh]`` per layer instead of per-slot rows,
    and the caller passes ``block_tables [B, max_len/page_size]``
    int32 mapping each slot's logical pages to pool pages (page P is
    the scratch page unmapped entries point at).  Appends scatter
    this call's K/V to ``pool[table[pos // page], pos % page]``;
    attention gathers the pool back into the SAME logical
    ``[B, max_len]`` view the contiguous layout stores, so the banded
    mask — and therefore every output bit — is unchanged.  Persistent
    HBM is the pool (pages allocated on demand, shared prefixes
    deduplicated by the allocator); the gathered view is a transient.
    With ``kv_quant`` the pool stores int8 with one f32 scale per
    (page row, KV head) — ``k_scale``/``v_scale``
    ``[P+1, page_size, Hkv]`` ride the cache collection, quantized on
    scatter and dequantized inside the gather (lossy: NOT part of the
    bit-identical contract).
    """

    d_model: int
    n_heads: int
    d_ff: int
    max_len: int
    dtype: Any = COMPUTE_DTYPE
    # False = full precision; True = weight-only int8 (QuantDense);
    # "int4" = packed 4-bit weights (Quant4Dense, dense configs only)
    quantized: Any = False
    n_experts: int = 0      # >0: MoE FFN (same MoEFFN as training)
    moe_k: int = 2
    moe_capacity_factor: float = 1.25
    n_kv_heads: Optional[int] = None  # < n_heads → GQA: cache shrinks H/Hkv
    ffn: str = "gelu"  # "swiglu" for the Llama MLP
    rope_theta: float = 10000.0
    n_adapters: int = 0   # >0: per-request LoRA (multi-adapter serving)
    lora_rank: int = 8
    lora_scale: float = 1.0
    kv_page_size: int = 0   # >0: paged KV pool (extend mode only)
    kv_quant: bool = False  # paged pool stores int8 + per-row scales

    @nn.compact
    def __call__(
        self, x: jax.Array, positions: jax.Array, decode: bool = False,
        adapter_ids: Optional[jax.Array] = None,  # [B] int32, -1 = base
        block_tables: Optional[jax.Array] = None,  # [B, T_max/page]
    ) -> jax.Array:
        B, T, _ = x.shape
        if self.quantized == "int4" and self.n_experts > 0:
            raise NotImplementedError(
                "int4 + MoE not supported (expert stacks stay "
                "int8); use quantized=True for MoE configs")
        dense = _dense_cls(self.quantized)
        head_dim = self.d_model // self.n_heads
        n_kv = self.n_kv_heads or self.n_heads
        _validate_attn_ffn(self.n_heads, n_kv, self.ffn)

        def proj(features: int, name: str, inp: jax.Array) -> jax.Array:
            """Projection + optional per-request LoRA delta.  With
            ``n_adapters > 0`` every projection carries stacked
            low-rank adapters ([n, Din, r] / [n, r, Dout], B zero-init
            so a fresh adapter is an exact no-op); each batch row
            gathers ITS adapter by id (-1 gates the delta off), so one
            compiled step serves any adapter mix — the multi-LoRA
            pattern vLLM ships, done the TPU way (dense gathers +
            masking, no per-request branching).  The stacks are params
            regardless of adapter_ids so the tree is stable across
            prefill/decode traces."""
            y = dense(features, use_bias=False, dtype=self.dtype,
                      name=name)(inp)
            if self.n_adapters > 0:
                a_stack = self.param(
                    f"{name}_lora_A", nn.initializers.normal(0.01),
                    (self.n_adapters, inp.shape[-1], self.lora_rank),
                    jnp.float32,
                )
                b_stack = self.param(
                    f"{name}_lora_B", nn.initializers.zeros,
                    (self.n_adapters, self.lora_rank, features),
                    jnp.float32,
                )
                if adapter_ids is not None:
                    sel = jnp.maximum(adapter_ids, 0)
                    gate = (adapter_ids >= 0).astype(jnp.float32) \
                        * self.lora_scale
                    mid = jnp.einsum(
                        "btd,bdr->btr", inp.astype(jnp.float32),
                        a_stack[sel],
                    )
                    delta = jnp.einsum(
                        "btr,bro->bto", mid, b_stack[sel]
                    ) * gate[:, None, None]
                    y = y + delta.astype(y.dtype)
            return y

        h = nn.RMSNorm(dtype=self.dtype, name="attn_norm")(x)
        qkv = proj((self.n_heads + 2 * n_kv) * head_dim, "qkv", h)
        q, k, v = split_qkv_heads(qkv, self.n_heads, n_kv, head_dim)
        q = apply_rope(q, positions, self.rope_theta)
        k = apply_rope(k, positions, self.rope_theta)

        # the cache stores the GROUPED heads — the whole point of GQA
        # serving: cache reads (the decode bandwidth bound) shrink by
        # n_heads / n_kv_heads.  Paged modules get POOL-shaped arrays
        # from the caller (init_pool_cache); the init shape below only
        # matters for contiguous model.init paths.
        cache_kwargs = dict(
            shape=(B, self.max_len, n_kv, head_dim),
            dtype=self.dtype,
        )
        cached_k = self.variable(
            "cache", "cached_k", jnp.zeros, cache_kwargs["shape"],
            cache_kwargs["dtype"],
        )
        cached_v = self.variable(
            "cache", "cached_v", jnp.zeros, cache_kwargs["shape"],
            cache_kwargs["dtype"],
        )
        cache_lens = self.variable(
            "cache", "cache_lens", jnp.zeros, (B,), jnp.int32
        )

        if not decode:
            if self.kv_page_size:
                raise NotImplementedError(
                    "paged KV serves the EXTEND path only: prefill "
                    "runs on contiguous B=1 mini caches (the engine "
                    "splices them into pool pages)")
            # prefill: cache head <- prompt K/V; plain causal attention
            # over the prompt (positions are the natural 0..T-1 here)
            cached_k.value = lax.dynamic_update_slice(
                cached_k.value, k, (0, 0, 0, 0)
            )
            cached_v.value = lax.dynamic_update_slice(
                cached_v.value, v, (0, 0, 0, 0)
            )
            cache_lens.value = jnp.full((B,), T, jnp.int32)
            # same math as training (the natural prompt order makes the
            # positions mask == the storage-order causal mask).  Long
            # prompts take the Pallas flash kernel — O(T·Dh) prefill
            # memory instead of the [T, T] score matrix; short ones
            # keep the einsum (kernel launch isn't worth it, and tests
            # compare against the einsum oracle exactly).  T is static,
            # so the choice is resolved at trace time.
            # prefill attends at full head count (MXU-bound; the
            # grouped layout only matters for what the cache STORES)
            kf, vf = repeat_kv(k, self.n_heads), repeat_kv(v, self.n_heads)
            if T >= _FLASH_PREFILL_MIN_T:
                from .flash_attention import flash_attention

                att = flash_attention(q, kf, vf, causal=True)
            else:
                att = local_causal_attention(q, kf, vf, positions)
        elif self.kv_page_size:
            # paged extend: scatter this call's K/V into pool pages by
            # block-table indirection, then gather the pool back into
            # the contiguous [B, max_len] logical view and run the SAME
            # banded attention — valid rows are value-identical to the
            # contiguous layout, masked rows contribute exactly zero
            # either way (softmax of -inf), so tokens stay bit-exact.
            if block_tables is None:
                raise ValueError(
                    "paged extend needs block_tables ([B, n_pages] "
                    "int32 — the engine passes its pool's tables)")
            ps = self.kv_page_size
            lens = cache_lens.value
            # clamp exactly like the contiguous vmapped
            # dynamic_update_slice: parked slots' garbage confines to
            # the band [max_len - T, max_len) of their OWN tail pages
            # (or scratch), which the engine's donor bounds keep clear
            # of any row another slot reads
            start = jnp.minimum(lens, self.max_len - T)
            pos = start[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
            pidx = pos // ps                                    # [B, T]
            off = pos % ps                                      # [B, T]
            phys = jnp.take_along_axis(block_tables, pidx, axis=1)
            if self.kv_quant:
                n_pool = cached_k.value.shape[0]
                k_scale = self.variable(
                    "cache", "k_scale", jnp.zeros,
                    (n_pool, ps, n_kv), jnp.float32)
                v_scale = self.variable(
                    "cache", "v_scale", jnp.zeros,
                    (n_pool, ps, n_kv), jnp.float32)
                kq, ks = quantize_kv_rows(k)
                vq, vs = quantize_kv_rows(v)
                cached_k.value = cached_k.value.at[phys, off].set(kq)
                cached_v.value = cached_v.value.at[phys, off].set(vq)
                k_scale.value = k_scale.value.at[phys, off].set(ks)
                v_scale.value = v_scale.value.at[phys, off].set(vs)
                view_k = _gather_pool_view(
                    cached_k.value, block_tables, self.dtype,
                    k_scale.value)
                view_v = _gather_pool_view(
                    cached_v.value, block_tables, self.dtype,
                    v_scale.value)
            else:
                cached_k.value = cached_k.value.at[phys, off].set(k)
                cached_v.value = cached_v.value.at[phys, off].set(v)
                view_k = _gather_pool_view(
                    cached_k.value, block_tables, self.dtype)
                view_v = _gather_pool_view(
                    cached_v.value, block_tables, self.dtype)
            cache_lens.value = lens + T
            att = _decode_attention(q, view_k, view_v, lens)
        else:
            # extend: per-slot append at lens[b] (vmapped so every slot
            # writes at its own depth), then banded attention against
            # the cache
            lens = cache_lens.value

            def _append(cache_b, new_b, off):
                return lax.dynamic_update_slice(cache_b, new_b, (off, 0, 0))

            cached_k.value = jax.vmap(_append)(cached_k.value, k, lens)
            cached_v.value = jax.vmap(_append)(cached_v.value, v, lens)
            cache_lens.value = lens + T
            att = _decode_attention(
                q, cached_k.value, cached_v.value, lens
            )

        att = att.reshape(B, T, self.d_model)
        x = x + proj(self.d_model, "out_proj", att)
        h = nn.RMSNorm(dtype=self.dtype, name="mlp_norm")(x)
        if self.n_experts > 0:
            from .moe import MoEFFN

            # same module as training (param tree matches Block's).  On
            # the extend path the per-expert capacity is pinned to T
            # (a token occupies at most one slot per expert, so C=T is
            # always dropless): without this, a T>1 chunked-prefill or
            # speculative-verify extend could drop tokens that the
            # equivalent sequence of T=1 decodes would keep, silently
            # diverging from the decode oracle.  Prefill keeps the
            # training capacity semantics (it IS the training forward).
            x = x + MoEFFN(
                n_experts=self.n_experts, d_model=self.d_model,
                d_ff=self.d_ff, k=self.moe_k,
                capacity_factor=self.moe_capacity_factor,
                capacity=(T if decode else None),
                dtype=self.dtype, quantized=self.quantized, name="moe",
            )(h, positions)
        elif self.ffn == "swiglu":
            gate = proj(self.d_ff, "mlp_gate", h)
            up = proj(self.d_ff, "mlp_up", h)
            x = x + proj(self.d_model, "mlp_down", nn.silu(gate) * up)
        else:
            h = proj(self.d_ff, "mlp_up", h)
            h = nn.gelu(h)
            x = x + proj(self.d_model, "mlp_down", h)
        return x


# int8 KV quantization grid: symmetric per-(token row, KV head) scale
# over the head dim — the GPTQ-style recipe at the granularity the
# pool stores (one f32 per Dh values; at Dh=64+bf16 storage that is
# ~53% of the full-precision bytes)
_KV_QMAX = 127.0


def quantize_kv_rows(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """[..., Hkv, Dh] K/V rows → (int8 values, f32 per-row scales
    [..., Hkv]).  Symmetric: q = round(x / s * 127), s = max|x| over
    Dh (0-rows get scale 1 so they round-trip to exact zeros)."""
    s = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    s = jnp.where(s == 0.0, 1.0, s)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / s[..., None]
                           * _KV_QMAX), -127, 127).astype(jnp.int8)
    return q, s


def dequantize_kv_rows(q: jax.Array, s: jax.Array,
                       dtype: Any) -> jax.Array:
    """Inverse of :func:`quantize_kv_rows` (values, not bits)."""
    return (q.astype(jnp.float32) * (s / _KV_QMAX)[..., None]
            ).astype(dtype)


def _gather_pool_view(pool, block_tables, dtype, scale=None):
    """Pool pages → the contiguous logical view
    ``[B, max_len, Hkv, Dh]`` the banded attention masks: one gather
    by block table, reshaped.  With *scale* the pool is int8 and rows
    dequantize on the way out.  Rows of unmapped (scratch) entries are
    garbage — all of them sit at logical positions >= the slot's lens,
    where the -inf mask zeroes them exactly."""
    B = block_tables.shape[0]
    v = pool[block_tables]           # [B, n_pages, page, Hkv, Dh]
    if scale is not None:
        v = dequantize_kv_rows(v, scale[block_tables], dtype)
    n_kv, hd = v.shape[-2], v.shape[-1]
    return v.reshape(B, -1, n_kv, hd)


def _decode_attention(q, k_cache, v_cache, lens):
    """Tq query positions against the cache: [B, Tq, H, Dh] x
    [B, T_max, Hkv, Dh], banded to each slot's depth — query t of slot
    b sees cache positions < lens[b] + t + 1 (the queries' own K/V are
    already appended starting at lens[b]).  Tq == 1 is the HBM-bound
    serving matvec (one cache read per token); Tq > 1 is a
    chunked-prefill / verify step.  With grouped K/V heads (GQA) the
    query reshapes to [B, Tq, Hkv, G, Dh] and the einsums run grouped,
    so the cache is read once at its compact size instead of being
    broadcast to H heads in HBM."""
    B, Tq, H, Dh = q.shape
    n_kv = k_cache.shape[2]
    g = H // n_kv
    scale = 1.0 / jnp.sqrt(jnp.array(Dh, jnp.float32))
    qg = q.reshape(B, Tq, n_kv, g, Dh).astype(jnp.float32)
    scores = jnp.einsum(
        "bqhgd,bkhd->bqhgk", qg, k_cache.astype(jnp.float32)
    ) * scale
    limit = lens[:, None] + jnp.arange(1, Tq + 1)[None, :]  # [B, Tq]
    valid = (
        jnp.arange(k_cache.shape[1])[None, None, :] < limit[:, :, None]
    )  # [B, Tq, T_max]
    scores = jnp.where(
        valid[:, :, None, None, :], scores, -jnp.inf
    )
    w = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bqhgk,bkhd->bqhgd", w, v_cache.astype(jnp.float32)
    )
    return out.reshape(B, Tq, H, Dh).astype(q.dtype)


class DecodeTransformerLM(nn.Module):
    """Inference twin of ``transformer.TransformerLM`` (dense or MoE
    FFN): identical parameter tree, plus the KV cache collection.

    The whole engine assumes natural token order: prefill writes the
    cache at slots 0..T-1 and decode masks by cache length, so
    *positions* must be the natural 0..T-1 (which also makes the flash
    prefill's storage-order causal mask equivalent to the positions
    mask).  Permuted layouts belong to the training side's ring paths,
    not serving."""

    vocab: int
    d_model: int = 256
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 1024
    max_len: int = 512
    dtype: Any = COMPUTE_DTYPE
    quantized: Any = False  # False | True (int8) | "int4"
    n_experts: int = 0
    moe_k: int = 2
    moe_capacity_factor: float = 1.25
    n_kv_heads: Optional[int] = None  # < n_heads → GQA (Llama family)
    ffn: str = "gelu"  # "swiglu" for the Llama MLP
    rope_theta: float = 10000.0
    n_adapters: int = 0   # >0: per-request LoRA stacks on every block
    lora_rank: int = 8
    lora_scale: float = 1.0
    kv_page_size: int = 0   # >0: paged KV pool (extend path)
    kv_quant: bool = False  # pool stores int8 + per-row scales

    @nn.compact
    def __call__(
        self, tokens: jax.Array, positions: jax.Array,
        decode: bool = False,
        adapter_ids: Optional[jax.Array] = None,
        block_tables: Optional[jax.Array] = None,
    ) -> jax.Array:
        x = nn.Embed(self.vocab, self.d_model, dtype=self.dtype,
                     name="embed")(tokens)
        for i in range(self.n_layers):
            x = CachedBlock(
                self.d_model, self.n_heads, self.d_ff,
                max_len=self.max_len, dtype=self.dtype,
                quantized=self.quantized, n_experts=self.n_experts,
                moe_k=self.moe_k,
                moe_capacity_factor=self.moe_capacity_factor,
                n_kv_heads=self.n_kv_heads, ffn=self.ffn,
                rope_theta=self.rope_theta,
                n_adapters=self.n_adapters, lora_rank=self.lora_rank,
                lora_scale=self.lora_scale,
                kv_page_size=self.kv_page_size,
                kv_quant=self.kv_quant,
                name=f"block_{i}",
            )(x, positions, decode=decode, adapter_ids=adapter_ids,
              block_tables=block_tables)
        x = nn.RMSNorm(dtype=self.dtype, name="final_norm")(x)
        dense = _dense_cls(self.quantized)
        logits = dense(self.vocab, use_bias=False, dtype=self.dtype,
                       name="lm_head")(x)
        return logits.astype(jnp.float32)


def make_decoder(
    vocab: int,
    d_model: int = 256,
    n_heads: int = 4,
    n_layers: int = 2,
    d_ff: int = 1024,
    max_len: int = 512,
    dtype: Any = COMPUTE_DTYPE,
    quantized: Any = False,
    n_experts: int = 0,
    moe_k: int = 2,
    moe_capacity_factor: float = 1.25,
    n_kv_heads: Optional[int] = None,
    ffn: str = "gelu",
    rope_theta: float = 10000.0,
    n_adapters: int = 0,
    lora_rank: int = 8,
    lora_scale: float = 1.0,
) -> "DecodeTransformerLM":
    return DecodeTransformerLM(
        vocab=vocab, d_model=d_model, n_heads=n_heads,
        n_layers=n_layers, d_ff=d_ff, max_len=max_len, dtype=dtype,
        quantized=quantized, n_experts=n_experts, moe_k=moe_k,
        moe_capacity_factor=moe_capacity_factor, n_kv_heads=n_kv_heads,
        ffn=ffn, rope_theta=rope_theta, n_adapters=n_adapters,
        lora_rank=lora_rank, lora_scale=lora_scale,
    )


def init_cache(model: DecodeTransformerLM, batch: int):
    """Fresh all-zero cache pytree (the ``cache`` collection) for a
    *batch*-sized request — built directly from the config so no tracing
    of the model is needed to start serving."""
    head_dim = model.d_model // model.n_heads
    n_kv = model.n_kv_heads or model.n_heads
    kv = (batch, model.max_len, n_kv, head_dim)
    return {
        f"block_{i}": {
            "cached_k": jnp.zeros(kv, model.dtype),
            "cached_v": jnp.zeros(kv, model.dtype),
            "cache_lens": jnp.zeros((batch,), jnp.int32),
        }
        for i in range(model.n_layers)
    }


@functools.partial(
    jax.jit, static_argnums=(0,), donate_argnums=(2,)
)
def extend_step(model: "DecodeTransformerLM", params, cache, tokens,
                positions, adapter_ids=None, block_tables=None):
    """One banded extend (``decode=True``, any T >= 1): returns
    ``(logits, new cache)``.  THE compiled serving step — the engine
    (serving.py) and speculative decoding (speculative.py) share this
    single executable per (model, shape).  The cache argument is
    DONATED: on TPU the per-layer appends update the KV buffers in
    place instead of copying the whole cache every token (decode is
    HBM-bound; an un-donated cache would double its traffic and peak
    footprint).  Callers must rebind: ``logits, cache = extend_step(
    model, params, cache, ...)``.  Paged models (``kv_page_size>0``)
    additionally take their pool's *block_tables* (NOT donated — the
    host mirror stays authoritative)."""
    logits, mut = model.apply(
        {"params": params, "cache": cache},
        tokens, positions, decode=True, adapter_ids=adapter_ids,
        block_tables=block_tables, mutable=["cache"],
    )
    return logits, mut["cache"]


def init_pool_cache(model: "DecodeTransformerLM", batch: int,
                    n_pages: int, page_size: int,
                    kv_quant: bool = False):
    """Fresh all-zero PAGED cache pytree: per layer a physical pool
    ``[n_pages + 1, page_size, Hkv, Dh]`` (the +1 is the scratch page
    clamped garbage writes land in) plus the usual ``cache_lens``
    ``[batch]``.  With *kv_quant* the pools are int8 and per-row f32
    scale arrays ride alongside.  Block tables live with the
    allocator (kv_pool.PagePool), not in the cache pytree — the host
    mirror is authoritative and the engine uploads it per dispatch."""
    head_dim = model.d_model // model.n_heads
    n_kv = model.n_kv_heads or model.n_heads
    kv = (n_pages + 1, page_size, n_kv, head_dim)
    out = {}
    for i in range(model.n_layers):
        buf = {
            "cached_k": jnp.zeros(kv, jnp.int8 if kv_quant
                                  else model.dtype),
            "cached_v": jnp.zeros(kv, jnp.int8 if kv_quant
                                  else model.dtype),
            "cache_lens": jnp.zeros((batch,), jnp.int32),
        }
        if kv_quant:
            buf["k_scale"] = jnp.zeros(kv[:3], jnp.float32)
            buf["v_scale"] = jnp.zeros(kv[:3], jnp.float32)
        out[f"block_{i}"] = buf
    return out


@functools.partial(jax.jit, static_argnums=(0,))
def _prefill(model: DecodeTransformerLM, params, prompt, positions):
    """Compiled once per (model config, prompt shape) — flax modules are
    frozen/hashable, so they key the jit cache as static arguments and
    repeat requests hit the compiled executable."""
    cache = init_cache(model, prompt.shape[0])
    logits, mut = model.apply(
        {"params": params, "cache": cache}, prompt, positions,
        mutable=["cache"],
    )
    return logits, mut["cache"]


def _greedy_pick(logits, key, top_k, temperature):
    """Deterministic next-token rule (ignores the PRNG key)."""
    del key, top_k, temperature
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def scan_boundary_update(fin, frs, nxt, i, eos_vec, stop_mat,
                         emitted0, budget):
    """One decode step's on-device finish detection, for a ``lax.scan``
    carry: given the step's picked tokens ``nxt`` [S] and the carried
    first-boundary state (``fin`` [S] int32 step index, -1 = none yet;
    ``frs`` [S] int32 reason code), record which slots just hit a
    finish boundary.  Reason codes mirror the engine's finish_reason
    taxonomy: 1 = eos, 2 = stop token, 3 = length (budget).

    Detection is data, not shapes: ``eos_vec`` [S] is the effective
    per-slot eos id (-1 disables — no token id is negative), ``stop_mat``
    [S, K] is the padded per-slot stop-id matrix (pad -1), ``emitted0``
    [S] the tokens already emitted before the window, and ``budget`` a
    scalar cap (pass a huge value for "no budget").  Precedence matches
    the host walk exactly: the earliest flagged token wins (first write
    into ``fin``), and on one token eos beats stop beats length — the
    budget cut therefore only applies strictly before any eos/stop.
    Pure carry bookkeeping: the token math of the surrounding scan is
    untouched, which is what keeps a fused window byte-identical to the
    per-step path by construction."""
    eos_hit = nxt == eos_vec
    stop_hit = (stop_mat == nxt[:, None]).any(axis=1)
    len_hit = (emitted0 + i + 1) >= budget
    reason = jnp.where(
        eos_hit, 1,
        jnp.where(stop_hit, 2, jnp.where(len_hit, 3, 0))
    ).astype(jnp.int32)
    first = (fin < 0) & (reason > 0)
    return (jnp.where(first, i, fin).astype(jnp.int32),
            jnp.where(first, reason, frs).astype(jnp.int32))


def _sample_pick(logits, key, top_k, temperature):
    """Temperature-scaled, optionally top-k truncated sampling.
    ``lax.top_k`` (the TPU-lowered primitive — no full vocab sort) gives
    the k-th value as the truncation threshold."""
    scaled = logits / jnp.maximum(temperature, 1e-6)
    if top_k is not None:
        kth = lax.top_k(scaled, top_k)[0][..., -1:]
        scaled = jnp.where(scaled >= kth, scaled, -jnp.inf)
    return jax.random.categorical(key, scaled).astype(jnp.int32)


@functools.partial(jax.jit, static_argnums=(0, 4, 6, 7))
def _decode_loop(model: DecodeTransformerLM, params, cache,
                 prefill_logits_last, n_steps: int, pos0, top_k, pick,
                 temperature, rng):
    """The whole generation loop as ONE executable: ``lax.scan`` over
    decode steps, no per-token host round-trips or retraces.  One loop
    serves both decoding modes — *pick* (a static arg) is the
    next-token rule, greedy or sampled.

    The first generated token comes from the prefill logits, so only
    ``n_steps - 1`` decode forwards run and each step emits the token it
    just computed — no trailing forward whose output is discarded.
    """

    def step(carry, _):
        cache, tok, pos, key = carry
        key, sub = jax.random.split(key)
        logits, mut = model.apply(
            {"params": params, "cache": cache},
            tok[:, None], pos[:, None], decode=True,
            mutable=["cache"],
        )
        nxt = pick(logits[:, -1, :], sub, top_k, temperature)
        return (mut["cache"], nxt, pos + 1, key), nxt

    rng, sub = jax.random.split(rng)
    first = pick(prefill_logits_last, sub, top_k, temperature)
    (_, _, _, _), toks = lax.scan(
        step, (cache, first, pos0, rng), None, length=n_steps - 1
    )
    return jnp.concatenate(
        [first[:, None], toks.transpose(1, 0)], axis=1
    )  # [B, n_steps]


def greedy_generate(
    model: DecodeTransformerLM,
    params,
    prompt: jax.Array,   # [B, T_prompt] int32
    n_steps: int,
) -> Tuple[jax.Array, jax.Array]:
    """Greedy decoding: one jitted prefill + one jitted ``lax.scan`` over
    ``n_steps`` decode steps.  The executables are cached at module
    level (model config is a static jit arg), so repeated requests with
    the same shapes never recompile.

    Returns ``(generated [B, n_steps], prefill_logits [B, T_p, V])``.
    """
    B, T_p = _check_request(model, prompt, n_steps)
    positions = jnp.broadcast_to(
        jnp.arange(T_p, dtype=jnp.int32), (B, T_p)
    )
    logits, cache = _prefill(model, params, prompt, positions)
    pos0 = jnp.full((B,), T_p, jnp.int32)
    toks = _decode_loop(
        model, params, cache, logits[:, -1, :], n_steps, pos0, None,
        _greedy_pick, jnp.float32(1.0), jax.random.PRNGKey(0),
    )
    return toks, logits


def _check_request(model, prompt, n_steps: int):
    B, T_p = prompt.shape
    if n_steps < 1:
        raise ValueError(f"n_steps must be >= 1, got {n_steps}")
    if T_p + n_steps > model.max_len:
        raise ValueError(
            f"prompt {T_p} + steps {n_steps} exceeds max_len {model.max_len}"
        )
    return B, T_p


def attach_lora(params, model: "DecodeTransformerLM", rng,
                init_scale: float = 0.01):
    """Add LoRA adapter stacks to an existing (trained or quantized)
    base tree so it loads into a ``n_adapters > 0`` decoder: every
    projection dict in every block gains ``{name}_lora_A`` (normal
    init) and ``{name}_lora_B`` (zeros — a fresh adapter is an exact
    no-op until trained).  Layout matches what ``model.init`` would
    create, so serving sees one coherent tree."""
    if model.n_adapters < 1:
        raise ValueError("model has n_adapters == 0")
    proj_names = ("qkv", "out_proj", "mlp_gate", "mlp_up", "mlp_down")
    out = jax.tree_util.tree_map(lambda x: x, params)  # shallow copy
    for bname, block in out.items():
        if not bname.startswith("block_"):
            continue
        for name in proj_names:
            if name not in block:
                continue
            kern = block[name].get(
                "kernel",
                block[name].get("kernel_int8",
                                block[name].get("kernel_int4")))
            din = kern.shape[0]
            # output dim from the scale, not the kernel: the int4
            # kernel is PACKED (F/2 wide) and int4 scales are
            # group-wise [D/g, F] — the last scale axis is F in every
            # quantized layout, and full-precision kernels carry F
            # directly
            if "scale" in block[name]:
                dout = block[name]["scale"].shape[-1]
            else:
                dout = kern.shape[1]
            rng, k1 = jax.random.split(rng)
            block[f"{name}_lora_A"] = (
                jax.random.normal(
                    k1, (model.n_adapters, din, model.lora_rank),
                    jnp.float32) * init_scale)
            block[f"{name}_lora_B"] = jnp.zeros(
                (model.n_adapters, model.lora_rank, dout), jnp.float32)
    return out


def validate_top_k(model, top_k) -> None:
    """Shared top-k range check for the sampling entry points."""
    if top_k is not None and not 1 <= top_k <= model.vocab:
        raise ValueError(
            f"top_k {top_k} outside [1, vocab={model.vocab}]")


def sample_generate(
    model: DecodeTransformerLM,
    params,
    prompt: jax.Array,
    n_steps: int,
    rng: jax.Array,
    temperature: float = 1.0,
    top_k: Optional[int] = None,
) -> jax.Array:
    """Stochastic decoding (temperature / top-k), cache-backed and
    single-scan like :func:`greedy_generate` (same ``_decode_loop``, a
    sampling pick rule); returns ``generated [B, n_steps]``,
    reproducible from *rng*.  ``temperature → 0`` recovers greedy."""
    validate_top_k(model, top_k)
    B, T_p = _check_request(model, prompt, n_steps)
    positions = jnp.broadcast_to(
        jnp.arange(T_p, dtype=jnp.int32), (B, T_p)
    )
    logits, cache = _prefill(model, params, prompt, positions)
    pos0 = jnp.full((B,), T_p, jnp.int32)
    return _decode_loop(
        model, params, cache, logits[:, -1, :], n_steps, pos0, top_k,
        _sample_pick, jnp.float32(temperature), rng,
    )


def decode_throughput(
    model: DecodeTransformerLM, params, prompt: jax.Array, n_steps: int,
    rounds: int = 3,
) -> Dict[str, float]:
    """tokens/sec of the compiled decode loop — prefill runs once
    outside the timed region, so this really is the per-token serving
    rate; best of *rounds* (same de-noising rationale as
    bench_main._timed_loop)."""
    import time

    B, T_p = prompt.shape
    positions = jnp.broadcast_to(
        jnp.arange(T_p, dtype=jnp.int32), (B, T_p)
    )
    logits, cache = _prefill(model, params, prompt, positions)
    last = logits[:, -1, :]
    pos0 = jnp.full((B,), T_p, jnp.int32)

    def decode():
        return _decode_loop(
            model, params, cache, last, n_steps, pos0, None,
            _greedy_pick, jnp.float32(1.0), jax.random.PRNGKey(0),
        )

    generated = decode()  # warm/compile
    int(generated[0, -1])  # value-transfer sync (bench_main notes)
    best = None
    for _ in range(rounds):
        t0 = time.perf_counter()
        generated = decode()
        int(generated[0, -1])
        dt = time.perf_counter() - t0
        best = dt if best is None or dt < best else best
    return {
        "tokens_per_sec": B * n_steps / best,
        "tokens_per_sec_per_seq": n_steps / best,
        "batch": float(B),
        "steps": float(n_steps),
    }
