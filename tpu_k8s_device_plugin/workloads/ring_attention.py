"""Ring attention: sequence-parallel attention over the ICI ring.

The long-context side of the framework's workload layer.  The reference
delegates all parallelism to workloads (SURVEY.md §2.4); this is the
TPU-native pattern for sequences too long for one chip's HBM: shard the
sequence across the mesh, keep Q resident, and rotate K/V blocks around
the ring with ``lax.ppermute`` while accumulating attention with the
numerically-stable online softmax (blockwise/ring attention, public
technique).  Under ``shard_map`` XLA lowers the permutes to neighbour
ICI transfers, so communication overlaps compute and per-chip memory
stays O(seq/num_chips).

No NCCL/MPI analog exists or is needed — the collective backend is XLA
over ICI (SURVEY.md §5 "distributed communication backend").
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _ring_attention_shard(
    q: jax.Array,  # [B, Tq, H, D] local query block
    k: jax.Array,  # [B, Tk, H, D] local key block
    v: jax.Array,  # [B, Tk, H, D] local value block
    axis_name: str,
    causal: bool,
) -> jax.Array:
    """Per-shard body: online-softmax accumulation over all K/V blocks,
    rotating them one ring hop per step."""
    n_blocks = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = 1.0 / jnp.sqrt(jnp.array(D, jnp.float32))

    # accumulators in f32 regardless of input dtype (bf16-safe softmax)
    o0 = jnp.zeros((B, Tq, H, D), jnp.float32)
    l0 = jnp.zeros((B, Tq, H), jnp.float32)
    m0 = jnp.full((B, Tq, H), -jnp.inf, jnp.float32)

    # ring neighbourhood: at step s we hold the block originally owned by
    # (my_idx - s) mod n; send k/v to the next rank each iteration
    perm = [(i, (i + 1) % n_blocks) for i in range(n_blocks)]

    def accumulate(o, l, m, k_blk, v_blk, kv_idx):
        # [B, Tq, H, Tk] attention scores for this block pair
        scores = jnp.einsum(
            "bqhd,bkhd->bqhk", q.astype(jnp.float32),
            k_blk.astype(jnp.float32),
        ) * scale
        if causal:
            q_pos = my_idx * Tq + lax.broadcasted_iota(
                jnp.int32, (Tq, Tk), 0
            )
            k_pos = kv_idx * Tk + lax.broadcasted_iota(
                jnp.int32, (Tq, Tk), 1
            )
            mask = q_pos >= k_pos  # [Tq, Tk]
            scores = jnp.where(mask[None, :, None, :], scores, -jnp.inf)

        blk_max = jnp.max(scores, axis=-1)               # [B, Tq, H]
        m_new = jnp.maximum(m, blk_max)
        # fully-masked rows keep -inf max; exp(-inf - -inf) would be NaN
        safe_m = jnp.where(jnp.isinf(m_new), 0.0, m_new)
        p = jnp.exp(scores - safe_m[..., None])
        correction = jnp.where(
            jnp.isinf(m), jnp.where(jnp.isinf(m_new), 1.0, 0.0),
            jnp.exp(m - safe_m),
        )
        l = l * correction + jnp.sum(p, axis=-1)
        o = o * correction[..., None] + jnp.einsum(
            "bqhk,bkhd->bqhd", p, v_blk.astype(jnp.float32)
        )
        return o, l, m_new

    def step(carry, s):
        o, l, m, k_blk, v_blk = carry
        kv_idx = (my_idx - s) % n_blocks

        if causal:
            # Entirely-future blocks contribute nothing; skip their FLOPs.
            # The predicate differs per rank, which is fine — the branch
            # bodies are pure local compute (collectives stay outside).
            # Ranks still process ~(rank+1) real blocks each, so the ring
            # is load-imbalanced; a zig-zag block layout would level it
            # at the cost of a second permute stream.
            o, l, m = lax.cond(
                kv_idx > my_idx,
                lambda o, l, m, kb, vb, ki: (o, l, m),
                accumulate,
                o, l, m, k_blk, v_blk, kv_idx,
            )
        else:
            o, l, m = accumulate(o, l, m, k_blk, v_blk, kv_idx)

        # the final rotation would only restore the original layout for a
        # result we never read — skip it (uniform predicate: collective
        # inside cond is legal because every rank takes the same branch)
        k_blk, v_blk = lax.cond(
            s < n_blocks - 1,
            lambda kb, vb: (
                lax.ppermute(kb, axis_name, perm),
                lax.ppermute(vb, axis_name, perm),
            ),
            lambda kb, vb: (kb, vb),
            k_blk, v_blk,
        )
        return (o, l, m, k_blk, v_blk), None

    (o, l, m, _, _), _ = lax.scan(
        step, (o0, l0, m0, k, v), jnp.arange(n_blocks)
    )
    # rows with no visible keys (can't happen with causal diagonal) get 0
    denom = jnp.where(l == 0.0, 1.0, l)
    return (o / denom[..., None]).astype(q.dtype)


def make_ring_attention(
    mesh: Mesh, seq_axis: str = "data", causal: bool = False
):
    """jit-compiled ring attention over *mesh*: [B, T, H, D] inputs with T
    sharded on *seq_axis*.  Returns (fn, in_sharding)."""
    spec = P(None, seq_axis, None, None)
    sharding = NamedSharding(mesh, spec)
    body = jax.shard_map(
        functools.partial(
            _ring_attention_shard, axis_name=seq_axis, causal=causal
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return jax.jit(body), sharding


def full_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = False
) -> jax.Array:
    """Single-device reference implementation (the correctness oracle)."""
    T, S = q.shape[1], k.shape[1]
    scores = jnp.einsum(
        "bqhd,bkhd->bqhk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) / jnp.sqrt(jnp.array(q.shape[-1], jnp.float32))
    if causal:
        mask = (
            lax.broadcasted_iota(jnp.int32, (T, S), 0)
            >= lax.broadcasted_iota(jnp.int32, (T, S), 1)
        )
        scores = jnp.where(mask[None, :, None, :], scores, -jnp.inf)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bqhk,bkhd->bqhd", w, v.astype(jnp.float32)).astype(
        q.dtype
    )
