"""Ring attention: sequence-parallel attention over the ICI ring.

The long-context side of the framework's workload layer.  The reference
delegates all parallelism to workloads (SURVEY.md §2.4); this is the
TPU-native pattern for sequences too long for one chip's HBM: shard the
sequence across the mesh, keep Q resident, and rotate K/V blocks around
the ring with ``lax.ppermute`` while accumulating attention with the
numerically-stable online softmax (blockwise/ring attention, public
technique).  Under ``shard_map`` XLA lowers the permutes to neighbour
ICI transfers, so communication overlaps compute and per-chip memory
stays O(seq/num_chips).

No NCCL/MPI analog exists or is needed — the collective backend is XLA
over ICI (SURVEY.md §5 "distributed communication backend").
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _shard_map(fn, mesh, in_specs, out_specs):
    """jax.shard_map with the no-replication-check knob, across jax
    versions: the top-level API (with check_vma) only exists in recent
    releases; older ones ship jax.experimental.shard_map (check_rep).
    pyproject's [workloads] extra pins jax>=0.7, but the fallback keeps
    the module importable on hosts with an older preinstalled jax."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map as legacy_shard_map

    return legacy_shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def _online_softmax_update(o, l, m, q_blk, k_blk, v_blk, scale, mask=None):
    """One K/V block's numerically-stable online-softmax accumulation, in
    f32.  *mask* is an optional [Tq, Tk] boolean of visible positions.
    Fully-masked rows keep a -inf running max; the isinf-guarded
    correction keeps exp(-inf - -inf) from producing NaN.  This is the
    subtle part of ring attention — the single source of truth shared by
    both the contiguous and zig-zag shard bodies.

    Grouped-query attention: K/V may carry fewer heads than Q — the
    expansion to the query head count happens HERE, locally, after the
    blocks have already rotated, so the ring only ever moves the
    compact Hkv heads (H/Hkv less ICI traffic per hop).  The einsum
    ring bodies are plain autodiff code, so the repeat's transpose
    (a sum over each group) flows dK/dV back around the ring at the
    grouped size too."""
    if k_blk.shape[2] != q_blk.shape[2]:
        from .transformer import repeat_kv

        k_blk = repeat_kv(k_blk, q_blk.shape[2])
        v_blk = repeat_kv(v_blk, q_blk.shape[2])
    scores = jnp.einsum(
        "bqhd,bkhd->bqhk", q_blk.astype(jnp.float32),
        k_blk.astype(jnp.float32),
    ) * scale
    if mask is not None:
        scores = jnp.where(mask[None, :, None, :], scores, -jnp.inf)
    blk_max = jnp.max(scores, axis=-1)               # [B, Tq, H]
    m_new = jnp.maximum(m, blk_max)
    safe_m = jnp.where(jnp.isinf(m_new), 0.0, m_new)
    p = jnp.exp(scores - safe_m[..., None])
    correction = jnp.where(
        jnp.isinf(m), jnp.where(jnp.isinf(m_new), 1.0, 0.0),
        jnp.exp(m - safe_m),
    )
    l = l * correction + jnp.sum(p, axis=-1)
    o = o * correction[..., None] + jnp.einsum(
        "bqhk,bkhd->bqhd", p, v_blk.astype(jnp.float32)
    )
    return o, l, m_new


def _ring_attention_shard(
    q: jax.Array,  # [B, Tq, H, D] local query block
    k: jax.Array,  # [B, Tk, H, D] local key block
    v: jax.Array,  # [B, Tk, H, D] local value block
    axis_name: str,
    causal: bool,
) -> jax.Array:
    """Per-shard body: online-softmax accumulation over all K/V blocks,
    rotating them one ring hop per step."""
    n_blocks = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = 1.0 / jnp.sqrt(jnp.array(D, jnp.float32))

    # accumulators in f32 regardless of input dtype (bf16-safe softmax)
    o0 = jnp.zeros((B, Tq, H, D), jnp.float32)
    l0 = jnp.zeros((B, Tq, H), jnp.float32)
    m0 = jnp.full((B, Tq, H), -jnp.inf, jnp.float32)

    # ring neighbourhood: at step s we hold the block originally owned by
    # (my_idx - s) mod n; send k/v to the next rank each iteration
    perm = [(i, (i + 1) % n_blocks) for i in range(n_blocks)]

    def accumulate(o, l, m, k_blk, v_blk, kv_idx):
        mask = None
        if causal:
            q_pos = my_idx * Tq + lax.broadcasted_iota(
                jnp.int32, (Tq, Tk), 0
            )
            k_pos = kv_idx * Tk + lax.broadcasted_iota(
                jnp.int32, (Tq, Tk), 1
            )
            mask = q_pos >= k_pos  # [Tq, Tk]
        return _online_softmax_update(o, l, m, q, k_blk, v_blk, scale, mask)

    def step(carry, s):
        o, l, m, k_blk, v_blk = carry
        kv_idx = (my_idx - s) % n_blocks

        if causal:
            # Entirely-future blocks contribute nothing; skip their FLOPs.
            # The predicate differs per rank, which is fine — the branch
            # bodies are pure local compute (collectives stay outside).
            # Ranks still process ~(rank+1) real blocks each, so this
            # layout is load-imbalanced under causal masking; use
            # layout="zigzag" (below) for rank-uniform work.
            o, l, m = lax.cond(
                kv_idx > my_idx,
                lambda o, l, m, kb, vb, ki: (o, l, m),
                accumulate,
                o, l, m, k_blk, v_blk, kv_idx,
            )
        else:
            o, l, m = accumulate(o, l, m, k_blk, v_blk, kv_idx)

        # the final rotation would only restore the original layout for a
        # result we never read — skip it (uniform predicate: collective
        # inside cond is legal because every rank takes the same branch)
        k_blk, v_blk = _rotate_kv(
            k_blk, v_blk, s, n_blocks, axis_name, perm
        )
        return (o, l, m, k_blk, v_blk), None

    (o, l, m, _, _), _ = lax.scan(
        step, (o0, l0, m0, k, v), jnp.arange(n_blocks)
    )
    # rows with no visible keys (can't happen with causal diagonal) get 0
    denom = jnp.where(l == 0.0, 1.0, l)
    return (o / denom[..., None]).astype(q.dtype)


# ---------------------------------------------------------------------------
# Zig-zag layout: balanced causal ring attention
#
# With contiguous sequence chunks, causal masking makes rank r process ~r+1
# real block-pairs per sweep — rank n-1 does n× rank 0's work and the ring's
# step time is the worst rank's (the imbalance the contiguous path documents
# below).  The zig-zag layout (public technique, a.k.a. zigzag ring / flash
# attention) splits the sequence into 2n chunks and gives rank r chunks
# {r, 2n-1-r}: every rank then owns one "early" and one "late" chunk, and
# for any K/V block pair exactly half the quarter-interactions are causally
# visible — per-step work becomes uniform (2 C×C score tiles per step, 3 on
# the diagonal step, identical for every rank).
# ---------------------------------------------------------------------------


def zigzag_permute(x: jax.Array, n_shards: int, axis: int = 1) -> jax.Array:
    """Reorder a contiguous sequence into zig-zag layout: chunk order
    (0, 2n-1, 1, 2n-2, …) so an even split over n ranks gives rank r
    chunks {r, 2n-1-r}.  Training loops keep tensors permuted end-to-end,
    so this runs once at ingress, not per step."""
    idx = _zigzag_indices(x.shape[axis], n_shards)
    return jnp.take(x, jnp.asarray(idx), axis=axis)


def zigzag_unpermute(x: jax.Array, n_shards: int, axis: int = 1) -> jax.Array:
    """Inverse of zigzag_permute (egress back to natural token order)."""
    fwd = _zigzag_indices(x.shape[axis], n_shards)
    inv = np.empty_like(fwd)
    inv[fwd] = np.arange(len(fwd))
    return jnp.take(x, jnp.asarray(inv), axis=axis)


def _zigzag_indices(T: int, n_shards: int) -> np.ndarray:
    n_chunks = 2 * n_shards
    if T % n_chunks:
        raise ValueError(f"sequence length {T} not divisible by {n_chunks}")
    C = T // n_chunks
    order = []
    for r in range(n_shards):
        order.extend((r, n_chunks - 1 - r))
    return np.concatenate([np.arange(c * C, (c + 1) * C) for c in order])


def _ring_attention_shard_zigzag(
    q: jax.Array,  # [B, Tq, H, D] local: [chunk i ; chunk 2n-1-i]
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
) -> jax.Array:
    """Per-shard body for the causal zig-zag layout.  Each K/V rotation
    step computes only the causally visible half of the local score tile:
      holder i vs block owner j:
        i < j : only q_hi attends (to all of k)        — 2 C×C tiles
        i > j : both q halves attend k_lo only         — 2 C×C tiles
        i == j: lo×lo diag, hi×lo full, hi×hi diag     — 3 C×C tiles
    so per-step FLOPs are rank-uniform (vs ~(r+1)/n utilisation in the
    contiguous layout)."""
    n_blocks = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    C = Tq // 2
    scale = 1.0 / jnp.sqrt(jnp.array(D, jnp.float32))
    perm = [(i, (i + 1) % n_blocks) for i in range(n_blocks)]

    def halves(x):
        return x[:, :C], x[:, C:]

    q_lo, q_hi = halves(q)

    def acc_tile(o, l, m, q_blk, k_blk, v_blk, diag_mask):
        """Online-softmax update of one (q half × k half/full) tile.
        diag_mask=True applies the within-chunk causal diagonal (only ever
        needed for equal-position chunks, where q and k positions align)."""
        mask = None
        if diag_mask:
            Tq_b, Tk_b = q_blk.shape[1], k_blk.shape[1]
            mask = (
                lax.broadcasted_iota(jnp.int32, (Tq_b, Tk_b), 0)
                >= lax.broadcasted_iota(jnp.int32, (Tq_b, Tk_b), 1)
            )
        return _online_softmax_update(
            o, l, m, q_blk, k_blk, v_blk, scale, mask
        )

    def step(carry, s):
        (lo, hi, k_blk, v_blk) = carry
        j = (my_idx - s) % n_blocks
        k_lo, k_hi = halves(k_blk)
        v_lo, v_hi = halves(v_blk)

        def on_lt(lo, hi):  # i < j: only the late half attends, unmasked
            o, l, m = acc_tile(*hi, q_hi, k_blk, v_blk, diag_mask=False)
            return lo, (o, l, m)

        def on_gt(lo, hi):  # i > j: both halves attend the early K half
            lo = acc_tile(*lo, q_lo, k_lo, v_lo, diag_mask=False)
            hi = acc_tile(*hi, q_hi, k_lo, v_lo, diag_mask=False)
            return lo, hi

        def on_eq(lo, hi):  # diagonal step
            lo = acc_tile(*lo, q_lo, k_lo, v_lo, diag_mask=True)
            hi = acc_tile(*hi, q_hi, k_lo, v_lo, diag_mask=False)
            hi = acc_tile(*hi, q_hi, k_hi, v_hi, diag_mask=True)
            return lo, hi

        branch = _zigzag_branch(j, my_idx)
        lo, hi = lax.switch(branch, (on_eq, on_lt, on_gt), lo, hi)

        k_blk, v_blk = _rotate_kv(
            k_blk, v_blk, s, n_blocks, axis_name, perm
        )
        return (lo, hi, k_blk, v_blk), None

    def zeros():
        return (
            jnp.zeros((B, C, H, D), jnp.float32),
            jnp.zeros((B, C, H), jnp.float32),
            jnp.full((B, C, H), -jnp.inf, jnp.float32),
        )

    (lo, hi, _, _), _ = lax.scan(
        step, (zeros(), zeros(), k, v), jnp.arange(n_blocks)
    )
    outs = []
    for o, l, m in (lo, hi):
        denom = jnp.where(l == 0.0, 1.0, l)
        outs.append((o / denom[..., None]).astype(q.dtype))
    return jnp.concatenate(outs, axis=1)


# ---------------------------------------------------------------------------
# Flash-fused ring attention (impl="flash")
#
# Same ring schedule as the contiguous einsum path, but each rank×block
# interaction runs the Pallas flash kernel (flash_attention.py) instead of
# materializing the [Tq, Tk] score tile: per-step partials (o_s, lse_s)
# merge through logsumexp algebra, so per-step HBM traffic is O(T·D) and
# the score matrix never exists at any scale.  The whole rotation is one
# jax.custom_vjp: the backward re-rotates K/V around the ring and, per
# step, reuses the Pallas dq/dkv kernels with the GLOBAL lse/delta (under
# which the exact gradient decomposes blockwise — see flash_block_grads);
# dK/dV partial sums ride the ring alongside K/V and arrive home after n
# rotations.
# ---------------------------------------------------------------------------


def _lse_merge(o_acc, lse_acc, o_s, lse_s):
    """Merge a new normalized partial (o_s, lse_s) into the running
    (o_acc, lse_acc).  −inf lse (no visible keys) contributes weight 0;
    all-−inf rows stay (0, −inf) without producing NaN."""
    m = jnp.maximum(lse_acc, lse_s)
    safe_m = jnp.where(jnp.isinf(m), 0.0, m)
    a = jnp.where(jnp.isinf(lse_acc), 0.0, jnp.exp(lse_acc - safe_m))
    b = jnp.where(jnp.isinf(lse_s), 0.0, jnp.exp(lse_s - safe_m))
    tot = a + b
    denom = jnp.where(tot == 0.0, 1.0, tot)
    o = (
        o_acc * (a / denom)[..., None]
        + o_s.astype(jnp.float32) * (b / denom)[..., None]
    )
    lse = jnp.where(tot == 0.0, -jnp.inf, safe_m + jnp.log(denom))
    return o, lse


def _causal_branch(kv_idx, my_idx):
    """Ring-step branch selector shared by the flash forward and
    backward: 0 = future block (skip), 1 = diagonal (causal kernel),
    2 = past (unmasked kernel).  Both directions must agree on which
    held block is the masked diagonal."""
    return jnp.where(kv_idx > my_idx, 0, jnp.where(kv_idx == my_idx, 1, 2))


def _zigzag_branch(j, my_idx):
    """Zig-zag step branch selector shared by every zig-zag body
    (einsum, flash forward, flash backward): 0 = diagonal (own block),
    1 = holder-earlier (only the late q half attends, unmasked),
    2 = holder-later (both q halves attend the early K half)."""
    return jnp.where(j == my_idx, 0, jnp.where(my_idx < j, 1, 2))


def _rotate_kv(k_blk, v_blk, s, n_blocks, axis_name, perm):
    """One ring hop for the K/V pair, skipping the dead final rotation
    (its result is never read).  The uniform predicate makes the
    collective inside ``lax.cond`` legal — every rank takes the same
    branch at every step."""
    return lax.cond(
        s < n_blocks - 1,
        lambda kb, vb: (
            lax.ppermute(kb, axis_name, perm),
            lax.ppermute(vb, axis_name, perm),
        ),
        lambda kb, vb: (kb, vb),
        k_blk, v_blk,
    )


def _ring_flash_fwd_impl(q, k, v, axis_name, causal):
    from .flash_attention import flash_block_forward

    n_blocks = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    perm = [(i, (i + 1) % n_blocks) for i in range(n_blocks)]

    o0 = jnp.zeros((B, Tq, H, D), jnp.float32)
    lse0 = jnp.full((B, Tq, H), -jnp.inf, jnp.float32)

    def step(carry, s):
        o_acc, lse_acc, k_blk, v_blk = carry
        kv_idx = (my_idx - s) % n_blocks

        def merged(blk_causal):
            o_s, lse_s = flash_block_forward(
                q, k_blk, v_blk, causal=blk_causal
            )
            return _lse_merge(o_acc, lse_acc, o_s, lse_s)

        if causal:
            # future block: skip; diagonal: causal kernel (local storage
            # order == global order offset, so the mask aligns); past:
            # unmasked kernel
            o_acc, lse_acc = lax.switch(
                _causal_branch(kv_idx, my_idx),
                (
                    lambda: (o_acc, lse_acc),
                    lambda: merged(True),
                    lambda: merged(False),
                ),
            )
        else:
            o_acc, lse_acc = merged(False)

        k_blk, v_blk = _rotate_kv(
            k_blk, v_blk, s, n_blocks, axis_name, perm
        )
        return (o_acc, lse_acc, k_blk, v_blk), None

    (o, lse, _, _), _ = lax.scan(
        step, (o0, lse0, k, v), jnp.arange(n_blocks)
    )
    return o.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ring_flash(q, k, v, axis_name, causal):
    out, _ = _ring_flash_fwd_impl(q, k, v, axis_name, causal)
    return out


def _ring_flash_fwd(q, k, v, axis_name, causal):
    out, lse = _ring_flash_fwd_impl(q, k, v, axis_name, causal)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(axis_name, causal, res, g):
    from .flash_attention import flash_block_grads

    q, k, v, out, lse = res
    n_blocks = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n_blocks) for i in range(n_blocks)]
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )  # [B, Tq, H] — global, like lse

    zeros_q = jnp.zeros(q.shape, jnp.float32)
    zeros_kv = jnp.zeros(k.shape, jnp.float32)

    def step(carry, s):
        dq_acc, k_blk, v_blk, dk_blk, dv_blk = carry
        kv_idx = (my_idx - s) % n_blocks

        def grads(blk_causal):
            # flash_block_grads returns f32 partials — accumulate as-is
            return flash_block_grads(
                q, k_blk, v_blk, g, lse, delta, causal=blk_causal
            )

        if causal:
            dq_c, dk_c, dv_c = lax.switch(
                _causal_branch(kv_idx, my_idx),
                (
                    lambda: (zeros_q, zeros_kv, zeros_kv),
                    lambda: grads(True),
                    lambda: grads(False),
                ),
            )
        else:
            dq_c, dk_c, dv_c = grads(False)
        dq_acc = dq_acc + dq_c
        dk_blk = dk_blk + dk_c
        dv_blk = dv_blk + dv_c

        # dK/dV rotate every step (n total): block j's partial sums ride
        # with the block and are home at rank j after the final rotation.
        # K/V skip the last rotation like the forward — their final
        # position is never read (uniform predicate, so the collective
        # inside cond is legal).
        dk_blk, dv_blk = (
            lax.ppermute(x, axis_name, perm) for x in (dk_blk, dv_blk)
        )
        k_blk, v_blk = _rotate_kv(
            k_blk, v_blk, s, n_blocks, axis_name, perm
        )
        return (dq_acc, k_blk, v_blk, dk_blk, dv_blk), None

    (dq, _, _, dk, dv), _ = lax.scan(
        step, (zeros_q, k, v, zeros_kv, zeros_kv), jnp.arange(n_blocks)
    )
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def _ring_attention_shard_flash(q, k, v, axis_name, causal):
    """Per-shard body for impl="flash" (contiguous layout)."""
    if k.shape[2] != q.shape[2]:
        raise ValueError(
            "impl='flash' ring attention requires equal Q/KV head "
            "counts; repeat_kv before the ring (the einsum impl "
            "rotates grouped heads natively)")
    return _ring_flash(q, k, v, axis_name, causal)


# -- zig-zag layout with the flash kernels ----------------------------------
#
# Every zig-zag tile is either unmasked (cross-chunk, fully visible) or a
# locally-aligned causal diagonal — exactly the two modes the flash
# kernels provide — so the balanced layout composes with the Pallas path
# tile-by-tile: per-tile (o, lse) partials merge with _lse_merge per q
# half, and the backward reuses flash_block_grads with the global
# lse/delta halves, zero-padding each branch's dK/dV contribution to the
# full rotating block so the three causal branches stay shape-uniform.


def _ring_flash_zz_fwd_impl(q, k, v, axis_name):
    from .flash_attention import flash_block_forward

    n_blocks = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    C = Tq // 2
    perm = [(i, (i + 1) % n_blocks) for i in range(n_blocks)]

    def halves(x):
        return x[:, :C], x[:, C:]

    q_lo, q_hi = halves(q)

    def tile(acc, q_half, k_part, v_part, diag):
        o_s, lse_s = flash_block_forward(
            q_half, k_part, v_part, causal=diag
        )
        return _lse_merge(*acc, o_s, lse_s)

    def step(carry, s):
        lo, hi, k_blk, v_blk = carry
        j = (my_idx - s) % n_blocks
        k_lo, k_hi = halves(k_blk)
        v_lo, v_hi = halves(v_blk)

        def on_eq(lo, hi):
            lo = tile(lo, q_lo, k_lo, v_lo, True)
            hi = tile(hi, q_hi, k_lo, v_lo, False)
            hi = tile(hi, q_hi, k_hi, v_hi, True)
            return lo, hi

        def on_lt(lo, hi):  # i < j: only the late half attends, unmasked
            return lo, tile(hi, q_hi, k_blk, v_blk, False)

        def on_gt(lo, hi):  # i > j: both halves attend the early K half —
            # one kernel launch over the concatenated query (both tiles
            # are unmasked against the same k_lo), halves split after
            o_s, lse_s = flash_block_forward(q, k_lo, v_lo, causal=False)
            o_l, o_h = halves(o_s)
            l_l, l_h = halves(lse_s)
            return (
                _lse_merge(*lo, o_l, l_l),
                _lse_merge(*hi, o_h, l_h),
            )

        branch = _zigzag_branch(j, my_idx)
        lo, hi = lax.switch(branch, (on_eq, on_lt, on_gt), lo, hi)

        k_blk, v_blk = _rotate_kv(
            k_blk, v_blk, s, n_blocks, axis_name, perm
        )
        return (lo, hi, k_blk, v_blk), None

    def zeros():
        return (
            jnp.zeros((B, C, H, D), jnp.float32),
            jnp.full((B, C, H), -jnp.inf, jnp.float32),
        )

    (lo, hi, _, _), _ = lax.scan(
        step, (zeros(), zeros(), k, v), jnp.arange(n_blocks)
    )
    out = jnp.concatenate([lo[0], hi[0]], axis=1).astype(q.dtype)
    lse = jnp.concatenate([lo[1], hi[1]], axis=1)  # [B, Tq, H]
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _ring_flash_zigzag(q, k, v, axis_name):
    out, _ = _ring_flash_zz_fwd_impl(q, k, v, axis_name)
    return out


def _ring_flash_zz_fwd(q, k, v, axis_name):
    out, lse = _ring_flash_zz_fwd_impl(q, k, v, axis_name)
    return out, (q, k, v, out, lse)


def _ring_flash_zz_bwd(axis_name, res, g):
    from .flash_attention import flash_block_grads

    q, k, v, out, lse = res
    n_blocks = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    C = Tq // 2
    perm = [(i, (i + 1) % n_blocks) for i in range(n_blocks)]
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )  # [B, Tq, H]

    def halves(x):
        return x[:, :C], x[:, C:]

    q_lo, q_hi = halves(q)
    g_lo, g_hi = halves(g)
    lse_lo, lse_hi = halves(lse)
    delta_lo, delta_hi = halves(delta)
    zc = jnp.zeros((B, C, H, D), jnp.float32)

    def tile(q_half, k_part, v_part, g_half, lse_half, delta_half, diag):
        return flash_block_grads(
            q_half, k_part, v_part, g_half, lse_half, delta_half,
            causal=diag,
        )

    def step(carry, s):
        dq_lo, dq_hi, k_blk, v_blk, dk_blk, dv_blk = carry
        j = (my_idx - s) % n_blocks
        k_lo, k_hi = halves(k_blk)
        v_lo, v_hi = halves(v_blk)

        # each branch returns shape-uniform (dq_lo+, dq_hi+, dk+, dv+)
        # with dk/dv zero-padded to the full [B, 2C, H, D] block
        def on_eq():
            dql, dkl1, dvl1 = tile(
                q_lo, k_lo, v_lo, g_lo, lse_lo, delta_lo, True
            )
            dqh1, dkl2, dvl2 = tile(
                q_hi, k_lo, v_lo, g_hi, lse_hi, delta_hi, False
            )
            dqh2, dkh, dvh = tile(
                q_hi, k_hi, v_hi, g_hi, lse_hi, delta_hi, True
            )
            return (
                dql, dqh1 + dqh2,
                jnp.concatenate([dkl1 + dkl2, dkh], axis=1),
                jnp.concatenate([dvl1 + dvl2, dvh], axis=1),
            )

        def on_lt():
            dqh, dkf, dvf = tile(
                q_hi, k_blk, v_blk, g_hi, lse_hi, delta_hi, False
            )
            return jnp.zeros_like(zc), dqh, dkf, dvf

        def on_gt():
            # one kernel launch over the concatenated query (both tiles
            # unmasked vs the same k_lo) — dq comes back pre-split and
            # the two dk_lo/dv_lo partials are already summed inside
            dq_c, dkl, dvl = tile(
                q, k_lo, v_lo, g, lse, delta, False
            )
            dql, dqh = halves(dq_c)
            return (
                dql, dqh,
                jnp.concatenate([dkl, jnp.zeros_like(zc)], axis=1),
                jnp.concatenate([dvl, jnp.zeros_like(zc)], axis=1),
            )

        branch = _zigzag_branch(j, my_idx)
        dql_c, dqh_c, dk_c, dv_c = lax.switch(branch, (on_eq, on_lt, on_gt))
        dq_lo = dq_lo + dql_c
        dq_hi = dq_hi + dqh_c
        dk_blk = dk_blk + dk_c
        dv_blk = dv_blk + dv_c

        # dK/dV ride all n rotations home; K/V skip the dead last one
        dk_blk, dv_blk = (
            lax.ppermute(x, axis_name, perm) for x in (dk_blk, dv_blk)
        )
        k_blk, v_blk = _rotate_kv(
            k_blk, v_blk, s, n_blocks, axis_name, perm
        )
        return (dq_lo, dq_hi, k_blk, v_blk, dk_blk, dv_blk), None

    zkv = jnp.zeros((B, 2 * C, H, D), jnp.float32)
    (dq_lo, dq_hi, _, _, dk, dv), _ = lax.scan(
        step, (zc, zc, k, v, zkv, zkv), jnp.arange(n_blocks)
    )
    dq = jnp.concatenate([dq_lo, dq_hi], axis=1)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_flash_zigzag.defvjp(_ring_flash_zz_fwd, _ring_flash_zz_bwd)


def _ring_attention_shard_zigzag_flash(q, k, v, axis_name):
    """Per-shard body for impl="flash", layout="zigzag"."""
    if k.shape[2] != q.shape[2]:
        raise ValueError(
            "impl='flash' ring attention requires equal Q/KV head "
            "counts; repeat_kv before the ring (the einsum impl "
            "rotates grouped heads natively)")
    return _ring_flash_zigzag(q, k, v, axis_name)


def make_ring_attention(
    mesh: Mesh, seq_axis: str = "data", causal: bool = False,
    layout: str = "contiguous", spec: Optional[P] = None,
    impl: str = "einsum",
):
    """jit-compiled ring attention over *mesh*: [B, T, H, D] inputs with T
    sharded on *seq_axis*.  Returns (fn, in_sharding).

    ``layout="zigzag"`` (causal only) expects inputs permuted with
    :func:`zigzag_permute` over ``mesh.shape[seq_axis]`` shards and returns
    the output in the same order — per-rank causal work is then uniform
    instead of growing with rank index.  Keep tensors permuted across the
    whole training loop; permute once at ingress/egress.

    *spec* overrides the partitioning of the [B, T, H, D] operands (default:
    only T on *seq_axis*) so batch/heads can ride other mesh axes — e.g.
    ``P("data", "seq", "model", None)`` inside a 3-axis LM step.  The ring
    only ever communicates over *seq_axis*; other axes just shrink the
    local block.

    ``impl="flash"`` runs each rank×block interaction through the
    Pallas flash kernels instead of the einsum online-softmax update:
    no [Tq, Tk] score tile is ever materialized, and the backward
    re-rotates K/V reusing the Pallas dq/dkv kernels with the global
    logsumexp.  Differentiable end-to-end like the einsum path; composes
    with both layouts (the zig-zag tiles are all either unmasked or
    locally-aligned causal, which are exactly the kernels' two modes)."""
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown layout {layout!r}")
    if layout == "zigzag" and not causal:
        raise ValueError("zigzag layout only pays off for causal attention")
    if impl not in ("einsum", "flash"):
        raise ValueError(f"unknown impl {impl!r}")
    if spec is None:
        spec = P(None, seq_axis, None, None)
    sharding = NamedSharding(mesh, spec)
    if layout == "zigzag" and impl == "flash":
        shard_fn = functools.partial(
            _ring_attention_shard_zigzag_flash, axis_name=seq_axis
        )
    elif layout == "zigzag":
        shard_fn = functools.partial(
            _ring_attention_shard_zigzag, axis_name=seq_axis
        )
    elif impl == "flash":
        shard_fn = functools.partial(
            _ring_attention_shard_flash, axis_name=seq_axis, causal=causal
        )
    else:
        shard_fn = functools.partial(
            _ring_attention_shard, axis_name=seq_axis, causal=causal
        )
    body = _shard_map(shard_fn, mesh, (spec, spec, spec), spec)
    return jax.jit(body), sharding


def full_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = False
) -> jax.Array:
    """Single-device reference implementation (the correctness oracle)."""
    T, S = q.shape[1], k.shape[1]
    scores = jnp.einsum(
        "bqhd,bkhd->bqhk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) / jnp.sqrt(jnp.array(q.shape[-1], jnp.float32))
    if causal:
        mask = (
            lax.broadcasted_iota(jnp.int32, (T, S), 0)
            >= lax.broadcasted_iota(jnp.int32, (T, S), 1)
        )
        scores = jnp.where(mask[None, :, None, :], scores, -jnp.inf)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bqhk,bkhd->bqhd", w, v.astype(jnp.float32)).astype(
        q.dtype
    )
