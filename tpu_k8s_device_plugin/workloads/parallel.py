"""Sharded AlexNet training over a jax.sharding.Mesh.

The reference leaves parallelism entirely to workloads (SURVEY.md §2.4) —
this module is that workload side, done the TPU way: a ``Mesh`` with
``data`` × ``model`` axes, ``NamedSharding`` annotations on the pytrees,
and a single ``jit`` of the whole train step so XLA places the collectives
(psum for data-parallel grads, all-gather/reduce-scatter for the sharded
dense layers) on ICI.  No NCCL/MPI analog exists or is needed: the
communication backend is XLA itself.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .alexnet import AlexNet, train_step


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    model_parallel: Optional[int] = None,
) -> Mesh:
    """``data`` × ``model`` mesh over the given (default: all) devices.

    Model-axis size defaults to 2 when the device count allows it, so the
    big dense layers exercise tensor parallelism; pass 1 for pure DP.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if model_parallel is None:
        model_parallel = 2 if n % 2 == 0 and n >= 2 else 1
    if n % model_parallel:
        raise ValueError(f"{n} devices not divisible by model={model_parallel}")
    grid = mesh_utils.create_device_mesh(
        (n // model_parallel, model_parallel), devices=devices
    )
    return Mesh(grid, axis_names=("data", "model"))


def _path_str(path) -> str:
    return "/".join(
        str(getattr(p, "key", getattr(p, "name", getattr(p, "idx", p))))
        for p in path
    )


def _pspec(path, leaf) -> P:
    """Sharding rule: dense-layer weights split on ``model`` (tensor
    parallelism for the 4096-wide FC layers — AlexNet's parameter mass),
    everything else replicated.  Conv kernels are small; replicating them
    keeps their gradients a pure-DP psum."""
    name = _path_str(path)
    if "Dense" in name and name.endswith("kernel") and leaf.ndim == 2:
        return P(None, "model")
    if "Dense" in name and name.endswith("bias") and leaf.ndim == 1:
        return P("model")
    return P()


def tree_shardings(mesh: Mesh, tree):
    """NamedSharding pytree mirroring *tree* under the ``_pspec`` rule."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(mesh, _pspec(path, leaf)), tree
    )


def make_sharded_train_step(model: AlexNet, tx, mesh: Mesh, params, opt_state):
    """jit the full train step over *mesh*; returns (step_fn, placed_state).

    Batch is split on ``data``; params/opt_state follow ``_pspec``.  XLA
    derives every collective from these annotations — grads psum over
    ``data``, activations gather over ``model`` where needed.
    """
    param_sh = tree_shardings(mesh, params)
    opt_sh = tree_shardings(mesh, opt_state)
    img_sh = NamedSharding(mesh, P("data", None, None, None))
    lbl_sh = NamedSharding(mesh, P("data"))
    loss_sh = NamedSharding(mesh, P())

    params = jax.device_put(params, param_sh)
    opt_state = jax.device_put(opt_state, opt_sh)

    step = jax.jit(
        functools.partial(train_step, model, tx),
        in_shardings=(param_sh, opt_sh, img_sh, lbl_sh),
        out_shardings=(param_sh, opt_sh, loss_sh),
        donate_argnums=(0, 1),
    )
    return step, params, opt_state, (img_sh, lbl_sh)
