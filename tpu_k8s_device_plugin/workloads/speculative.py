"""Speculative decoding: a small draft model proposes, the target
verifies in one banded extend — exact greedy output, fewer
target-model passes.

Serving on TPU is weight-bandwidth-bound: each greedy step reads every
target weight once to emit ONE token.  Speculative decoding amortizes
that read: the draft (cheap) proposes ``gamma`` tokens autoregressively,
then the target scores all of them in a single ``CachedBlock`` extend
(``decode=True, T=gamma+...``) — one weight read for up to ``gamma+1``
emitted tokens.  With greedy acceptance the output is PROVABLY
identical to target-only greedy decoding (each accepted token equals
the target's argmax given the same prefix; the first mismatch is
replaced by the target's own argmax, exactly what plain greedy would
have emitted), which tests/test_speculative.py asserts token-for-token.

The rollback that acceptance needs is free in this engine: rejected
positions' K/V stay in the cache as garbage beyond ``cache_lens``
(reset by one scatter) and are overwritten by the next append — no
copies, no paging.

This is the serving-side counterpart of the reference's vLLM example
feature set (/root/reference/example/vllm-serve/deployment.yaml:28-56);
vLLM ships speculative decoding as a core serving optimization.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .inference import DecodeTransformerLM, extend_step, init_cache


@functools.partial(jax.jit, donate_argnums=(0,))
def _rollback(cache, new_len):
    """Reset every layer's cache_lens to *new_len* ([B] or scalar).
    K/V beyond the new length become dead rows the next append
    overwrites — rollback is one scatter, not a copy."""
    out = {}
    for layer, buf in cache.items():
        out[layer] = dict(buf)
        out[layer]["cache_lens"] = jnp.broadcast_to(
            jnp.asarray(new_len, jnp.int32), buf["cache_lens"].shape
        )
    return out


@functools.partial(jax.jit, static_argnums=(0, 2), donate_argnums=(3,))
def _draft_propose(model, params, gamma, cache, first, pos0):
    """Draft *gamma* tokens greedily from its own cache via lax.scan.
    Returns (proposed [1, gamma], cache after the proposals).

    The scan's g steps append K/V for [first, props[0..g-2]]; a final
    logit-discarded extend appends props[g-1] too, so the draft cache
    always covers every token that can end up committed (the
    all-accepted case needs props[g-1]'s row on the next round)."""

    def step(carry, _):
        cache, tok, pos = carry
        logits, mut = model.apply(
            {"params": params, "cache": cache},
            tok[:, None], pos[:, None], decode=True, mutable=["cache"],
        )
        nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
        return (mut["cache"], nxt, pos + 1), nxt

    (cache, last, pos), toks = lax.scan(
        step, (cache, first, pos0), None, length=gamma
    )
    _, mut = model.apply(
        {"params": params, "cache": cache},
        last[:, None], pos[:, None], decode=True, mutable=["cache"],
    )
    return toks.transpose(1, 0), mut["cache"]  # [1, gamma]


def speculative_generate(
    target: DecodeTransformerLM,
    target_params,
    draft: DecodeTransformerLM,
    draft_params,
    prompt: jax.Array,  # [T_p] or [1, T_p] int32
    n_steps: int,
    gamma: int = 4,
) -> Tuple[jax.Array, float]:
    """Greedy speculative decoding for a single sequence.

    Returns ``(generated [n_steps], accept_rate)`` where the tokens are
    bit-identical to ``greedy_generate(target, ...)`` and accept_rate
    is the fraction of draft proposals the target kept (a quality
    metric for the draft, not a correctness knob).
    """
    if gamma < 1:
        raise ValueError("gamma must be >= 1")
    prompt = jnp.asarray(prompt, jnp.int32).reshape(1, -1)
    t_p = int(prompt.shape[1])
    if t_p + n_steps > target.max_len:
        raise ValueError(
            f"prompt {t_p} + steps {n_steps} exceeds target max_len "
            f"{target.max_len}")
    if t_p + n_steps + gamma > draft.max_len:
        raise ValueError(
            f"draft max_len {draft.max_len} too small for prompt {t_p} "
            f"+ steps {n_steps} + gamma {gamma}")

    pos_p = jnp.arange(t_p, dtype=jnp.int32)[None, :]
    t_cache = init_cache(target, 1)
    d_cache = init_cache(draft, 1)
    t_logits, t_cache = extend_step(
        target, target_params, t_cache, prompt, pos_p)
    _, d_cache = extend_step(draft, draft_params, d_cache, prompt, pos_p)

    out = [int(jnp.argmax(t_logits[0, -1]))]
    produced = 1
    length = t_p  # committed tokens in both caches (excl. generated tail)
    proposed_total = 0
    accepted_total = 0

    # committed state: caches hold `length` positions; `out[-1]` is the
    # last committed token, not yet appended to either cache
    while produced < n_steps:
        # the entry guard (t_p + n_steps <= target.max_len) plus the
        # invariant length == t_p + produced - 1 gives
        # max_len - length - 1 >= n_steps - produced >= g, so the g+1
        # verify appends always fit the target cache
        g = min(gamma, n_steps - produced)
        first = jnp.asarray([out[-1]], jnp.int32)
        pos0 = jnp.asarray([length], jnp.int32)
        props, d_cache = _draft_propose(
            draft, draft_params, g, d_cache, first, pos0)

        # target verifies last-committed + proposals in ONE extend:
        # logits[t] is the target's next-token dist after seeing
        # out[-1], props[0..t-1]
        verify_toks = jnp.concatenate([first[:, None], props], axis=1)
        verify_pos = (
            jnp.arange(g + 1, dtype=jnp.int32) + length)[None, :]
        v_logits, t_cache = extend_step(
            target, target_params, t_cache, verify_toks, verify_pos)
        choices = np.asarray(
            jnp.argmax(v_logits[0], axis=-1), dtype=np.int32)  # [g+1]
        props_h = np.asarray(props[0], dtype=np.int32)

        n_acc = 0
        while n_acc < g and choices[n_acc] == props_h[n_acc]:
            n_acc += 1
        # accepted proposals + the target's own next token (the
        # correction at the first mismatch, or the bonus token when all
        # proposals were accepted)
        new_toks = [int(x) for x in props_h[:n_acc]] + [int(choices[n_acc])]
        new_toks = new_toks[: n_steps - produced]
        out.extend(new_toks)
        produced += len(new_toks)
        proposed_total += g
        accepted_total += n_acc

        # commit: both caches advance past out[-1]'s predecessors —
        # the target cache holds length + g + 1 appended rows, of which
        # (1 + n_acc) are committed (first + accepted proposals); the
        # draft also holds length + g + 1 (the scan's g appends plus
        # the final logit-discarded extend), same commit point
        length += 1 + n_acc
        t_cache = _rollback(t_cache, length)
        d_cache = _rollback(d_cache, length)

    rate = accepted_total / proposed_total if proposed_total else 0.0
    return jnp.asarray(out, jnp.int32), rate
