"""Shared HTTP load-generation client: the ONE stream-reading loop.

Before this module, bench_serving.py carried three hand-rolled copies
of the same client code (the single-replica load loop, the router
load loop, the disagg load loop) — each parsing the server's
JSON-lines stream frames, stamping a ``traceparent``, and timing
TTFT/TPOT slightly differently.  The replay harness
(:mod:`.replay`) would have been copy number four.  This module is
the single place for:

- **frame parsing** (:func:`parse_frame`): the hot coalesced window
  frame ``{"tokens":[...]}`` is counted by comma WITHOUT a full json
  parse — on shared CPU the load generator must not steal cycles from
  the engine it is measuring — while terminal ``done``/``error``
  frames (and the legacy per-token shape) parse fully,
- **SSE framing** (:func:`sse_data`): the OpenAI routes' wire shape,
- **traceparent stamping**: every request carries a client-chosen
  W3C trace context so the server-side spans are queryable by an id
  the CLIENT knows,
- **client behaviors** (:class:`ClientBehavior`): slow reading at N
  bytes/s, abandonment after T ms or after K tokens — the
  production-shaped misbehavior trafficgen traces encode and both
  bench and replay must execute identically,
- **terminal outcomes** (:class:`StreamOutcome`): ``ok``,
  ``abandoned`` (the client left — previously invisible on the
  client side), ``shed`` (429), ``error`` (in-band error frame or
  non-200), ``transport_error`` (socket died).

Stdlib + ``obs`` only (no jax): importable on a bare box, mypy
--strict like the router/kv_pool core.
"""
# tpulint: disable-file=R1 -- load-generation CLIENT: its raw HTTP calls MEASURE the serving stack (429s, drops, resets are data points, and the abandon behaviors DELIBERATELY break connections); a retry/breaker wrapper here would hide exactly the outcomes bench/replay exist to report

from __future__ import annotations

import http.client
import json
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from .. import obs

# terminal outcome vocabulary (bounded: safe as a metric label value)
OUTCOME_OK = "ok"
OUTCOME_ABANDONED = "abandoned"
OUTCOME_SHED = "shed"
OUTCOME_ERROR = "error"
OUTCOME_TRANSPORT = "transport_error"
OUTCOMES: Tuple[str, ...] = (
    OUTCOME_OK, OUTCOME_ABANDONED, OUTCOME_SHED, OUTCOME_ERROR,
    OUTCOME_TRANSPORT)

_TOKENS_FAST_PREFIX = b'{"tokens":['
_SSE_DATA_PREFIX = b"data: "
_SSE_DONE = b"[DONE]"


def parse_frame(line: bytes
                ) -> Tuple[int, Optional[Dict[str, object]]]:
    """One stripped JSON-lines stream frame -> ``(token_count,
    parsed_event)``.  The hot wire shape — the coalesced n>=1 window
    frame ``{"tokens":[a,b,...]}`` — is counted by comma instead of a
    full json parse and comes back with ``parsed_event=None``; every
    other frame (terminal ``done``/``error``, the legacy per-token
    ``{"token": t}``) parses fully.  Raises ValueError on frames that
    are not JSON objects (a malformed stream must fail loudly, not
    count as zero tokens)."""
    if line.startswith(_TOKENS_FAST_PREFIX) and line[-2:] == b"]}":
        return line.count(b",") + 1, None
    ev = json.loads(line)
    if not isinstance(ev, dict):
        raise ValueError(
            f"stream frame is not a JSON object: {line[:80]!r}")
    if "done" in ev or "error" in ev:
        return 0, ev
    toks = ev.get("tokens")
    if isinstance(toks, list):
        return len(toks), ev
    if "token" in ev:
        return 1, ev
    return 0, ev


def sse_data(line: bytes) -> Optional[bytes]:
    """The JSON payload of one SSE line, or None for non-data framing
    (``event:``/``id:`` fields, comments, blank lines) and the
    ``[DONE]`` sentinel — the OpenAI routes' framing in one place."""
    if not line.startswith(_SSE_DATA_PREFIX):
        return None
    payload = line[len(_SSE_DATA_PREFIX):].strip()
    if not payload or payload == _SSE_DONE:
        return None
    return payload


@dataclass(frozen=True)
class ClientBehavior:
    """How the client consumes its response — the production-shaped
    misbehaviors a trace encodes.  ``read_bytes_per_s`` throttles the
    read loop (a slow reader backs the server's bounded event queue
    up); ``abandon_after_ms`` closes the connection that many ms
    after the request started; ``abandon_after_tokens`` closes it
    after the K-th streamed token (bench's historical
    ``--cancel-every`` posture).  Zero disables each."""

    stream: bool = True
    read_bytes_per_s: int = 0
    abandon_after_ms: float = 0.0
    abandon_after_tokens: int = 0


@dataclass
class StreamOutcome:
    """One request as the wire saw it.  ``outcome`` is one of
    :data:`OUTCOMES`; ``tokens`` counts streamed token frames,
    ``done_tokens`` the terminal frame's full token list (0 unless
    the stream completed).  ``ttft_s``/``tpot_s`` are None when no
    token (or no second token) ever arrived."""

    status: int
    outcome: str
    total_s: float
    ttft_s: Optional[float] = None
    tpot_s: Optional[float] = None
    tokens: int = 0
    done_tokens: int = 0
    error: Optional[str] = None
    replica: Optional[str] = None
    trace_id: Optional[str] = None


def _headers(trace: obs.TraceContext,
             extra: Optional[Dict[str, str]]) -> Dict[str, str]:
    out = {"Content-Type": "application/json",
           "traceparent": trace.to_traceparent()}
    if extra:
        out.update(extra)
    return out


def stream_request(host: str, port: int, body: Dict[str, object], *,
                   path: str = "/generate",
                   behavior: Optional[ClientBehavior] = None,
                   trace: Optional[obs.TraceContext] = None,
                   timeout_s: float = 600.0,
                   headers: Optional[Dict[str, str]] = None
                   ) -> StreamOutcome:
    """One streaming POST with the behaviors applied.  Never raises
    on request-level failure: sheds, in-band error frames, transport
    resets, and deliberate abandonment all come back as a terminal
    :class:`StreamOutcome` — clean-looking numbers over a broken run
    would be worse than no numbers."""
    beh = behavior if behavior is not None else ClientBehavior()
    tr = trace if trace is not None else obs.new_trace()
    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    t0 = time.perf_counter()
    first: Optional[float] = None
    last: Optional[float] = None
    n_toks = 0
    done_tokens = 0
    abandoned = False
    error: Optional[str] = None
    status = -1
    replica: Optional[str] = None
    saw_done = False
    try:
        conn.request("POST", path, json.dumps(body),
                     _headers(tr, headers))
        resp = conn.getresponse()
        status = resp.status
        replica = resp.headers.get("X-Replica")
        if status != 200:
            payload = resp.read(4096)
            try:
                ev = json.loads(payload)
                if isinstance(ev, dict) and "error" in ev:
                    error = str(ev["error"])
            except ValueError:
                error = f"unparseable {status} body: {payload[:80]!r}"
            return StreamOutcome(
                status=status,
                outcome=OUTCOME_SHED if status == 429
                else OUTCOME_ERROR,
                total_s=time.perf_counter() - t0, error=error,
                replica=replica, trace_id=tr.trace_id)
        bytes_read = 0
        for line in resp:
            s = line.strip()
            if not s:
                continue
            now = time.perf_counter()
            if beh.read_bytes_per_s > 0:
                # slow reader: cap the cumulative drain rate — sleep
                # until the bytes read so far fit under the budget
                bytes_read += len(line)
                floor = bytes_read / beh.read_bytes_per_s
                if floor > now - t0:
                    time.sleep(floor - (now - t0))
                    now = time.perf_counter()
            if beh.abandon_after_ms > 0 \
                    and (now - t0) * 1000.0 >= beh.abandon_after_ms:
                abandoned = True
                break
            k, ev = parse_frame(s)
            if k:
                n_toks += k
                last = now
                if first is None:
                    first = now
                if beh.abandon_after_tokens \
                        and n_toks >= beh.abandon_after_tokens:
                    abandoned = True
                    break
            elif ev is not None and "error" in ev:
                error = str(ev["error"])
                break
            elif ev is not None and "done" in ev:
                toks = ev.get("tokens")
                done_tokens = len(toks) if isinstance(toks, list) \
                    else n_toks
                saw_done = True
    except OSError as e:
        return StreamOutcome(
            status=status, outcome=OUTCOME_TRANSPORT,
            total_s=time.perf_counter() - t0, tokens=n_toks,
            ttft_s=None if first is None else first - t0,
            error=str(e), replica=replica, trace_id=tr.trace_id)
    finally:
        conn.close()
    total_s = time.perf_counter() - t0
    ttft_s = None if first is None else first - t0
    tpot_s = None
    if first is not None and last is not None and n_toks > 1 \
            and last > first:
        tpot_s = (last - first) / (n_toks - 1)
    if abandoned:
        outcome = OUTCOME_ABANDONED
    elif error is not None:
        outcome = OUTCOME_ERROR
    elif saw_done:
        outcome = OUTCOME_OK
    else:
        # headers + frames but no terminal frame: a truncated stream
        # (e.g. the upstream replica died without an error frame)
        outcome = OUTCOME_ERROR
        error = "stream ended without a terminal frame"
    return StreamOutcome(
        status=status, outcome=outcome, total_s=total_s,
        ttft_s=ttft_s, tpot_s=tpot_s, tokens=n_toks,
        done_tokens=done_tokens, error=error, replica=replica,
        trace_id=tr.trace_id)


def unary_request(host: str, port: int, body: Dict[str, object], *,
                  path: str = "/generate",
                  trace: Optional[obs.TraceContext] = None,
                  timeout_s: float = 600.0,
                  headers: Optional[Dict[str, str]] = None
                  ) -> StreamOutcome:
    """One unary (``stream: false``) POST: single JSON body back.
    Same terminal-outcome contract as :func:`stream_request`; TTFT is
    None (nothing streams), the deadline-class SLO judges total_s."""
    tr = trace if trace is not None else obs.new_trace()
    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    t0 = time.perf_counter()
    status = -1
    replica: Optional[str] = None
    try:
        conn.request("POST", path, json.dumps(body),
                     _headers(tr, headers))
        resp = conn.getresponse()
        status = resp.status
        replica = resp.headers.get("X-Replica")
        payload = resp.read()
    except OSError as e:
        return StreamOutcome(
            status=status, outcome=OUTCOME_TRANSPORT,
            total_s=time.perf_counter() - t0, error=str(e),
            replica=replica, trace_id=tr.trace_id)
    finally:
        conn.close()
    total_s = time.perf_counter() - t0
    error: Optional[str] = None
    done_tokens = 0
    try:
        ev = json.loads(payload)
    except ValueError:
        ev = None
        error = f"unparseable body: {payload[:80]!r}"
    if isinstance(ev, dict):
        if "error" in ev:
            error = str(ev["error"])
        else:
            toks = ev.get("tokens")
            done_tokens = len(toks) if isinstance(toks, list) else 0
    if status == 429:
        outcome = OUTCOME_SHED
    elif status == 200 and error is None:
        outcome = OUTCOME_OK
    else:
        outcome = OUTCOME_ERROR
    return StreamOutcome(
        status=status, outcome=outcome, total_s=total_s,
        done_tokens=done_tokens, error=error, replica=replica,
        trace_id=tr.trace_id)


def free_port(host: str = "127.0.0.1") -> int:
    """An OS-assigned free TCP port (the bench/replay spawn helper)."""
    import socket

    s = socket.socket()
    s.bind((host, 0))
    port = int(s.getsockname()[1])
    s.close()
    return port


def wait_http_ok(port: int, path: str, timeout_s: float,
                 predicate: Optional[
                     Callable[[Dict[str, object]], bool]] = None,
                 host: str = "127.0.0.1") -> bool:
    """Poll ``GET path`` until 200 (and *predicate*(parsed JSON body)
    when given).  Raises RuntimeError with the last status on
    timeout — a replica that never came up must fail the run, not
    hang it."""
    deadline = time.monotonic() + timeout_s
    last: Optional[Tuple[int, bytes]] = None
    while time.monotonic() < deadline:
        try:
            conn = http.client.HTTPConnection(host, port, timeout=5)
            conn.request("GET", path)
            resp = conn.getresponse()
            payload = resp.read()
            conn.close()
            last = (resp.status, payload[:120])
            if resp.status == 200:
                if predicate is None:
                    return True
                parsed = json.loads(payload)
                if isinstance(parsed, dict) and predicate(parsed):
                    return True
        except (OSError, ValueError):
            # boot races: connection refused / partial JSON while the
            # server is still coming up — the loop IS the handling
            # (the deadline raises below), nothing to account per poll
            pass
        time.sleep(0.25)
    raise RuntimeError(f"{path} on :{port} not ready within "
                       f"{timeout_s}s (last: {last})")


def fetch_json(port: int, path: str, timeout_s: float = 30.0,
               host: str = "127.0.0.1") -> Dict[str, object]:
    """One GET returning a parsed JSON object (raises on non-dict /
    transport failure: callers want the surface or an error, never a
    silent empty)."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    try:
        conn.request("GET", path)
        payload = conn.getresponse().read()
    finally:
        conn.close()
    out = json.loads(payload)
    if not isinstance(out, dict):
        raise ValueError(f"{path} returned non-object JSON")
    return out


def fetch_trace_events(port: int, trace_id: str,
                       timeout_s: float = 30.0,
                       host: str = "127.0.0.1"
                       ) -> List[Dict[str, object]]:
    """One trace's server-side events from ``/debug/traces`` — flat
    for a single replica, flattened from the stitched ``tree`` shape
    when the endpoint is a router."""
    from urllib.parse import quote

    body = fetch_json(
        port, f"/debug/traces?trace_id={quote(trace_id, safe='')}",
        timeout_s=timeout_s, host=host)
    events = body.get("events")
    if isinstance(events, list):
        return [e for e in events if isinstance(e, dict)]
    tree = body.get("tree")
    if isinstance(tree, list):
        return obs.flatten(tree)
    return []
