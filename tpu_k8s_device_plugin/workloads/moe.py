"""Mixture-of-experts FFN with expert parallelism, TPU-first.

The reference delegates every parallelism strategy to its workload images
(SURVEY.md §2.4 — DP/TP/PP/SP/EP all "absent; parallelism lives in
workloads"); this build ships the workload layer natively, and this module
is the expert-parallel (EP) member of that set.

TPU-first design constraints drive the whole shape:

* **Static shapes only.**  Token routing is data-dependent, which XLA
  cannot tile; the classic TPU answer (GShard/Switch, public technique)
  is *dense dispatch*: a fixed per-expert capacity ``C`` and one-hot
  dispatch/combine tensors, so every einsum has a static shape and the
  MXU sees large batched matmuls (`jnp.einsum` over an ``E``-leading
  expert weight stack) instead of gather/scatter.
* **EP via sharding annotations, not hand-written all-to-all.**  Expert
  weight stacks ``[E, D, F]`` are sharded on an ``expert`` mesh axis
  (`transformer._lm_pspec`); tokens arrive data-sharded.  XLA's SPMD
  partitioner derives the dispatch/combine all-to-alls from those two
  annotations — the scaling-book recipe, no NCCL analog anywhere
  (SURVEY.md §5 "distributed communication backend").
* **Router math in f32** (softmax + top-k on bf16 logits loses routing
  determinism); expert matmuls in the model's compute dtype (bf16).

Capacity overflow drops tokens (they ride the residual connection, the
standard Switch behavior); the Switch load-balancing auxiliary loss is
sown into the ``losses`` collection so ``transformer.lm_loss`` can add it
without threading an extra return value through every layer.
"""

from __future__ import annotations

import math
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from .transformer import COMPUTE_DTYPE  # single compute-dtype knob


def moe_capacity(
    tokens: int, n_experts: int, k: int, capacity_factor: float
) -> int:
    """Per-expert capacity slots: ceil(k·T/E · factor), at least 1."""
    return max(1, math.ceil(k * tokens / n_experts * capacity_factor))


def _top_k_gates(router_logits: jax.Array, k: int):
    """Shared gate computation: softmax probs, top-k (distinct experts),
    and per-token renormalized gate values — the single source of truth
    for both the dense dispatch plan and the single-token serving path,
    so the two routes agree exactly."""
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, k)
    # renormalize the kept gates so the combine weights sum to 1 per token
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9
    )
    return probs, gate_vals, gate_idx


def _aux_loss(probs: jax.Array, gate_idx: jax.Array, k: int) -> jax.Array:
    """Switch load-balancing loss from probs + chosen experts (shared by
    both paths): E · Σ_e route-fraction(e) · mean-prob(e)."""
    E = probs.shape[-1]
    choice_onehot = jax.nn.one_hot(gate_idx, E, dtype=jnp.float32)
    route_frac = jnp.mean(jnp.sum(choice_onehot, axis=2), axis=(0, 1)) / k
    prob_mean = jnp.mean(probs, axis=(0, 1))
    return E * jnp.sum(route_frac * prob_mean)


def top_k_routing(
    router_logits: jax.Array,  # [B, T, E] (any float dtype; cast to f32)
    k: int,
    capacity: int,
    priority: Optional[jax.Array] = None,  # [B, T] lower claims slots first
):
    """Dense top-k dispatch plan from router logits.

    Returns ``(dispatch, combine, aux_loss)`` where

    * ``dispatch`` — [B, T, E, C] one-hot: token t occupies capacity slot
      c of expert e (at most k ones per token, fewer when an expert
      overflows its capacity),
    * ``combine`` — same shape, dispatch weighted by the token's
      normalized gate value for that expert,
    * ``aux_loss`` — the Switch load-balancing loss
      E · Σ_e (token fraction routed to e) · (mean router prob of e),
      which is 1.0 at perfect balance.

    Capacity slots are granted in token order, earlier choice ranks
    first — deterministic and shape-static, so the whole plan jits.
    *priority* overrides the token order (lower value = earlier claim):
    the LM passes its *positions* array so overflow drops the same tokens
    no matter how the sequence is laid out in storage — without it, the
    zig-zag ring-attention layout (sequence permuted at ingress) would
    silently route/drop a different token subset than the natural-order
    model.
    """
    B, T, E = router_logits.shape
    probs, gate_vals, gate_idx = _top_k_gates(router_logits, k)

    # Flatten (token, choice) in priority order: token-major, then choice
    # rank — token 0's 2nd choice beats token 1's 1st for a capacity slot
    # iff it comes earlier in this flattened order.  (Choice-rank-major
    # within a token keeps top-1 routes from being starved by later
    # tokens' top-1s no matter what; token-major is the simpler, standard
    # layout and the difference washes out at realistic capacities.)
    choice_onehot = jax.nn.one_hot(gate_idx, E, dtype=jnp.float32)
    if priority is not None:
        # queue-position computation in priority order, results scattered
        # back to storage order (argsort is stable, shapes stay static)
        order = jnp.argsort(priority, axis=1)  # [B, T]
        inv = jnp.argsort(order, axis=1)

        def by_token(a, idx):
            return jnp.take_along_axis(a, idx[:, :, None, None], axis=1)

        flat_sorted = by_token(choice_onehot, order).reshape(B, T * k, E)
        pos_sorted = jnp.cumsum(flat_sorted, axis=1) - flat_sorted
        pos = by_token(
            pos_sorted.reshape(B, T, k, E), inv
        ).reshape(B, T * k, E)
    else:
        flat_sorted = choice_onehot.reshape(B, T * k, E)
        pos = jnp.cumsum(flat_sorted, axis=1) - flat_sorted
    flat = choice_onehot.reshape(B, T * k, E)
    # pos = position of each (token, choice) in its expert's queue.  Each
    # route targets exactly one expert, so reduce E out *before* building
    # the capacity one-hot — the intermediate is [B, T, k, C], a factor E
    # smaller than the naive [B, T, k, E, C] slot tensor.
    pos_route = jnp.sum(pos * flat, axis=-1)  # [B, T*k]
    kept = jnp.sum((pos < capacity) * flat, axis=-1)  # [B, T*k] ∈ {0, 1}
    slot_route = (
        jax.nn.one_hot(pos_route.astype(jnp.int32), capacity)
        * kept[..., None]
    ).reshape(B, T, k, capacity)
    dispatch = jnp.einsum(
        "btke,btkc->btec", choice_onehot, slot_route
    )  # [B, T, E, C]
    combine = jnp.einsum(
        "btke,btkc->btec", choice_onehot,
        slot_route * gate_vals[..., None].astype(jnp.float32),
    )

    return dispatch, combine, _aux_loss(probs, gate_idx, k)


class MoEFFN(nn.Module):
    """Top-k routed expert FFN, drop-in for the dense MLP in a
    transformer block: ``[B, T, D] -> [B, T, D]``.

    Expert weights are stacked with a leading ``E`` axis (``experts_up``
    [E, D, F], ``experts_down`` [E, F, D]) so the per-expert matmuls are
    two batched einsums — the layout the ``expert`` mesh axis shards
    (see ``transformer._lm_pspec``).  The aux loss is sown into the
    ``losses`` collection (scaled by ``aux_weight``).
    """

    n_experts: int
    d_model: int
    d_ff: int
    k: int = 2
    capacity_factor: float = 1.25
    capacity: Optional[int] = None  # explicit override (tests/oracles)
    aux_weight: float = 1e-2
    dtype: Any = COMPUTE_DTYPE
    quantized: bool = False  # serving: int8 expert stacks + f32 scales

    def _expert_weights(self, E: int, D: int, F: int):
        """Expert stacks in one of two layouts: trained f32 (default) or
        weight-only int8 with per-(expert, output-channel) f32 scales
        (``quantized`` — serving; convert a trained tree with
        ``inference.quantize_lm_params``, which converts expert stacks
        unconditionally alongside the projections).
        Returns ``(w_up, w_down, up_scale, down_scale)`` where the
        scales are None in the unquantized layout."""
        if not self.quantized:
            w_up = self.param(
                "experts_up",
                nn.initializers.lecun_normal(batch_axis=(0,)),
                (E, D, F),
                jnp.float32,
            )
            w_down = self.param(
                "experts_down",
                nn.initializers.lecun_normal(batch_axis=(0,)),
                (E, F, D),
                jnp.float32,
            )
            return w_up, w_down, None, None
        zeros_i8 = lambda rng, shape: jnp.zeros(shape, jnp.int8)  # noqa: E731
        ones_f32 = lambda rng, shape: jnp.ones(shape, jnp.float32)  # noqa: E731
        return (
            self.param("experts_up_int8", zeros_i8, (E, D, F)),
            self.param("experts_down_int8", zeros_i8, (E, F, D)),
            self.param("experts_up_scale", ones_f32, (E, F)),
            self.param("experts_down_scale", ones_f32, (E, D)),
        )

    @nn.compact
    def __call__(
        self, x: jax.Array, positions: Optional[jax.Array] = None
    ) -> jax.Array:
        B, T, D = x.shape
        E, F = self.n_experts, self.d_ff
        cap = (
            self.capacity
            if self.capacity is not None
            else moe_capacity(T, E, self.k, self.capacity_factor)
        )

        # router in f32 end-to-end; tiny [D, E] matmul, not MXU-bound
        w_router = self.param(
            "router", nn.initializers.lecun_normal(), (D, E), jnp.float32
        )
        logits = jnp.einsum("btd,de->bte", x.astype(jnp.float32), w_router)

        w_up, w_down, up_scale, down_scale = self._expert_weights(E, D, F)

        if T == 1 and B * self.k <= E:
            # Single-token serving path (decode steps): gather ONLY the k
            # routed experts' stacks instead of streaming all E through
            # the dense dispatch — at T=1 every route keeps its slot
            # (dropless), so this is exactly the dense result at k/E of
            # the weight HBM traffic.  Only taken while B·k ≤ E: the
            # gather materializes per-token weight copies [B, k, D, F],
            # so past that point dense dispatch reads fewer bytes.  All
            # of B/T/k/E are static, so the branch resolves at trace
            # time; training (T > 1) never takes it.
            probs, gate_vals, gate_idx = _top_k_gates(logits, self.k)
            self.sow(
                "losses", "moe_aux",
                self.aux_weight * _aux_loss(probs, gate_idx, self.k),
            )
            idx = gate_idx[:, 0]  # [B, k]
            up_sel = w_up[idx].astype(self.dtype)      # [B, k, D, F]
            down_sel = w_down[idx].astype(self.dtype)  # [B, k, F, D]
            x_tok = x[:, 0].astype(self.dtype)         # [B, D]
            h = jnp.einsum("bd,bkdf->bkf", x_tok, up_sel)
            if up_scale is not None:  # dequant on the dot output, f32
                h = (h * up_scale[idx]).astype(self.dtype)
            h = nn.gelu(h)
            out = jnp.einsum("bkf,bkfd->bkd", h, down_sel)
            if down_scale is not None:
                out = (out * down_scale[idx]).astype(self.dtype)
            y = jnp.einsum(
                "bk,bkd->bd", gate_vals[:, 0],
                out.astype(jnp.float32),
            )
            return y[:, None].astype(x.dtype)

        dispatch, combine, aux = top_k_routing(
            logits, self.k, cap, priority=positions
        )
        self.sow("losses", "moe_aux", self.aux_weight * aux)

        # dense dispatch → batched expert matmuls → weighted combine.
        # [B,T,E,C]×[B,T,D] → [B,E,C,D]: with tokens data-sharded and
        # experts expert-sharded, XLA lowers this contraction pair to the
        # EP all-to-all.
        xin = jnp.einsum(
            "btec,btd->becd", dispatch.astype(self.dtype), x.astype(self.dtype)
        )
        h = jnp.einsum("becd,edf->becf", xin, w_up.astype(self.dtype))
        if up_scale is not None:  # dequant on the dot output, f32
            h = (h * up_scale[None, :, None, :]).astype(self.dtype)
        h = nn.gelu(h)
        out = jnp.einsum("becf,efd->becd", h, w_down.astype(self.dtype))
        if down_scale is not None:
            out = (out * down_scale[None, :, None, :]).astype(self.dtype)
        y = jnp.einsum(
            "btec,becd->btd", combine.astype(jnp.float32),
            out.astype(jnp.float32),
        )
        return y.astype(x.dtype)


def moe_ffn_oracle(params, x, k: int, capacity: Optional[int] = None):
    """Per-token reference implementation (no dense dispatch): each token
    runs through its top-k experts directly, gates renormalized — the
    correctness oracle for :class:`MoEFFN` when no token exceeds
    capacity.  f32 throughout."""
    w_router = params["router"]
    w_up = params["experts_up"].astype(jnp.float32)
    w_down = params["experts_down"].astype(jnp.float32)
    B, T, D = x.shape
    xf = x.astype(jnp.float32)
    logits = jnp.einsum("btd,de->bte", xf, w_router)
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, k)
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9
    )
    # every token through every expert, then select (oracle-only cost)
    h = jnp.einsum("btd,edf->betf", xf, w_up)
    h = jax.nn.gelu(h)
    all_out = jnp.einsum("betf,efd->betd", h, w_down)  # [B, E, T, D]
    sel = jnp.take_along_axis(
        jnp.moveaxis(all_out, 1, 2),  # [B, T, E, D]
        gate_idx[..., None, None].repeat(D, -1).reshape(B, T, k, D),
        axis=2,
    )  # -> [B, T, k, D]
    return jnp.einsum("btk,btkd->btd", gate_vals, sel)
