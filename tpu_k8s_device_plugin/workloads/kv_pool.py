# tpulint: deterministic-path -- the free-list fuzz replays allocator decisions from seeds; D1 bans bare random/time.time() here
"""Host-side page-pool allocator for the paged KV cache.

The vLLM PagedAttention bookkeeping, host-only: the serving engine's
KV storage becomes a ``[P, page_size, Hkv, Dh]`` physical pool per
layer plus a per-slot ``[S, max_len/page_size]`` int32 block table,
and THIS module owns every allocation decision — a free list, per-page
reference counts, and copy-on-write semantics for shared prefixes.
No JAX imports: all device data movement (page copies, splices,
gathers) stays in serving.py's jitted helpers; the allocator is pure
deterministic host state, which is what makes it unit/fuzz-testable
at C speed and lets mypy --strict cover it.

Sharing model (RadixAttention-lite, adapted to the engine's fixed
chunk grid):

* a **block-table entry** maps one logical page of a slot's sequence
  to a physical page; ``SCRATCH`` (= ``n_pages``, one extra physical
  page every pool carries) marks an unmapped entry.  Decode writes of
  parked slots clamp into mapped tail entries or SCRATCH, mirroring
  the contiguous engine's clamped-write band — SCRATCH absorbs the
  garbage nothing ever reads.
* ``refs[p]`` counts block-table entries (across all slots) that map
  physical page ``p``.  An entry is **writable** only while it is the
  page's sole reference; appending into a shared page first pays a
  :meth:`cow` — allocate a fresh page, (caller copies the device
  data), swap the entry — so a reader of the shared page never sees a
  neighbor's writes.
* released slots KEEP their mappings: the resident-prompt donor
  record pins pages through the table itself (no separate pin count),
  which also means eviction of a donor record is just
  :meth:`clear_slot`.

Everything is deterministic: the free list is LIFO over a fixed
initial order, so identical call sequences produce identical tables —
the property the ENGINE_FUZZ_SEED sweep and the paged-vs-contiguous
equivalence suite replay.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np


class PagePoolExhausted(RuntimeError):
    """No free page satisfies the request.  The serving layer turns
    this into policy: reclaim parked donor pages, preempt a
    lower-priority slot (checkpoint its pages to host), or 429."""


class PagePool:
    """Free-list page allocator + per-slot block tables.

    Pure host state; device pools are indexed BY this object's
    ``tables`` array (mirrored to the device by the engine whenever
    ``dirty`` flips).  Single-threaded by contract, like the engine
    that owns it.
    """

    def __init__(self, n_pages: int, page_size: int, n_slots: int,
                 max_len: int) -> None:
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        if max_len % page_size:
            raise ValueError(
                f"page_size {page_size} must divide max_len {max_len} "
                "(a divisor is what keeps padded admission from "
                "overflowing the table)")
        n_tables = max_len // page_size
        if n_pages < n_tables:
            raise ValueError(
                f"pool of {n_pages} pages cannot hold even one "
                f"full-length sequence ({n_tables} pages)")
        if n_slots < 1:
            raise ValueError("n_slots must be >= 1")
        self.page_size = page_size
        self.n_pages = n_pages
        self.n_slots = n_slots
        self.n_tables = n_tables
        #: the one physical page garbage writes land in and unmapped
        #: entries point at (pool arrays are sized n_pages + 1)
        self.scratch = n_pages
        self.tables = np.full((n_slots, n_tables), self.scratch,
                              np.int32)
        self.refs = np.zeros(n_pages, np.int32)
        # LIFO free list over a fixed order: pop() hands out 0, 1, 2…
        # first, and frees return to the top — deterministic for the
        # fuzz suite, and recently-touched pages (warm in cache) are
        # reused first
        self._free: List[int] = list(range(n_pages - 1, -1, -1))
        #: device block-table mirror is stale (engine re-uploads)
        self.dirty = True
        #: copy-on-write page copies performed (engine-observed too,
        #: but the pool is the single source of truth for the count)
        self.cow_copies = 0

    # -- queries ------------------------------------------------------------

    def free_pages(self) -> int:
        return len(self._free)

    def shared_pages(self) -> int:
        """Physical pages referenced by more than one table entry —
        the storage the prefix sharing is actually deduplicating."""
        return int((self.refs > 1).sum())

    def used_pages(self) -> int:
        return self.n_pages - len(self._free)

    def entry(self, slot: int, idx: int) -> int:
        return int(self.tables[slot, idx])

    def mapped(self, slot: int) -> List[Tuple[int, int]]:
        """All (logical idx, physical page) mappings of *slot*."""
        row = self.tables[slot]
        return [(int(i), int(row[i])) for i in
                np.flatnonzero(row != self.scratch)]

    def writable(self, slot: int, idx: int) -> bool:
        """True when the entry maps a page this slot may write: mapped
        and sole-referenced."""
        p = int(self.tables[slot, idx])
        return p != self.scratch and int(self.refs[p]) == 1

    def pages_for(self, start: int, end: int) -> range:
        """Logical page indices covering token positions
        [*start*, *end*)."""
        if end <= start:
            return range(0)
        return range(start // self.page_size,
                     (end - 1) // self.page_size + 1)

    def pages_needed(self, tokens: int) -> int:
        """Physical pages a *tokens*-long sequence occupies — the
        capacity arithmetic resume and /migrate admission share."""
        return (tokens + self.page_size - 1) // self.page_size

    # -- allocation ---------------------------------------------------------

    def alloc(self) -> int:
        """Pop a free page (refcount 1 on mapping — alloc itself hands
        out an unreferenced page; pair with :meth:`map`)."""
        if not self._free:
            raise PagePoolExhausted(
                f"all {self.n_pages} KV pages in use")
        return self._free.pop()

    def give_back(self, page: int) -> None:
        """Return a page obtained from :meth:`alloc` that was never
        mapped (a multi-page reservation failed partway)."""
        if int(self.refs[page]) != 0:
            raise RuntimeError(
                f"give_back: page {page} is referenced")
        self._free.append(page)

    def map(self, slot: int, idx: int, page: int) -> None:
        """Install *page* at (*slot*, *idx*).  The entry must be
        unmapped (SCRATCH) — remapping without an unmap is how leaks
        happen, so it is an error here."""
        if int(self.tables[slot, idx]) != self.scratch:
            raise RuntimeError(
                f"entry ({slot}, {idx}) already mapped to "
                f"{int(self.tables[slot, idx])}")
        self.tables[slot, idx] = page
        self.refs[page] += 1
        self.dirty = True

    def unmap(self, slot: int, idx: int) -> None:
        """Drop one mapping; the page returns to the free list when
        its last reference goes."""
        p = int(self.tables[slot, idx])
        if p == self.scratch:
            return
        self.tables[slot, idx] = self.scratch
        self.refs[p] -= 1
        if int(self.refs[p]) < 0:
            raise RuntimeError(f"page {p} refcount underflow")
        if int(self.refs[p]) == 0:
            self._free.append(p)
        self.dirty = True

    def share(self, src_slot: int, n_pages: int) -> List[int]:
        """Take an extra reference on *src_slot*'s first *n_pages*
        mapped pages (a prefix share) and return them IN ORDER.  The
        caller installs them into the destination slot with
        :meth:`map_shared` AFTER clearing the destination — the
        incref-first order is what makes sharing from the destination
        slot itself (re-admitting a prompt over its own donor pages)
        safe."""
        pages: List[int] = []
        for idx in range(n_pages):
            p = int(self.tables[src_slot, idx])
            if p == self.scratch:
                raise RuntimeError(
                    f"share: donor slot {src_slot} has no page at "
                    f"logical index {idx}")
            self.refs[p] += 1
            pages.append(p)
        return pages

    def unshare(self, pages: List[int]) -> None:
        """Release references taken by :meth:`share` that were never
        installed (an admission aborted between begin and finish)."""
        for p in pages:
            self.refs[p] -= 1
            if int(self.refs[p]) < 0:
                raise RuntimeError(f"page {p} refcount underflow")
            if int(self.refs[p]) == 0:
                self._free.append(p)
        if pages:
            self.dirty = True

    def map_shared(self, slot: int, pages: List[int]) -> None:
        """Install prefix pages (reference already counted by
        :meth:`share`) at logical indices 0..len-1 of *slot*."""
        for idx, p in enumerate(pages):
            if int(self.tables[slot, idx]) != self.scratch:
                raise RuntimeError(
                    f"map_shared: entry ({slot}, {idx}) occupied")
            self.tables[slot, idx] = p
        if pages:
            self.dirty = True

    def cow(self, slot: int, idx: int, new_page: int) -> int:
        """Swap a SHARED entry for freshly-allocated *new_page* (the
        caller has already copied the device data old → new).  Returns
        the old page.  Counts the copy."""
        old = int(self.tables[slot, idx])
        if old == self.scratch:
            raise RuntimeError(f"cow: entry ({slot}, {idx}) unmapped")
        if int(self.refs[old]) <= 1:
            raise RuntimeError(
                f"cow: page {old} is not shared (write in place)")
        self.tables[slot, idx] = new_page
        self.refs[new_page] += 1
        self.refs[old] -= 1
        self.cow_copies += 1
        self.dirty = True
        return old

    def clear_slot(self, slot: int) -> None:
        """Unmap every entry of *slot* (re-admission / donor-record
        eviction / preemption).  Pages drop to the free list as their
        last references go."""
        row = self.tables[slot]
        for idx in np.flatnonzero(row != self.scratch):
            self.unmap(slot, int(idx))

    # -- invariants ---------------------------------------------------------

    def check(self) -> None:
        """Integrity oracle for the fuzz suite: refcounts equal table
        occurrences, the free list is exactly the zero-ref pages with
        no duplicates, and no table entry escapes the pool."""
        if self.tables.min() < 0 or self.tables.max() > self.scratch:
            raise AssertionError("table entry outside the pool")
        counts: Dict[int, int] = {}
        for p in self.tables.ravel().tolist():
            if p != self.scratch:
                counts[p] = counts.get(p, 0) + 1
        for p in range(self.n_pages):
            if counts.get(p, 0) != int(self.refs[p]):
                raise AssertionError(
                    f"page {p}: refs={int(self.refs[p])} but "
                    f"{counts.get(p, 0)} table references")
        free = set(self._free)
        if len(free) != len(self._free):
            raise AssertionError("duplicate pages in the free list")
        zero = {p for p in range(self.n_pages)
                if int(self.refs[p]) == 0}
        if free != zero:
            raise AssertionError(
                f"free list {sorted(free)} != zero-ref pages "
                f"{sorted(zero)}")

    def stats(self) -> Dict[str, int]:
        # "kv_pages" (not *_total): these bridge to /metrics as
        # GAUGES, and promlint reserves the _total suffix for counters
        return {
            "kv_pages": self.n_pages,
            "kv_pages_free": self.free_pages(),
            "kv_pages_shared": self.shared_pages(),
            "kv_page_size": self.page_size,
            "kv_cow_copies": self.cow_copies,
        }
