# tpulint: deterministic-path -- WFQ/quota decisions are replayed by the QoS suites; D1 bans bare random here (time.monotonic is the bucket clock by design)
"""Tenant QoS primitives shared by the serving replica AND the router.

``TenantQuota`` (a token bucket over estimated tokens plus a WFQ
weight) started life inside ``workloads.server``; the router tier
needs the identical bucket semantics for GLOBAL quota enforcement —
a tenant spread evenly over N replicas used to get RATE x N because
each replica's bucket was its own little world.  The router cannot
import ``server`` (that module pulls in jax at import time; the
router runs on 1-vCPU sidecars), so the primitives live here:
stdlib + nothing, importable from both sides, mypy --strict.

The grammar is shared too: ``name=rate[:burst[:weight]]``, repeatable,
with ``*`` as the template for unknown tenants (each unknown tenant
gets its OWN bucket cloned from the template — shared state would let
one tenant drain another's budget).
"""

from __future__ import annotations

import time
from typing import Dict, Iterable, Optional

__all__ = ["TenantQuota", "parse_tenant_quotas", "resolve_quota"]


class TenantQuota:
    """Per-tenant QoS config: a token-rate budget (token bucket over
    ESTIMATED tokens — prompt + requested budget — charged at
    admission) and a WFQ weight.  ``rate <= 0`` disables the bucket
    (weight-only tenants); ``weight`` scales the tenant's share of
    the admission heap under contention."""

    __slots__ = ("rate", "burst", "weight", "tokens", "stamp",
                 "_last_vft")

    def __init__(self, rate: float, burst: Optional[float] = None,
                 weight: float = 1.0):
        if weight <= 0:
            raise ValueError("tenant weight must be > 0")
        self.rate = float(rate)
        self.burst = float(burst if burst is not None
                           else max(rate, 1.0))
        self.weight = float(weight)
        self.tokens = self.burst       # bucket starts full
        self.stamp = time.monotonic()
        self._last_vft = 0.0           # WFQ backlog marker

    def try_charge(self, cost: float) -> bool:
        """Refill-then-charge; False = over quota (shed with 429)."""
        if self.rate <= 0:
            return True
        now = time.monotonic()
        self.tokens = min(self.burst,
                          self.tokens + (now - self.stamp) * self.rate)
        self.stamp = now
        if self.tokens < cost:
            return False
        self.tokens -= cost
        return True


def parse_tenant_quotas(
        specs: Optional[Iterable[str]]) -> Dict[str, TenantQuota]:
    """``name=rate[:burst[:weight]]`` (repeatable; name ``*`` is the
    default for unknown tenants) -> {name: TenantQuota}."""
    out: Dict[str, TenantQuota] = {}
    for spec in specs or ():
        name, _, rest = spec.partition("=")
        if not name or not rest:
            raise ValueError(
                f"bad --tenant-quota {spec!r} (want "
                "name=rate[:burst[:weight]])")
        parts = rest.split(":")
        if len(parts) > 3:
            raise ValueError(f"bad --tenant-quota {spec!r}")
        rate = float(parts[0])
        burst = float(parts[1]) if len(parts) > 1 else None
        weight = float(parts[2]) if len(parts) > 2 else 1.0
        out[name] = TenantQuota(rate, burst, weight)
    return out


def resolve_quota(quotas: Dict[str, TenantQuota],
                  tenant: str) -> Optional[TenantQuota]:
    """Per-tenant QoS state out of *quotas*; the ``*`` spec is a
    TEMPLATE — each unknown tenant gets its own bucket and WFQ chain
    cloned from it.  The caller holds whatever lock guards *quotas*
    (both the server's admission path and the router's route path
    call this under their own lock)."""
    q = quotas.get(tenant)
    if q is None:
        d = quotas.get("*")
        if d is None:
            return None
        q = TenantQuota(d.rate, d.burst, d.weight)
        quotas[tenant] = q
    return q
