# tpulint: deterministic-path
"""Autoscaling fleet control plane: the reconciler that closes the loop
between the node agents' capacity labels and the serving tier.

The device plugin advertises chips, the labeller advertises slice shape
(``slice-generation`` / ``slice-workers`` / ``slice-degraded``), replicas
self-register with the router, and the router aggregates per-class
goodput and pressure at ``/fleet/statz`` — but none of those components
*acts*.  This module is the missing controller: a labeller-idiom
observe→decide→act loop that

- **observes** the router's fleet snapshot (queue/KV pressure, per-class
  goodput ratio + burn rate, shed counts) and node capacity (slice
  labels read from membership state files, or a ``--capacity-spec``
  JSON file for environments without a coordinator);
- **decides** through a pure, seeded state machine
  (:class:`FleetPlanner`) with hysteresis and cooldown so the loop
  cannot flap: scale out on sustained pressure or a burning SLO, scale
  in on sustained calm, scale to zero on sustained idle, replace dead
  replicas immediately, and drain + re-register replicas whose slice
  reshaped to a new generation;
- **acts** by driving real replica CLI subprocesses
  (``workloads.server --register-with …``, warmed through the
  persistent compile cache) and the router's ``POST /drain`` eviction
  surface.

Every transition is journaled through the flight recorder and counted
on the ``tpu_fleet_*`` families; the spawn/drain boundaries carry
``fleet.spawn`` / ``fleet.drain`` fault hooks plus retry/breaker
coverage so chaos runs can provoke every failure path.

The decision core never reads a clock or an unseeded RNG — time is
injected by the caller (``FleetObservation.now_s``), which is what makes
the unit suite's seeded statz sequences replay to byte-identical action
sequences.
"""

from __future__ import annotations

import argparse
import http.client
import json
import logging
import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field, replace
from typing import (
    Any,
    Callable,
    Dict,
    List,
    Mapping,
    Optional,
    Sequence,
    Tuple,
)

from .. import obs, resilience
from ..resilience import faults
from ..slice import state as slice_state
from . import loadclient

log = logging.getLogger("tpu.fleet")

# replica lifecycle states (controller-side; the router only ever sees
# registered-or-not plus the draining flag)
STATE_STARTING = "starting"
STATE_READY = "ready"
STATE_DRAINING = "draining"

# action kinds — the bounded label set of tpu_fleet_decisions_total
ACTION_SPAWN = "spawn"
ACTION_DRAIN = "drain"
ACTION_STOP = "stop"
ACTION_HOLD = "hold"
ACTIONS = (ACTION_SPAWN, ACTION_DRAIN, ACTION_STOP, ACTION_HOLD)

# scale-event reasons — bounded label set of tpu_fleet_scale_events_total
REASON_PRESSURE = "pressure"
REASON_GOODPUT = "goodput"
REASON_IDLE = "idle"
REASON_DEGRADED = "degraded"
REASON_FAILURE = "failure"
REASON_FLOOR = "floor"
# a firing page-severity burn-rate alert (the router's fleet-level
# evaluator or any replica's local one, via the /fleet/statz
# firing_alerts roll-up) — the alerting loop closed back into scaling
REASON_ALERT = "alert"
REASONS = (REASON_PRESSURE, REASON_GOODPUT, REASON_IDLE,
           REASON_DEGRADED, REASON_FAILURE, REASON_FLOOR,
           REASON_ALERT)

DIRECTIONS = ("up", "down")

ROLE_MIXED = "mixed"
ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"


# -- capacity ---------------------------------------------------------------


@dataclass(frozen=True)
class SliceCapacity:
    """One slice's advertised shape — the reconciler's unit of
    placement.  ``slots`` is how many replicas the slice hosts
    (defaults to ``workers``: one replica per worker host, the
    gang-placement the labeller's ``slice-workers`` label implies)."""

    slice_id: str
    generation: int
    workers: int
    degraded: bool = False
    max_replicas: int = 0

    @property
    def slots(self) -> int:
        return self.max_replicas if self.max_replicas > 0 \
            else self.workers


def load_capacity_spec(path: str) -> Tuple[SliceCapacity, ...]:
    """Parse a ``--capacity-spec`` JSON file::

        {"slices": [{"slice_id": "s0", "generation": 1, "workers": 2,
                     "degraded": false, "max_replicas": 2}]}

    Raises ValueError on a malformed document — capacity is the
    scale-out ceiling, and a silently-empty spec would read as "no
    capacity anywhere" and drain the world."""
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) or not isinstance(
            doc.get("slices"), list):
        raise ValueError(
            f"capacity spec {path!r}: want {{'slices': [...]}}")
    out: List[SliceCapacity] = []
    for i, row in enumerate(doc["slices"]):
        if not isinstance(row, dict):
            raise ValueError(
                f"capacity spec {path!r}: slices[{i}] not an object")
        try:
            out.append(SliceCapacity(
                slice_id=str(row["slice_id"]),
                generation=int(row["generation"]),
                workers=int(row.get("workers", 1)),
                degraded=bool(row.get("degraded", False)),
                max_replicas=int(row.get("max_replicas", 0))))
        except (KeyError, TypeError, ValueError) as e:
            raise ValueError(
                f"capacity spec {path!r}: slices[{i}]: {e}")
    return tuple(out)


def capacity_from_membership(
        paths: Sequence[str]) -> Tuple[SliceCapacity, ...]:
    """Capacity the labeller's way: each path is a slice-agent
    membership state file (``slice.state.save_membership``), yielding
    exactly the ``slice-generation``/``slice-workers``/
    ``slice-degraded`` label values the node carries.  An absent or
    corrupt file contributes nothing — same degraded-open posture as
    the label generators."""
    out: List[SliceCapacity] = []
    for path in paths:
        m = slice_state.load_membership(path)
        if m is None:
            continue
        out.append(SliceCapacity(
            slice_id=m.slice_id, generation=m.generation,
            workers=m.num_workers, degraded=m.degraded))
    return tuple(out)


# -- observation ------------------------------------------------------------


@dataclass(frozen=True)
class ReplicaView:
    """One managed replica as the planner sees it: controller process
    state joined with the router's cached statz row."""

    rid: str
    role: str
    state: str
    slice_id: str
    generation: int
    alive: bool
    healthy: bool
    queue_depth: int
    in_flight: int
    capacity: int
    started_at_s: float
    drain_started_at_s: float = 0.0
    drain_reason: str = ""


@dataclass(frozen=True)
class FleetObservation:
    """One observe() snapshot — everything plan() may consult.  Pure
    data: the planner must stay replayable from a recorded sequence of
    these."""

    now_s: float
    replicas: Tuple[ReplicaView, ...]
    slices: Tuple[SliceCapacity, ...]
    queue_depth: int = 0
    in_flight: int = 0
    capacity: int = 0
    requests_served: int = 0
    no_replica_total: int = 0
    kv_pages: int = 0
    kv_pages_free: int = 0
    shed_total: int = 0
    # class -> {"goodput_ratio": r, "burn_rate_max": b,
    #           "window_total": n}
    goodput: Mapping[str, Mapping[str, float]] = \
        field(default_factory=dict)
    # the /fleet/statz firing_alerts roll-up: each entry carries at
    # least {"source", "name", "severity"} — page severity is a
    # scale-up signal (reason=alert)
    firing_alerts: Tuple[Mapping[str, str], ...] = ()


@dataclass(frozen=True)
class Action:
    """One planned transition.  ``rid`` names the subject for
    drain/stop; spawn carries placement (slice, generation, role)."""

    kind: str
    reason: str
    rid: str = ""
    role: str = ROLE_MIXED
    slice_id: str = ""
    generation: int = 0


@dataclass(frozen=True)
class Plan:
    """plan()'s full verdict: the actions plus the bookkeeping the
    controller exports (desired gauge, the pressure that drove it)."""

    actions: Tuple[Action, ...]
    desired: int
    pressure: float


# -- decision core ----------------------------------------------------------


@dataclass(frozen=True)
class PlannerConfig:
    """The control knobs (docs/user-guide/fleet.md documents each).
    Watermarks are normalized pressure: (queue_depth + in_flight) /
    fleet capacity."""

    min_replicas: int = 1
    max_replicas: int = 4
    high_watermark: float = 1.5
    low_watermark: float = 0.25
    goodput_floor: float = 0.7
    burn_rate_high: float = 2.0
    up_stable_s: float = 1.0
    down_stable_s: float = 10.0
    idle_to_zero_s: float = 60.0
    cooldown_s: float = 5.0
    drain_timeout_s: float = 30.0
    # the statz snapshot a drain verdict reads can be one scrape
    # interval stale: a just-drained replica may still be finishing a
    # stream the cached counters no longer show.  Never trust
    # queue==0 before the drain has aged past this dwell.
    drain_min_s: float = 1.0
    start_grace_s: float = 120.0
    disagg: bool = False

    def __post_init__(self) -> None:
        if self.min_replicas < 0 or self.max_replicas < 1:
            raise ValueError("replica bounds out of range")
        if self.min_replicas > self.max_replicas:
            raise ValueError("min_replicas > max_replicas")
        if self.low_watermark >= self.high_watermark:
            raise ValueError("low watermark must sit below high")
        if not 0.0 <= self.goodput_floor <= 1.0:
            raise ValueError("goodput_floor is a ratio in [0, 1]")


class FleetPlanner:
    """The pure decision core.  Feed it a sequence of
    :class:`FleetObservation` snapshots; it returns the same
    :class:`Plan` sequence every time — no clocks, no RNG, no I/O.

    Decision order per cycle (first match wins a given replica, all
    rules run every cycle):

    1. **reap + replace**: a dead process is stopped and — if it was
       starting/ready — replaced immediately, cooldown bypassed
       (failure healing must not wait out a scale event).
    2. **drain completion**: a draining replica whose queue emptied
       (or whose drain timed out) is stopped; a degraded-drain gets
       its 1:1 replacement spawned onto the slice's current
       generation.
    3. **degraded rolling drain**: one ready replica whose slice
       generation no longer matches advertised capacity is drained
       (at most one in flight at a time — a reshape must roll, not
       thundering-herd the fleet).
    4. **floor**: below ``min_replicas``, spawn (no cooldown — the
       floor is an invariant, not a preference).
    5. **scale up**: pressure above the high watermark (or a class
       burning its SLO) sustained for ``up_stable_s``, cooldown
       passed, capacity available.
    6. **scale to zero / scale in**: sustained idle (to zero, only
       when ``min_replicas == 0``) or pressure below the low
       watermark for ``down_stable_s``, cooldown passed — drains the
       newest safe victim rather than killing it.
    """

    def __init__(self, config: PlannerConfig) -> None:
        self.config = config
        self._high_since: Optional[float] = None
        self._low_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._last_scale_s: Optional[float] = None
        self._last_served: Optional[int] = None
        self._last_norep: Optional[int] = None
        self._spawn_seq = 0

    # -- helpers ------------------------------------------------------------

    @staticmethod
    def _stale(r: ReplicaView,
               by_slice: Mapping[str, SliceCapacity]) -> bool:
        """Does *r* run on a shape capacity no longer advertises?
        Generation mismatch is THE trigger: a degraded reshape always
        bumps the generation (slice.state), and keying on the flag
        alone would drain the replacement too, forever."""
        if not r.slice_id:
            return False  # placeless replica (no capacity source)
        s = by_slice.get(r.slice_id)
        return s is None or s.generation != r.generation

    @staticmethod
    def _slots(s: SliceCapacity) -> int:
        return s.slots

    def _effective_max(self, slices: Sequence[SliceCapacity]) -> int:
        cap = sum(self._slots(s) for s in slices)
        if not slices:
            cap = self.config.max_replicas
        return min(self.config.max_replicas, cap)

    def _place(self, occupied: Mapping[str, int],
               slices: Sequence[SliceCapacity]
               ) -> Optional[Tuple[str, int]]:
        """The slice for one new replica: most free slots first,
        healthy generations before degraded ones, slice_id as the
        deterministic tie-break.  None when every slot is taken (the
        spawn is capacity-bound, not config-bound)."""
        if not slices:
            return ("", 0)
        best: Optional[SliceCapacity] = None
        best_key: Tuple[int, int, str] = (0, 0, "")
        for s in sorted(slices, key=lambda s: s.slice_id):
            free = self._slots(s) - occupied.get(s.slice_id, 0)
            if free <= 0:
                continue
            key = (0 if not s.degraded else 1, -free, s.slice_id)
            if best is None or key < best_key:
                best, best_key = s, key
        if best is None:
            return None
        return (best.slice_id, best.generation)

    def _choose_role(self, active: Sequence[ReplicaView]) -> str:
        """The live disagg knob: a homogeneous fleet spawns mixed;
        a disagg fleet keeps one of each phase alive, then feeds
        whichever phase queues deeper (prefill-vs-decode pressure)."""
        if not self.config.disagg:
            return ROLE_MIXED
        by_role: Dict[str, List[ReplicaView]] = {}
        for r in active:
            by_role.setdefault(r.role, []).append(r)
        if not by_role.get(ROLE_PREFILL):
            return ROLE_PREFILL
        if not by_role.get(ROLE_DECODE):
            return ROLE_DECODE

        def role_pressure(role: str) -> float:
            rs = by_role.get(role, [])
            depth = sum(r.queue_depth + r.in_flight for r in rs)
            cap = sum(max(r.capacity, 1) for r in rs)
            return depth / max(cap, 1)

        return ROLE_PREFILL \
            if role_pressure(ROLE_PREFILL) >= role_pressure(ROLE_DECODE) \
            else ROLE_DECODE

    def _scale_down_victim(self, active: Sequence[ReplicaView]
                           ) -> Optional[ReplicaView]:
        """Newest ready replica whose removal keeps every live role
        populated (a disagg fleet must not drain its last prefill
        while decode replicas still depend on it)."""
        ready = [r for r in active if r.state == STATE_READY]
        roles = {r.role for r in active}
        for r in sorted(ready, key=lambda r: (-r.started_at_s, r.rid)):
            remaining = [x for x in active if x.rid != r.rid]
            if self.config.disagg and len(roles) > 1:
                if r.role not in {x.role for x in remaining}:
                    continue
            return r
        return None

    # -- the loop body ------------------------------------------------------

    def plan(self, o: FleetObservation) -> Plan:
        cfg = self.config
        now = o.now_s
        by_slice = {s.slice_id: s for s in o.slices}
        actions: List[Action] = []

        alive = [r for r in o.replicas if r.alive]
        dead = [r for r in o.replicas if not r.alive]
        active = [r for r in alive
                  if r.state in (STATE_STARTING, STATE_READY)]
        draining = [r for r in alive if r.state == STATE_DRAINING]

        # deltas for idle / scale-from-zero detection (cumulative
        # counters; a replica death shrinks the served sum, so clamp)
        served_delta = 0 if self._last_served is None else max(
            0, o.requests_served - self._last_served)
        self._last_served = o.requests_served
        norep_delta = 0 if self._last_norep is None else max(
            0, o.no_replica_total - self._last_norep)
        self._last_norep = o.no_replica_total

        # 1. reap dead processes; replace the ones that were carrying
        # traffic (cooldown deliberately bypassed: failover speed is
        # the point of running a controller at all)
        spawns = 0
        drains = 0
        occupied: Dict[str, int] = {}
        for r in active + draining:
            if r.slice_id:
                occupied[r.slice_id] = occupied.get(r.slice_id, 0) + 1
        eff_max = self._effective_max(o.slices)
        for r in dead:
            actions.append(Action(ACTION_STOP, REASON_FAILURE,
                                  rid=r.rid, role=r.role,
                                  slice_id=r.slice_id,
                                  generation=r.generation))
            if r.state in (STATE_STARTING, STATE_READY) \
                    and len(active) + spawns < eff_max:
                placed = self._place(occupied, o.slices)
                if placed is not None:
                    sid, gen = placed
                    actions.append(Action(
                        ACTION_SPAWN, REASON_FAILURE, role=r.role,
                        slice_id=sid, generation=gen))
                    spawns += 1
                    if sid:
                        occupied[sid] = occupied.get(sid, 0) + 1

        # a replica stuck starting past the grace window is a failure
        # too (hung backend init): stop it, let the floor/pressure
        # rules re-spawn next cycle with fresh state
        for r in list(active):
            if r.state == STATE_STARTING \
                    and now - r.started_at_s >= cfg.start_grace_s:
                actions.append(Action(ACTION_STOP, REASON_FAILURE,
                                      rid=r.rid, role=r.role,
                                      slice_id=r.slice_id,
                                      generation=r.generation))
                active.remove(r)
                if r.slice_id:
                    occupied[r.slice_id] = max(
                        0, occupied.get(r.slice_id, 1) - 1)

        # 2. drain completion: queue empty (or timeout) -> stop; a
        # degraded drain re-registers 1:1 onto the current generation
        for r in draining:
            age = now - r.drain_started_at_s
            done = (age >= cfg.drain_min_s
                    and (r.queue_depth + r.in_flight) == 0) \
                or age >= cfg.drain_timeout_s
            if not done:
                continue
            actions.append(Action(ACTION_STOP,
                                  r.drain_reason or REASON_IDLE,
                                  rid=r.rid, role=r.role,
                                  slice_id=r.slice_id,
                                  generation=r.generation))
            if r.slice_id:
                occupied[r.slice_id] = max(
                    0, occupied.get(r.slice_id, 1) - 1)
            if r.drain_reason == REASON_DEGRADED \
                    and len(active) + spawns < eff_max:
                placed = self._place(occupied, o.slices)
                if placed is not None:
                    sid, gen = placed
                    actions.append(Action(
                        ACTION_SPAWN, REASON_DEGRADED, role=r.role,
                        slice_id=sid, generation=gen))
                    spawns += 1
                    if sid:
                        occupied[sid] = occupied.get(sid, 0) + 1

        # 3. degraded rolling drain — one at a time, oldest first
        if not draining:
            stale = sorted(
                (r for r in active
                 if r.state == STATE_READY
                 and self._stale(r, by_slice)),
                key=lambda r: (r.started_at_s, r.rid))
            if stale:
                v = stale[0]
                actions.append(Action(ACTION_DRAIN, REASON_DEGRADED,
                                      rid=v.rid, role=v.role,
                                      slice_id=v.slice_id,
                                      generation=v.generation))
                drains += 1
                active.remove(v)

        n = len(active)

        # pressure + goodput signals
        pressure = ((o.queue_depth + o.in_flight)
                    / max(o.capacity, 1)) if o.capacity else 0.0
        goodput_bad = False
        for row in o.goodput.values():
            if float(row.get("window_total", 0.0)) <= 0:
                continue
            if float(row.get("goodput_ratio", 1.0)) < cfg.goodput_floor \
                    or float(row.get("burn_rate_max", 0.0)) \
                    >= cfg.burn_rate_high:
                goodput_bad = True
                break
        # a firing page-severity alert (PR 18) is the alert engine's
        # pre-chewed verdict — multi-window burn already confirmed it,
        # so it drives scale-up even when the raw-threshold signals
        # above haven't tripped (and keeps working as a fallback when
        # the fleet runs without the evaluator)
        alert_hot = any(
            str(f.get("severity", "")) == "page"
            for f in o.firing_alerts if isinstance(f, Mapping))
        high = (n > 0 and pressure >= cfg.high_watermark) \
            or (n > 0 and goodput_bad) \
            or (n > 0 and alert_hot) \
            or (n == 0 and norep_delta > 0)
        low = n > 0 and pressure <= cfg.low_watermark \
            and not goodput_bad and not alert_hot
        idle = n > 0 and o.queue_depth == 0 and o.in_flight == 0 \
            and served_delta == 0

        if high and self._high_since is None:
            self._high_since = now
        if not high:
            self._high_since = None
        if low and self._low_since is None:
            self._low_since = now
        if not low:
            self._low_since = None
        if idle and self._idle_since is None:
            self._idle_since = now
        if not idle:
            self._idle_since = None

        cooldown_ok = self._last_scale_s is None \
            or now - self._last_scale_s >= cfg.cooldown_s

        # 4. the floor invariant (also the scale-from-zero path once
        # norep pressure flips `high` with an empty fleet)
        if n + spawns < cfg.min_replicas \
                or (n == 0 and spawns == 0 and high):
            placed = self._place(occupied, o.slices)
            if placed is not None and n + spawns < eff_max:
                sid, gen = placed
                actions.append(Action(
                    ACTION_SPAWN,
                    REASON_FLOOR if n + spawns < cfg.min_replicas
                    else REASON_PRESSURE,
                    role=self._choose_role(active),
                    slice_id=sid, generation=gen))
                spawns += 1
                if sid:
                    occupied[sid] = occupied.get(sid, 0) + 1

        # 5. scale up on sustained pressure / burning SLO
        elif self._high_since is not None \
                and now - self._high_since >= cfg.up_stable_s \
                and cooldown_ok and n + spawns < eff_max:
            placed = self._place(occupied, o.slices)
            if placed is not None:
                sid, gen = placed
                actions.append(Action(
                    ACTION_SPAWN,
                    REASON_ALERT if alert_hot
                    else REASON_GOODPUT if goodput_bad
                    else REASON_PRESSURE,
                    role=self._choose_role(active),
                    slice_id=sid, generation=gen))
                spawns += 1
                self._last_scale_s = now
                self._high_since = None

        # 6. scale to zero / scale in (drain, never kill)
        elif not draining and drains == 0 and cooldown_ok:
            to_zero = cfg.min_replicas == 0 \
                and self._idle_since is not None \
                and now - self._idle_since >= cfg.idle_to_zero_s
            shrink = self._low_since is not None \
                and now - self._low_since >= cfg.down_stable_s \
                and n > cfg.min_replicas
            if to_zero or shrink:
                v = self._scale_down_victim(active)
                if v is not None:
                    actions.append(Action(
                        ACTION_DRAIN,
                        REASON_IDLE if to_zero else REASON_PRESSURE,
                        rid=v.rid, role=v.role, slice_id=v.slice_id,
                        generation=v.generation))
                    drains += 1
                    self._last_scale_s = now
                    self._low_since = None
                    self._idle_since = None

        desired = max(0, n + spawns - drains)
        return Plan(actions=tuple(actions), desired=desired,
                    pressure=round(pressure, 4))


# -- metrics ----------------------------------------------------------------


class FleetMetrics:
    """The tpu_fleet_* families — every decision the planner makes is
    visible here and in the journal, never only in logs."""

    def __init__(self, registry: obs.Registry) -> None:
        self.registry = registry
        self.replicas = registry.gauge(
            "tpu_fleet_replicas",
            "Live managed replicas (starting + ready + draining).")
        self.desired = registry.gauge(
            "tpu_fleet_desired_replicas",
            "The planner's current target replica count.")
        self.scale_events = registry.counter(
            "tpu_fleet_scale_events_total",
            "Fleet size transitions by direction and trigger "
            "(pressure/goodput watermarks, idle scale-to-zero, "
            "degraded-slice re-registration, failure replacement, "
            "min-replica floor).", ("direction", "reason"))
        self.decisions = registry.counter(
            "tpu_fleet_decisions_total",
            "Planner verdicts per reconcile cycle, by action kind "
            "(hold = an observe cycle that changed nothing).",
            ("action",))
        self.drain_seconds = registry.histogram(
            "tpu_fleet_drain_seconds",
            "Drain start (router eviction) to replica stop: how long "
            "in-flight work took to leave a condemned replica.",
            buckets=obs.SLOW_BUCKETS_S)
        for d in DIRECTIONS:
            for r in REASONS:
                self.scale_events.labels(direction=d, reason=r).inc(0)
        for a in ACTIONS:
            self.decisions.labels(action=a).inc(0)


# -- controller (the act layer) ---------------------------------------------


@dataclass(frozen=True)
class ServerSpec:
    """How to launch one replica CLI — the knobs the reconciler passes
    straight through to ``workloads.server``."""

    config: str = "tiny"
    slots: int = 4
    max_len: int = 2048
    max_new_tokens: int = 256
    window: int = 4
    prefix_chunk: int = 0
    slo: Tuple[str, ...] = ()
    compile_cache_dir: str = ""
    kv_paging: bool = False
    # replica-local alert engine (PR 18): 0 keeps the replica's CLI
    # defaults; set both to shrink the burn-rate windows and tighten
    # the evaluation tick so soak episodes see alerts fire in seconds
    alert_interval_s: float = 0.0
    alert_window_scale: float = 0.0
    extra_args: Tuple[str, ...] = ()


@dataclass
class _Managed:
    """Controller-side record of one spawned replica process."""

    rid: str
    proc: "subprocess.Popen[bytes]"
    port: int
    role: str
    slice_id: str
    generation: int
    state: str
    started_at_s: float
    drain_started_at_s: float = 0.0
    drain_reason: str = ""


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


class FleetController:
    """observe → plan → act against a live router.

    The controller owns the subprocess table and the router client;
    every boundary (spawn, drain POST, statz GET) runs under the
    resilience layer (seeded RetryPolicy + breaker) and fires a fault
    hook (``fleet.spawn`` / ``fleet.drain``) so the chaos harness can
    break it on purpose.  All controller clocks are monotonic."""

    def __init__(self, router_url: str, *,
                 planner: Optional[FleetPlanner] = None,
                 config: Optional[PlannerConfig] = None,
                 server: Optional[ServerSpec] = None,
                 capacity_spec: str = "",
                 membership_paths: Sequence[str] = (),
                 interval_s: float = 1.0,
                 seed: int = 0,
                 registry: Optional[obs.Registry] = None,
                 recorder: Optional[obs.FlightRecorder] = None,
                 spawn_env: Optional[Dict[str, str]] = None) -> None:
        self.router_url = router_url.rstrip("/")
        host, _, port_s = self.router_url.rpartition("//")[-1] \
            .rpartition(":")
        self.router_host = host or "127.0.0.1"
        self.router_port = int(port_s)
        self.planner = planner or FleetPlanner(
            config or PlannerConfig())
        self.server = server or ServerSpec()
        self.capacity_spec = capacity_spec
        self.membership_paths = tuple(membership_paths)
        self.interval_s = interval_s
        self.seed = seed
        self.registry = registry or obs.Registry()
        self.recorder = recorder or obs.FlightRecorder(
            registry=self.registry)
        self.metrics = FleetMetrics(self.registry)
        self._rmetrics = resilience.ResilienceMetrics(self.registry)
        self._retry = resilience.RetryPolicy(
            max_attempts=3, initial_backoff_s=0.1, max_backoff_s=1.0,
            seed=seed)
        self._breaker = resilience.CircuitBreaker(
            op="fleet.router", failure_threshold=5,
            reset_timeout_s=2.0, metrics=self._rmetrics,
            recorder=self.recorder)
        self._procs: Dict[str, _Managed] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._seq = 0
        self._spawn_env = dict(spawn_env or {})
        self.max_observed = 0
        self.cycles = 0

    # -- observe ------------------------------------------------------------

    def _fetch_json(self, path: str) -> Dict[str, Any]:
        def get() -> Dict[str, Any]:
            conn = http.client.HTTPConnection(
                self.router_host, self.router_port, timeout=5.0)
            try:
                conn.request("GET", path)
                resp = conn.getresponse()
                body = resp.read()
                if resp.status != 200:
                    raise OSError(
                        f"GET {path} -> {resp.status}")
            finally:
                conn.close()
            out = json.loads(body)
            if not isinstance(out, dict):
                raise ValueError(f"GET {path}: non-object body")
            return out

        def attempt() -> Dict[str, Any]:
            return self._breaker.call(get)

        return self._retry.call(
            attempt, op="fleet.statz",
            retry_on=(OSError, ValueError,
                      http.client.HTTPException),
            metrics=self._rmetrics, stop=self._stop)

    def capacity(self) -> Tuple[SliceCapacity, ...]:
        """Re-read capacity every cycle — slice reshape lands as a
        file change, exactly like the labeller re-reading membership
        on its poll."""
        if self.capacity_spec:
            try:
                return load_capacity_spec(self.capacity_spec)
            except (OSError, ValueError) as e:
                resilience.suppressed("fleet.capacity_spec", e,
                                      logger=log,
                                      metrics=self._rmetrics)
                return ()
        return capacity_from_membership(self.membership_paths)

    def observe(self) -> Optional[FleetObservation]:
        """One fleet snapshot, or None when the router is unreachable
        (the loop holds rather than act blind)."""
        now = time.monotonic()
        try:
            statz = self._fetch_json("/fleet/statz")
        except (OSError, ValueError, http.client.HTTPException,
                resilience.CircuitOpenError) as e:
            resilience.suppressed("fleet.observe", e, logger=log,
                                  metrics=self._rmetrics)
            return None
        per_replica = statz.get("per_replica")
        per_replica = per_replica if isinstance(per_replica, dict) \
            else {}
        fleet = statz.get("fleet")
        fleet = fleet if isinstance(fleet, dict) else {}
        router_row = statz.get("router")
        router_row = router_row if isinstance(router_row, dict) else {}
        views: List[ReplicaView] = []
        with self._lock:
            managed = list(self._procs.values())
        for m in managed:
            row = per_replica.get(m.rid)
            row = row if isinstance(row, dict) else {}
            rstatz = row.get("statz")
            rstatz = rstatz if isinstance(rstatz, dict) else {}
            healthy = bool(row.get("healthy"))
            alive = m.proc.poll() is None
            if m.state == STATE_STARTING and healthy:
                m.state = STATE_READY
                self.recorder.record("tpu_fleet_replica_ready",
                                     replica=m.rid, role=m.role,
                                     slice_id=m.slice_id,
                                     generation=m.generation)
            views.append(ReplicaView(
                rid=m.rid, role=m.role, state=m.state,
                slice_id=m.slice_id, generation=m.generation,
                alive=alive, healthy=healthy,
                queue_depth=int(rstatz.get("queue_depth", 0) or 0),
                in_flight=int(rstatz.get("in_flight", 0) or 0),
                capacity=int(rstatz.get("capacity", 0) or 0),
                started_at_s=m.started_at_s,
                drain_started_at_s=m.drain_started_at_s,
                drain_reason=m.drain_reason))
        goodput_raw = fleet.get("goodput")
        goodput: Dict[str, Dict[str, float]] = {}
        if isinstance(goodput_raw, dict):
            for name, row in goodput_raw.items():
                if isinstance(row, dict):
                    goodput[str(name)] = {
                        k: float(v) for k, v in row.items()
                        if isinstance(v, (int, float))}
        shed = fleet.get("shed")
        shed_total = sum(
            int(v) for v in shed.values()
            if isinstance(v, (int, float))) \
            if isinstance(shed, dict) else 0
        firing_raw = fleet.get("firing_alerts")
        firing: List[Dict[str, str]] = []
        if isinstance(firing_raw, list):
            for f in firing_raw:
                if isinstance(f, dict) and f.get("name"):
                    firing.append({
                        "source": str(f.get("source", "")),
                        "name": str(f["name"]),
                        "severity": str(f.get("severity", ""))})
        return FleetObservation(
            now_s=now, replicas=tuple(views),
            slices=self.capacity(),
            queue_depth=int(fleet.get("queue_depth", 0) or 0),
            in_flight=int(fleet.get("in_flight", 0) or 0),
            capacity=int(fleet.get("capacity", 0) or 0),
            requests_served=int(
                fleet.get("requests_served", 0) or 0),
            no_replica_total=int(
                router_row.get("no_replica_total", 0) or 0),
            kv_pages=int(fleet.get("kv_pages", 0) or 0),
            kv_pages_free=int(fleet.get("kv_pages_free", 0) or 0),
            shed_total=shed_total, goodput=goodput,
            firing_alerts=tuple(firing))

    # -- act ----------------------------------------------------------------

    def _spawn_cmd(self, rid: str, port: int,
                   role: str) -> List[str]:
        s = self.server
        cmd = [sys.executable, "-m",
               "tpu_k8s_device_plugin.workloads.server",
               "--config", s.config, "--n-slots", str(s.slots),
               "--max-len", str(s.max_len),
               "--max-new-tokens", str(s.max_new_tokens),
               "--window", str(s.window),
               "--host", "127.0.0.1", "--port", str(port),
               "--register-with",
               f"http://{self.router_host}:{self.router_port}",
               "--replica-id", rid,
               "--register-interval", "0.3"]
        if s.prefix_chunk > 0:
            cmd += ["--prefix-chunk", str(s.prefix_chunk)]
        for spec in s.slo:
            cmd += ["--slo", spec]
        if s.compile_cache_dir:
            cmd += ["--compile-cache-dir", s.compile_cache_dir]
        if s.alert_interval_s > 0:
            cmd += ["--alert-interval", str(s.alert_interval_s)]
        if s.alert_window_scale > 0:
            cmd += ["--alert-window-scale",
                    str(s.alert_window_scale)]
        if role != ROLE_MIXED:
            cmd += ["--replica-role", role]
            if not s.kv_paging:
                cmd += ["--kv-paging"]
        if s.kv_paging:
            cmd += ["--kv-paging"]
        cmd += list(s.extra_args)
        return cmd

    def _spawn(self, action: Action) -> Optional[str]:
        if faults.ACTIVE is not None:
            try:
                faults.ACTIVE.fire("fleet.spawn")
            except faults.InjectedFault as e:
                resilience.suppressed("fleet.spawn", e, logger=log,
                                      metrics=self._rmetrics)
                return None
        self._seq += 1
        rid = f"fleet-{self._seq}"
        port = loadclient.free_port()
        env = dict(os.environ)
        env["PYTHONPATH"] = _repo_root() + os.pathsep \
            + env.get("PYTHONPATH", "")
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.update(self._spawn_env)

        def popen() -> "subprocess.Popen[bytes]":
            return subprocess.Popen(
                self._spawn_cmd(rid, port, action.role), env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

        try:
            proc = self._retry.call(
                popen, op="fleet.spawn", retry_on=(OSError,),
                metrics=self._rmetrics, stop=self._stop)
        except OSError as e:
            resilience.suppressed("fleet.spawn", e, logger=log,
                                  metrics=self._rmetrics)
            return None
        with self._lock:
            self._procs[rid] = _Managed(
                rid=rid, proc=proc, port=port, role=action.role,
                slice_id=action.slice_id,
                generation=action.generation,
                state=STATE_STARTING,
                started_at_s=time.monotonic())
        self.recorder.record("tpu_fleet_replica_spawned",
                             replica=rid, role=action.role,
                             slice_id=action.slice_id,
                             generation=action.generation,
                             reason=action.reason, port=port)
        self.metrics.scale_events.labels(
            direction="up", reason=action.reason).inc()
        log.info("spawned %s (role=%s slice=%s gen=%d reason=%s)",
                 rid, action.role, action.slice_id,
                 action.generation, action.reason)
        return rid

    def _drain(self, action: Action) -> None:
        if faults.ACTIVE is not None:
            try:
                faults.ACTIVE.fire("fleet.drain")
            except faults.InjectedFault as e:
                resilience.suppressed("fleet.drain", e, logger=log,
                                      metrics=self._rmetrics)
                return
        body = json.dumps({"replica_id": action.rid}).encode()

        def post() -> None:
            conn = http.client.HTTPConnection(
                self.router_host, self.router_port, timeout=5.0)
            try:
                conn.request("POST", "/drain", body=body, headers={
                    "Content-Type": "application/json"})
                resp = conn.getresponse()
                resp.read()
                # 404 = the router already evicted it (TTL beat us);
                # the drain goal is met either way
                if resp.status not in (200, 404):
                    raise OSError(f"POST /drain -> {resp.status}")
            finally:
                conn.close()

        try:
            self._retry.call(post, op="fleet.drain",
                             retry_on=(OSError,
                                       http.client.HTTPException),
                             metrics=self._rmetrics, stop=self._stop)
        except (OSError, http.client.HTTPException) as e:
            resilience.suppressed("fleet.drain", e, logger=log,
                                  metrics=self._rmetrics)
            return
        with self._lock:
            m = self._procs.get(action.rid)
            if m is not None:
                m.state = STATE_DRAINING
                m.drain_started_at_s = time.monotonic()
                m.drain_reason = action.reason
        self.recorder.record("tpu_fleet_replica_draining",
                             replica=action.rid,
                             reason=action.reason)
        self.metrics.scale_events.labels(
            direction="down", reason=action.reason).inc()
        log.info("draining %s (reason=%s)", action.rid,
                 action.reason)

    def _stop_replica(self, action: Action) -> None:
        with self._lock:
            m = self._procs.pop(action.rid, None)
        if m is None:
            return
        drained_s = 0.0
        if m.drain_started_at_s:
            drained_s = time.monotonic() - m.drain_started_at_s
            self.metrics.drain_seconds.observe(drained_s)
        if m.proc.poll() is None:
            m.proc.send_signal(signal.SIGTERM)
            try:
                m.proc.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                m.proc.kill()
                try:
                    m.proc.wait(timeout=10.0)
                except subprocess.TimeoutExpired:
                    log.warning("replica %s pid %d did not exit",
                                m.rid, m.proc.pid)
        else:
            m.proc.wait()
        self.recorder.record("tpu_fleet_replica_stopped",
                             replica=m.rid, reason=action.reason,
                             drain_s=round(drained_s, 3))
        log.info("stopped %s (reason=%s, drained %.1fs)", m.rid,
                 action.reason, drained_s)

    def act(self, plan: Plan) -> None:
        if not plan.actions:
            self.metrics.decisions.labels(action=ACTION_HOLD).inc()
            return
        for a in plan.actions:
            self.metrics.decisions.labels(action=a.kind).inc()
            self.recorder.record("tpu_fleet_decision",
                                 action=a.kind, reason=a.reason,
                                 replica=a.rid, role=a.role,
                                 slice_id=a.slice_id,
                                 generation=a.generation)
            if a.kind == ACTION_SPAWN:
                self._spawn(a)
            elif a.kind == ACTION_DRAIN:
                self._drain(a)
            elif a.kind == ACTION_STOP:
                self._stop_replica(a)

    # -- the loop -----------------------------------------------------------

    def replica_count(self) -> int:
        with self._lock:
            return len(self._procs)

    def managed(self) -> List[Tuple[str, "subprocess.Popen[bytes]"]]:
        """(rid, process) pairs — the chaos harness's kill surface."""
        with self._lock:
            return [(m.rid, m.proc) for m in self._procs.values()]

    def step(self) -> Optional[Plan]:
        """One reconcile cycle.  Returns the plan (None when the
        router was unobservable and the loop held)."""
        o = self.observe()
        if o is None:
            return None
        plan = self.planner.plan(o)
        self.act(plan)
        self.cycles += 1
        n = self.replica_count()
        self.max_observed = max(self.max_observed, n)
        self.metrics.replicas.set(float(n))
        self.metrics.desired.set(float(plan.desired))
        return plan

    def run(self, duration_s: float = 0.0) -> None:
        """The reconcile loop: step every ``interval_s`` until
        ``shutdown()`` (or *duration_s* elapses)."""
        deadline = time.monotonic() + duration_s if duration_s else None
        while not self._stop.is_set():
            if deadline is not None and time.monotonic() >= deadline:
                break
            self.step()
            self._stop.wait(self.interval_s)

    def shutdown(self, kill_replicas: bool = True) -> None:
        self._stop.set()
        if not kill_replicas:
            return
        with self._lock:
            managed = list(self._procs.values())
            self._procs.clear()
        for m in managed:
            m.proc.kill()
        for m in managed:
            try:
                m.proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                log.warning("replica %s pid %d did not exit",
                            m.rid, m.proc.pid)


# -- the trace-replay episode (the fleet gate) ------------------------------


def build_ramp_trace(seed: int, *, calm_requests: int = 16,
                     peak_requests: int = 72,
                     tail_requests: int = 20,
                     calm_rate: float = 2.0,
                     peak_rate: float = 10.0,
                     tail_rate: float = 1.5,
                     prefix_chunk: int = 16,
                     tenants: Tuple[str, ...] = ("default",),
                     tenant_weights: Optional[Tuple[float, ...]] = None
                     ) -> Tuple[Dict[str, object], List[Any]]:
    """A diurnal ramp from the seeded MMPP generator: calm → peak →
    calm, three deterministic segments concatenated on the virtual
    clock.  Same-seed-same-bytes, like every trace in this repo.

    The peak segment is HEAVY (long generations near the budget cap),
    not just frequent: arrival rate alone cannot raise queue pressure
    against a fast small model, and the whole point of the ramp is to
    make a correctly-tuned planner scale out BEFORE the chaos hooks
    fire — a fleet still at the floor when the SIGKILL lands drops to
    zero replicas and the episode can only fail its goodput floors."""
    from .trafficgen import TraceConfig, generate

    def seg(n: int, rate: float, sub: int, heavy: bool) -> List[Any]:
        # heavy bursts are tempered (2x, not 3x): the point of the
        # peak is sustained queue growth the planner can see through
        # up_stable_s, not a spike that saturates the floor replica
        # before any scale-out could possibly land.  Eight prefix
        # keys (not 4) so the router's affinity ring actually spreads
        # across a 2-3 replica fleet instead of pinning one.
        cfg = TraceConfig(
            n_requests=n, base_rate_rps=rate,
            burst_rate_rps=rate * (2.0 if heavy else 3.0),
            p_enter_burst=0.10, p_exit_burst=0.3,
            prefix_chunk=prefix_chunk, n_prefixes=8,
            max_prefix_chunks=2, prompt_median=24.0, prompt_max=48,
            output_median=100.0 if heavy else 20.0,
            output_max=128 if heavy else 48, vocab=256,
            tenants=tenants, tenant_weights=tenant_weights,
            unary_frac=0.25, slow_reader_frac=0.0, abandon_frac=0.0)
        return generate(cfg, seed + sub)

    requests: List[Any] = []
    t_off = 0.0
    for sub, (n, rate) in enumerate(
            ((calm_requests, calm_rate), (peak_requests, peak_rate),
             (tail_requests, tail_rate))):
        segment = seg(n, rate, sub, heavy=sub == 1)
        for r in segment:
            requests.append(replace(
                r, rid=f"r{len(requests):05d}",
                t_ms=r.t_ms + t_off))
        if segment:
            t_off = requests[-1].t_ms
    header: Dict[str, object] = {
        "schema": "tpu-trace/v1", "seed": seed,
        "requests": len(requests),
        "config": {"ramp": {
            "calm": {"requests": calm_requests, "rate": calm_rate},
            "peak": {"requests": peak_requests, "rate": peak_rate},
            "tail": {"requests": tail_requests, "rate": tail_rate},
        }}}
    return header, requests


def run_episode(args: argparse.Namespace) -> Tuple[
        Dict[str, Any], int]:
    """The fleet gate: an in-process router + the reconciler + a
    seeded diurnal ramp replayed open-loop, with a mid-ramp replica
    SIGKILL and a degraded-slice reshape.  Returns (report, exit
    code); every asserted fact comes from the replay report JSON, the
    ``tpu_fleet_*`` metrics, or the journals — never log text."""
    from . import replay
    from .router import RouterServer

    registry = obs.Registry()
    recorder = obs.FlightRecorder(registry=registry)
    policies = obs.parse_slo_specs(args.slo) if args.slo \
        else obs.default_slo_policies()
    metrics = replay.ReplayMetrics(registry, policies)
    header, requests = build_ramp_trace(
        args.seed, calm_requests=args.calm_requests,
        peak_requests=args.peak_requests,
        tail_requests=args.tail_requests,
        calm_rate=args.calm_rate, peak_rate=args.peak_rate,
        prefix_chunk=args.prefix_chunk)
    peak_start_ms = requests[args.calm_requests].t_ms \
        if len(requests) > args.calm_requests else 0.0
    trace_end_ms = requests[-1].t_ms if requests else 0.0
    # the kill lands mid-peak but PAST the pressure scale-out window
    # (up_stable_s + spawn + ready), so the death tests failover onto
    # a live fleet, not a fleet still booting its second replica; the
    # degraded reshape follows late-peak while load is still real.
    # Trace time alone cannot guarantee that ordering on a slow
    # machine (replica boot competes with serving for the same CPUs),
    # so each hook ALSO gates on the router reporting a second
    # routable replica before it fires — the trace offset is the
    # earliest the chaos may land, not a promise of fleet state.
    kill_at_ms = args.kill_at_ms if args.kill_at_ms is not None \
        else peak_start_ms + (trace_end_ms - peak_start_ms) * 0.5
    degrade_at_ms = args.degrade_at_ms \
        if args.degrade_at_ms is not None \
        else peak_start_ms + (trace_end_ms - peak_start_ms) * 0.8

    capacity_path = args.capacity_spec
    if not capacity_path:
        capacity_path = os.path.join(
            args.workdir, "fleet-capacity.json")
        with open(capacity_path, "w", encoding="utf-8") as fh:
            json.dump({"slices": [{
                "slice_id": "episode-slice", "generation": 1,
                "workers": args.max_replicas}]}, fh)

    # the router's fleet-level alert engine runs with shrunk burn-rate
    # windows so a mid-episode collapse traverses
    # inactive->pending->firing->resolved within the episode's wall
    # time (old Namespaces without the flags keep the CLI defaults)
    alert_interval = float(getattr(args, "alert_interval", 0.5))
    alert_scale = float(getattr(args, "alert_window_scale", 0.01))
    rt = RouterServer(statz_interval_s=0.3, replica_ttl_s=5.0,
                      breaker_reset_s=0.5, seed=args.seed,
                      registry=registry, slo_policies=policies,
                      alert_interval_s=alert_interval,
                      alert_window_scale=alert_scale,
                      # a mid-episode page then writes the fleet-level
                      # incident bundle (hand-built Namespaces without
                      # the flag keep the subscriber disarmed)
                      incident_dir=getattr(args, "incident_dir", None))
    rt.start(host="127.0.0.1", port=0)
    cache_dir = args.compile_cache_dir or os.path.join(
        args.workdir, "fleet-compile-cache")
    controller = FleetController(
        f"http://127.0.0.1:{rt.port}",
        config=PlannerConfig(
            min_replicas=1, max_replicas=args.max_replicas,
            high_watermark=args.high_watermark,
            low_watermark=args.low_watermark,
            up_stable_s=args.up_stable_s,
            down_stable_s=args.down_stable_s,
            cooldown_s=args.cooldown_s,
            drain_timeout_s=args.drain_timeout_s,
            start_grace_s=600.0),
        server=ServerSpec(
            config=args.config, slots=args.slots,
            max_len=args.max_len,
            max_new_tokens=args.max_new_tokens,
            prefix_chunk=args.prefix_chunk,
            slo=tuple(args.slo or ()),
            compile_cache_dir=cache_dir,
            alert_interval_s=alert_interval,
            alert_window_scale=alert_scale,
            extra_args=tuple(
                getattr(args, "server_extra_args", ()) or ())),
        capacity_spec=capacity_path, interval_s=0.25,
        seed=args.seed, registry=registry, recorder=recorder)
    if args.fault_spec:
        faults.install(args.fault_spec, seed=args.seed,
                       recorder=recorder)
    loop = threading.Thread(target=controller.run,
                            name="fleet-reconcile", daemon=True)
    t0 = time.monotonic()
    killed: Dict[str, str] = {}
    try:
        loop.start()
        # the reconciler itself brings up the floor replica — wait for
        # the router to report it routable before traffic starts
        loadclient.wait_http_ok(rt.port, "/healthz", 600.0)
        baseline_replicas = controller.replica_count()

        def routable_now() -> int:
            try:
                rows = loadclient.fetch_json(
                    rt.port, "/replicas").get("replicas")
                if not isinstance(rows, list):
                    return 0
                return sum(1 for row in rows
                           if isinstance(row, dict)
                           and row.get("healthy"))
            except Exception as e:
                resilience.suppressed("fleet.chaos_probe", e,
                                      logger=log)
                return 0

        def await_live_fleet(label: str,
                             bound_s: float = 90.0) -> None:
            # each hook runs on its own replay thread, so blocking
            # here never stalls the open-loop dispatcher.  If the
            # fleet never scales, fire anyway at the bound — the gate
            # then fails on its scale-out evidence, which is the
            # honest verdict.
            deadline = time.monotonic() + bound_s
            while time.monotonic() < deadline \
                    and routable_now() < 2:
                time.sleep(0.25)
            log.info("chaos: %s fires with %d routable replicas",
                     label, routable_now())

        def kill_one() -> None:
            await_live_fleet("SIGKILL")
            for rid, proc in controller.managed():
                if proc.poll() is None:
                    killed["rid"] = rid
                    log.info("chaos: SIGKILL %s at trace t=%.0fms",
                             rid, kill_at_ms)
                    proc.kill()
                    return

        degrade_fired: Dict[str, Optional[float]] = {}

        def degrade_slice() -> None:
            await_live_fleet("degraded reshape", bound_s=120.0)
            log.info("chaos: slice reshapes degraded at trace "
                     "t=%.0fms", degrade_at_ms)
            with open(capacity_path, "w", encoding="utf-8") as fh:
                json.dump({"slices": [{
                    "slice_id": "episode-slice", "generation": 2,
                    "degraded": True,
                    "workers": args.max_replicas}]}, fh)
            degrade_fired["t"] = time.monotonic()

        hooks: List[Tuple[float, Callable[[], None]]] = []
        if not args.no_kill:
            hooks.append((kill_at_ms / 1000.0 / args.time_scale,
                          kill_one))
        if not args.no_degrade:
            hooks.append((degrade_at_ms / 1000.0 / args.time_scale,
                          degrade_slice))

        results = replay.replay_trace(
            requests, "127.0.0.1", rt.port, policies=policies,
            metrics=metrics, time_scale=args.time_scale,
            late_ms=args.late_ms, timeout_s=args.timeout_s,
            hooks=hooks)

        # idle tail: the ramp is over — the reconciler must scale back
        # to the floor on sustained calm.  The routable-fleet gate on
        # the chaos hooks means the degraded reshape may fire AFTER
        # the last trace request on a slow box, so settle also waits
        # for it (and extends its deadline once it lands, giving the
        # rolling drain a full window to finish).
        settle_deadline = time.monotonic() + args.settle_s
        # alert-centric episodes (chaos soak ep. 15) additionally hold
        # the settle open until the router's evaluator reports no
        # firing alerts, so the firing -> resolved transition lands in
        # the journal BEFORE the harvest below reads it
        wait_alerts = bool(getattr(args, "settle_on_alerts", False))
        while time.monotonic() < settle_deadline:
            pending = not args.no_degrade \
                and "t" not in degrade_fired
            if degrade_fired.get("t") is not None:
                settle_deadline = max(
                    settle_deadline,
                    float(degrade_fired["t"]) + args.settle_s)
                degrade_fired["t"] = None
            if controller.replica_count() <= 1 and not pending \
                    and not (wait_alerts
                             and rt.alerts.brief()["firing"]):
                break
            time.sleep(0.25)
        scaled_back = controller.replica_count() <= max(
            1, baseline_replicas)

        report = replay.build_report(
            results, policies, trace_header=header,
            target=f"fleet:127.0.0.1:{rt.port} "
                   f"(reconciled, max {args.max_replicas})",
            time_scale=args.time_scale, late_ms=args.late_ms,
            debug_port=rt.port, top_missed=args.top_missed)

        # -- evidence: metrics + journals, never logs -------------------
        fleet_events = recorder.events()
        spawned = [e for e in fleet_events
                   if e.get("name") == "tpu_fleet_replica_spawned"]
        stopped = [e for e in fleet_events
                   if e.get("name") == "tpu_fleet_replica_stopped"]
        drains = [e for e in fleet_events
                  if e.get("name") == "tpu_fleet_replica_draining"]

        def _attr(e: Dict[str, object], key: str) -> object:
            a = e.get("attrs")
            return a.get(key) if isinstance(a, dict) else None

        samples = obs.parse_exposition(registry.render())
        fleet_metrics: Dict[str, float] = {}
        scale_up = scale_down = 0.0
        for name, labels, value in samples:
            if name == "tpu_fleet_scale_events_total":
                if labels.get("direction") == "up":
                    scale_up += value
                else:
                    scale_down += value
            if name.startswith("tpu_fleet_") and "seconds" not in name:
                key = name + ("{" + ",".join(
                    f"{k}={v}" for k, v in sorted(labels.items()))
                    + "}" if labels else "")
                fleet_metrics[key] = value
        replaced = any(_attr(e, "reason") == REASON_FAILURE
                       for e in spawned)
        degraded_drained = any(
            _attr(e, "reason") == REASON_DEGRADED for e in drains)
        regen_spawn = any(
            _attr(e, "reason") == REASON_DEGRADED
            and _attr(e, "generation") == 2 for e in spawned)
        # demand-driven scale-out specifically: floor/failure/degraded
        # spawns keep the fleet ALIVE, but the ramp's acceptance claim
        # is that load moved the replica count — only pressure/goodput
        # spawns prove that
        demand_spawns = sum(
            1 for e in spawned
            if _attr(e, "reason") in (REASON_PRESSURE, REASON_GOODPUT,
                                      REASON_ALERT))
        # alert evidence (PR 18): the state-machine transitions the
        # router's evaluator journaled, and any spawn the pre-chewed
        # alert verdict (rather than the raw thresholds) drove
        alert_transitions = [
            {"alert": _attr(e, "alert"),
             "severity": _attr(e, "severity"),
             "from": _attr(e, "state_from"),
             "to": _attr(e, "state_to")}
            for e in rt.recorder.events(
                name=obs.ALERT_TRANSITION_EVENT)]
        alert_spawns = sum(1 for e in spawned
                           if _attr(e, "reason") == REASON_ALERT)
        report["fleet"] = {
            "max_replicas_observed": controller.max_observed,
            "final_replicas": controller.replica_count(),
            "reconcile_cycles": controller.cycles,
            "scale_up_events": scale_up,
            "demand_scale_up_events": demand_spawns,
            "scale_down_events": scale_down,
            "scaled_back_to_floor": scaled_back,
            "replicas_spawned": len(spawned),
            "replicas_stopped": len(stopped),
            "replaced_after_kill": replaced,
            "degraded_drained": degraded_drained,
            "respawned_on_new_generation": regen_spawn,
            "alert_scale_up_events": alert_spawns,
            "alert_transitions": alert_transitions,
            "metrics": fleet_metrics,
            "journal": [
                {"name": str(e.get("name")), "attrs": e.get("attrs")}
                for e in fleet_events
                if str(e.get("name")).startswith("tpu_fleet_")],
        }
        aborts = 0.0
        for name, labels, value in samples:
            if name == "tpu_router_requests_total" \
                    and labels.get("outcome") == "stream_abort":
                aborts += value
        evicted = [e for e in rt.recorder.events(
            name="tpu_router_replica_evicted")]
        report["chaos"] = {
            "killed_replica": killed.get("rid"),
            "kill_at_trace_ms": None if args.no_kill else kill_at_ms,
            "degrade_at_trace_ms":
                None if args.no_degrade else degrade_at_ms,
            "replica_evicted": bool(evicted),
            "stream_aborts": aborts,
            "replaced_after_kill": replaced,
            "degraded_drained": degraded_drained,
            # malformed = the client saw a torn stream (transport
            # error) or the router aborted mid-frame.  A well-formed
            # 502/503 terminal frame is the fleet answering HONESTLY
            # while short a replica — it costs goodput (gated
            # separately), it is not a framing violation.
            "frame_errors": (report["outcomes"].get(
                loadclient.OUTCOME_TRANSPORT, 0)
                if isinstance(report["outcomes"], dict) else 0)
            + int(aborts),
            "error_responses": report["outcomes"].get(
                loadclient.OUTCOME_ERROR, 0)
            if isinstance(report["outcomes"], dict) else 0,
            "attainment_windows": {
                name: {
                    "pre_kill": replay._attainment_window(
                        results, name, 0.0, kill_at_ms),
                    "kill_window": replay._attainment_window(
                        results, name, kill_at_ms,
                        kill_at_ms + replay.CHAOS_SETTLE_MS),
                    "post_kill": replay._attainment_window(
                        results, name,
                        kill_at_ms + replay.CHAOS_SETTLE_MS,
                        float("inf")),
                } for name in policies} if not args.no_kill else {},
        }
        rc = _gate(args, report)
        if args.metrics_out:
            with open(args.metrics_out, "w",
                      encoding="utf-8") as fh:
                fh.write(registry.render())
        if args.report:
            with open(args.report, "w", encoding="utf-8") as fh:
                json.dump(report, fh, indent=2, sort_keys=True)
                fh.write("\n")
        print(json.dumps({
            "target": report["target"],
            "classes": report["classes"],
            "outcomes": report["outcomes"],
            "fleet": {k: v for k, v in report["fleet"].items()
                      if k != "journal"},
            "chaos": {k: v for k, v in report["chaos"].items()
                      if k != "attainment_windows"},
            "elapsed_s": round(time.monotonic() - t0, 1),
        }, indent=2, sort_keys=True))
        return report, rc
    finally:
        faults.uninstall()
        controller.shutdown()
        rt.stop()


def _gate(args: argparse.Namespace, report: Dict[str, Any]) -> int:
    """The gate verdict from the report document alone."""
    rc = 0
    from .replay import _parse_goodput_specs

    classes = report.get("classes")
    classes = classes if isinstance(classes, dict) else {}
    tenants = report.get("tenants")
    tenants = tenants if isinstance(tenants, dict) else {}
    for name, floor in _parse_goodput_specs(
            args.assert_goodput or []).items():
        if name.startswith("tenant:"):
            row = tenants.get(name.partition(":")[2], {})
        else:
            row = classes.get(name, {})
        got = row.get("attainment") if isinstance(row, dict) else None
        if got is None or float(got) < floor:
            print(f"FLEET GATE FAIL: {name} attainment {got} < "
                  f"{floor}", file=sys.stderr)
            rc = 1
        else:
            print(f"fleet gate ok: {name} attainment {got} >= "
                  f"{floor}")
    if not args.assert_fleet:
        return rc
    fleet = report.get("fleet")
    fleet = fleet if isinstance(fleet, dict) else {}
    chaos = report.get("chaos")
    chaos = chaos if isinstance(chaos, dict) else {}
    checks: List[Tuple[str, bool]] = [
        ("scaled out past the floor",
         int(fleet.get("max_replicas_observed", 0)) >= 2),
        ("scale-up events counted on tpu_fleet_scale_events_total",
         float(fleet.get("scale_up_events", 0)) >= 1),
        ("ramp drove a demand scale-up (reason=pressure|goodput)",
         int(fleet.get("demand_scale_up_events", 0)) >= 1),
        ("scaled back to the floor on idle",
         bool(fleet.get("scaled_back_to_floor"))),
        ("zero malformed client frames",
         int(chaos.get("frame_errors", 0)) == 0),
    ]
    if not args.no_kill:
        checks.append(("killed replica replaced (spawn "
                       "reason=failure journaled)",
                       bool(fleet.get("replaced_after_kill"))))
    if not args.no_degrade:
        checks.append(("degraded slice drained (drain "
                       "reason=degraded journaled)",
                       bool(fleet.get("degraded_drained"))))
        checks.append(("replacement re-registered on the new "
                       "generation",
                       bool(fleet.get("respawned_on_new_generation"))))
    for what, ok in checks:
        if ok:
            print(f"fleet gate ok: {what}")
        else:
            print(f"FLEET GATE FAIL: {what}", file=sys.stderr)
            rc = 1
    return rc


# -- CLI --------------------------------------------------------------------


def _add_server_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--config", default="tiny",
                   help="model config for spawned replicas")
    p.add_argument("--slots", type=int, default=2)
    p.add_argument("--max-len", type=int, default=512)
    p.add_argument("--max-new-tokens", type=int, default=128)
    p.add_argument("--prefix-chunk", type=int, default=16)
    p.add_argument("--slo", action="append", default=None,
                   metavar="CLASS=ttft_ms[:deadline_ms]")
    p.add_argument("--compile-cache-dir", default="",
                   help="persistent compile cache to warm replica "
                        "cold starts (TPU_DP_COMPILE_CACHE_DIR)")


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description="Fleet control plane: the reconciler tying slice "
                    "labels to replica lifecycle")
    sub = p.add_subparsers(dest="mode", required=True)

    runp = sub.add_parser(
        "run", help="reconcile against a live router until SIGINT")
    runp.add_argument("--router", required=True, metavar="URL",
                      help="router base URL (http://host:port)")
    runp.add_argument("--capacity-spec", default="", metavar="FILE",
                      help="slice capacity JSON (re-read every cycle)")
    runp.add_argument("--membership", action="append", default=None,
                      metavar="FILE",
                      help="slice membership state file (repeatable; "
                           "the labeller-idiom capacity source)")
    runp.add_argument("--min-replicas", type=int, default=1)
    runp.add_argument("--max-replicas", type=int, default=4)
    runp.add_argument("--high-watermark", type=float, default=1.5)
    runp.add_argument("--low-watermark", type=float, default=0.25)
    runp.add_argument("--goodput-floor", type=float, default=0.7)
    runp.add_argument("--burn-rate-high", type=float, default=2.0)
    runp.add_argument("--up-stable", type=float, default=1.0)
    runp.add_argument("--down-stable", type=float, default=10.0)
    runp.add_argument("--idle-to-zero", type=float, default=60.0)
    runp.add_argument("--cooldown", type=float, default=5.0)
    runp.add_argument("--drain-timeout", type=float, default=30.0)
    runp.add_argument("--drain-min", type=float, default=1.0)
    runp.add_argument("--start-grace", type=float, default=120.0)
    runp.add_argument("--disagg", action="store_true",
                      help="spawn prefill/decode role replicas "
                           "driven by per-phase queue pressure")
    runp.add_argument("--interval", type=float, default=1.0)
    runp.add_argument("--duration", type=float, default=0.0,
                      help="stop after this many seconds (0 = run "
                           "until interrupted)")
    runp.add_argument("--seed", type=int, default=0)
    runp.add_argument("--fault-spec", default=None, metavar="SPEC")
    runp.add_argument("--metrics-out", default=None, metavar="FILE")
    _add_server_flags(runp)

    epp = sub.add_parser(
        "episode",
        help="the fleet gate: diurnal ramp + SIGKILL + degraded "
             "reshape against an in-process router")
    epp.add_argument("--seed", type=int, default=0)
    epp.add_argument("--max-replicas", type=int, default=3)
    epp.add_argument("--calm-requests", type=int, default=16)
    epp.add_argument("--peak-requests", type=int, default=72)
    epp.add_argument("--tail-requests", type=int, default=20)
    epp.add_argument("--calm-rate", type=float, default=2.0)
    epp.add_argument("--peak-rate", type=float, default=10.0)
    epp.add_argument("--high-watermark", type=float, default=1.0)
    epp.add_argument("--low-watermark", type=float, default=0.25)
    epp.add_argument("--up-stable-s", type=float, default=0.5)
    epp.add_argument("--down-stable-s", type=float, default=2.0)
    epp.add_argument("--cooldown-s", type=float, default=2.0)
    epp.add_argument("--drain-timeout-s", type=float, default=20.0)
    epp.add_argument("--kill-at-ms", type=float, default=None,
                     help="SIGKILL a managed replica at this trace "
                          "time (default: mid-peak)")
    epp.add_argument("--degrade-at-ms", type=float, default=None,
                     help="reshape the slice degraded at this trace "
                          "time (default: late-peak)")
    epp.add_argument("--no-kill", action="store_true")
    epp.add_argument("--no-degrade", action="store_true")
    epp.add_argument("--capacity-spec", default="", metavar="FILE")
    epp.add_argument("--workdir", default=".", metavar="DIR")
    epp.add_argument("--time-scale", type=float, default=1.0)
    epp.add_argument("--late-ms", type=float, default=100.0)
    epp.add_argument("--timeout-s", type=float, default=120.0)
    epp.add_argument("--settle-s", type=float, default=30.0,
                     help="post-trace window for the idle scale-in")
    epp.add_argument("--top-missed", type=int, default=3)
    epp.add_argument("--report", default=None, metavar="FILE")
    epp.add_argument("--metrics-out", default=None, metavar="FILE")
    epp.add_argument("--assert-goodput", action="append",
                     default=None,
                     metavar="CLASS=RATIO|tenant:NAME=RATIO")
    epp.add_argument("--assert-fleet", action="store_true",
                     help="fail unless the report proves scale-out, "
                          "failure replacement, degraded drain, and "
                          "idle scale-in")
    epp.add_argument("--fault-spec", default=None, metavar="SPEC")
    epp.add_argument("--incident-dir", default=None, metavar="DIR",
                     help="arm the episode router's incident "
                          "subscriber: a firing page writes one "
                          "fleet-level bundle (with per-replica "
                          "fragments) under DIR")
    _add_server_flags(epp)

    args = p.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    if args.mode == "episode":
        _, rc = run_episode(args)
        return rc

    registry = obs.Registry()
    recorder = obs.FlightRecorder(registry=registry)
    if args.fault_spec:
        faults.install(args.fault_spec, seed=args.seed,
                       recorder=recorder)
    controller = FleetController(
        args.router,
        config=PlannerConfig(
            min_replicas=args.min_replicas,
            max_replicas=args.max_replicas,
            high_watermark=args.high_watermark,
            low_watermark=args.low_watermark,
            goodput_floor=args.goodput_floor,
            burn_rate_high=args.burn_rate_high,
            up_stable_s=args.up_stable,
            down_stable_s=args.down_stable,
            idle_to_zero_s=args.idle_to_zero,
            cooldown_s=args.cooldown,
            drain_timeout_s=args.drain_timeout,
            drain_min_s=args.drain_min,
            start_grace_s=args.start_grace,
            disagg=args.disagg),
        server=ServerSpec(
            config=args.config, slots=args.slots,
            max_len=args.max_len,
            max_new_tokens=args.max_new_tokens,
            prefix_chunk=args.prefix_chunk,
            slo=tuple(args.slo or ()),
            compile_cache_dir=args.compile_cache_dir),
        capacity_spec=args.capacity_spec,
        membership_paths=tuple(args.membership or ()),
        interval_s=args.interval, seed=args.seed,
        registry=registry, recorder=recorder)
    try:
        controller.run(duration_s=args.duration)
    except KeyboardInterrupt:
        log.info("interrupted; draining managed replicas")
    finally:
        controller.shutdown()
        faults.uninstall()
        if args.metrics_out:
            with open(args.metrics_out, "w",
                      encoding="utf-8") as fh:
                fh.write(registry.render())
    return 0


if __name__ == "__main__":
    sys.exit(main())
