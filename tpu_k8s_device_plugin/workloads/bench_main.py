"""Pod-runnable AlexNet benchmark (what the example pods execute).

≈ the reference pod's ``tf_cnn_benchmarks.py --model=alexnet`` invocation
(/root/reference/example/pod/alexnet-gpu.yaml:16): runs on whatever chips
the device plugin granted (TPU_VISIBLE_CHIPS) and prints images/sec to the
pod log.  ``--sharded`` trains over a mesh of all visible devices instead
of a single one.
"""

from __future__ import annotations

import argparse
import functools
import time

import jax


def _timed_loop(
    step, params, opt_state, images, labels, batch, steps, warmup,
    rounds: int = 1,
):
    """Shared timing harness.  Syncs via value transfer, not
    block_until_ready: the transfer has a hard data dependency on the whole
    dispatched chain, which some remote TPU transports honor more
    faithfully than buffer-ready events.

    With ``rounds > 1``, times several back-to-back rounds of *steps* and
    reports the best — timeit-style de-noising: scheduler jitter on a
    shared host only ever slows a round down, so the fastest round is the
    reproducible steady-state figure (same rationale as the Allocate
    p50 sampling in bench.py; VERDICT r1 flagged a 1.6x run-to-run swing)."""
    loss = None
    for _ in range(warmup):
        params, opt_state, loss = step(params, opt_state, images, labels)
    if loss is not None:
        float(loss)
    best = None
    for _ in range(rounds):
        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt_state, loss = step(params, opt_state, images, labels)
        float(loss)
        ips = batch * steps / (time.perf_counter() - t0)
        best = ips if best is None or ips > best else best
    return best


def _resolve_pool(pool):
    """Pool impl knob for A/B runs on the target chip without editing
    code: explicit argument, else ALEXNET_POOL env, else "xla".
    "pallas" routes the max-pools through the Pallas argmax-index
    kernel (workloads/pool.py); "fused" computes conv+pool in one
    kernel so the pre-pool activation never hits HBM
    (workloads/convpool.py).  Numerically equivalent either way."""
    import os

    return pool or os.environ.get("ALEXNET_POOL", "xla")


def run_single(
    batch: int, steps: int, warmup: int, s2d: bool = True,
    want_flops: bool = False, rounds: int = 1, pool=None,
):
    """Returns images/sec (and, with ``want_flops``, XLA's per-step FLOP
    count for MFU accounting).  ``s2d`` is on by default: the
    space-to-depth first conv is how this model should meet the MXU."""
    from .alexnet import create_train_state, synthetic_batch, train_step

    rng = jax.random.PRNGKey(0)
    model, state = create_train_state(
        rng, batch_size=batch, s2d=s2d, pool=_resolve_pool(pool))
    params, opt_state, tx = state["params"], state["opt_state"], state["tx"]
    images, labels = synthetic_batch(rng, batch, s2d=s2d)
    step = jax.jit(
        functools.partial(train_step, model, tx), donate_argnums=(0, 1)
    )
    flops = None
    if want_flops:
        flops, compiled = _step_flops(step, params, opt_state, images, labels)
        if compiled is not None:
            # reuse the AOT compilation for the timed loop: the jit
            # dispatch cache doesn't share entries with lower().compile(),
            # so timing through `step` would compile the model twice
            step = compiled
    ips = _timed_loop(
        step, params, opt_state, images, labels, batch, steps, warmup,
        rounds=rounds,
    )
    return (ips, flops) if want_flops else ips


def _step_flops(step, *args):
    """(per-step FLOPs, compiled executable).  FLOPs as XLA's compiler
    cost model counts them (the honest numerator for MFU — an analytic
    count would drift from what actually runs).  (None, None) when the
    backend doesn't expose AOT compilation / cost analysis."""
    try:
        compiled = step.lower(*args).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, list):  # older jax returns one dict per device
            ca = ca[0] if ca else None
        flops = ca.get("flops") if ca else None
        return (
            float(flops) if flops and flops > 0 else None,
            compiled,
        )
    except Exception as e:
        # no AOT/cost-analysis on this backend: MFU is simply omitted
        # from the report, but say why instead of swallowing (tpulint
        # R2) — a bench that silently drops a column looks healthy
        print(f"# cost_analysis unavailable ({type(e).__name__}: {e}); "
              "skipping FLOPs/MFU")
        return None, None


def run_sharded(batch: int, steps: int, warmup: int, s2d: bool = True,
                pool=None) -> float:
    from .alexnet import create_train_state, synthetic_batch
    from .parallel import make_mesh, make_sharded_train_step

    mesh = make_mesh()
    # keep per-device batch constant so chips stay MXU-bound as we scale
    batch *= mesh.shape["data"]
    rng = jax.random.PRNGKey(0)
    model, state = create_train_state(
        rng, batch_size=batch, s2d=s2d, pool=_resolve_pool(pool))
    step, params, opt_state, (img_sh, lbl_sh) = make_sharded_train_step(
        model, state["tx"], mesh, state["params"], state["opt_state"]
    )
    images, labels = synthetic_batch(rng, batch, s2d=s2d)
    images = jax.device_put(images, img_sh)
    labels = jax.device_put(labels, lbl_sh)
    return _timed_loop(
        step, params, opt_state, images, labels, batch, steps, warmup
    )


def run_elastic(
    batch: int,
    steps: int,
    checkpoint_dir: str,
    checkpoint_every: int,
    slice_state: str,
    s2d: bool = True,
    sharded: bool = False,
    pool=None,
    signal=None,
) -> int:
    """Checkpointed train loop for elastic slices: resume from the
    newest whole checkpoint, save every *checkpoint_every* steps, and —
    when the slice reshapes under us (ReshapeSignal observes the
    membership generation moving past the one our TPU_SLICE_GENERATION
    identity was issued for) — checkpoint immediately and exit with
    RESHAPE_EXIT_CODE so the orchestrator restarts this pod under the
    new generation's TPU_WORKER_ID/JAX_* contract.  Reformation becomes
    a restart, not a loss (docs/user-guide/resilience.md §Reshape
    runbook)."""
    from . import checkpoint as ckpt
    from .alexnet import create_train_state, synthetic_batch, train_step

    if signal is None:
        signal = ckpt.ReshapeSignal(slice_state)
    rng = jax.random.PRNGKey(0)
    model, state = create_train_state(
        rng, batch_size=batch, s2d=s2d, pool=_resolve_pool(pool))
    params, opt_state, tx = state["params"], state["opt_state"], state["tx"]
    images, labels = synthetic_batch(rng, batch, s2d=s2d)
    shardings = None
    if sharded:
        from .parallel import make_mesh, make_sharded_train_step

        mesh = make_mesh()
        step_fn, params, opt_state, (img_sh, lbl_sh) = \
            make_sharded_train_step(model, tx, mesh, params, opt_state)
        images = jax.device_put(images, img_sh)
        labels = jax.device_put(labels, lbl_sh)
        shardings = jax.tree_util.tree_map(
            lambda l: l.sharding, {"params": params,
                                   "opt_state": opt_state})
    else:
        step_fn = jax.jit(functools.partial(train_step, model, tx))

    start = 0
    latest = ckpt.latest_step(checkpoint_dir)
    if latest is not None:
        restored = ckpt.restore_checkpoint(
            checkpoint_dir,
            template={"params": params, "opt_state": opt_state},
            shardings=shardings,
        )
        params, opt_state = restored["params"], restored["opt_state"]
        start = latest
        print(f"resumed from checkpoint step {latest}", flush=True)

    def save(done_steps):
        ckpt.save_checkpoint(
            checkpoint_dir, done_steps,
            {"params": params, "opt_state": opt_state}, keep_last=3)

    loss = None
    for i in range(start, steps):
        params, opt_state, loss = step_fn(params, opt_state, images, labels)
        done = i + 1
        membership = signal.check()
        if membership is not None:
            float(loss)  # drain the dispatched step before serializing
            save(done)
            print(
                f"slice reshaped to gen {membership.generation} "
                f"({membership.num_workers} worker(s)"
                f"{', degraded' if membership.degraded else ''}); "
                f"checkpointed step {done}; exiting "
                f"{ckpt.RESHAPE_EXIT_CODE} for restart under the new "
                "identity", flush=True,
            )
            return ckpt.RESHAPE_EXIT_CODE
        if checkpoint_every and done % checkpoint_every == 0 \
                and done < steps:
            save(done)
    if loss is not None:
        print(f"final loss after {steps} steps: {float(loss):.4f}",
              flush=True)
    if steps > start:
        save(steps)
    return 0


def _maybe_init_distributed() -> bool:
    """Join a multi-host slice when the deployment wired one up.

    example/multihost/jobset.yaml sets JAX_COORDINATOR_ADDRESS (headless
    Service DNS of the index-0 pod), JAX_NUM_PROCESSES (hosts in the
    slice), and JAX_PROCESS_ID (the Job completion index); with them
    present, jax.distributed.initialize() forms the global mesh so
    jax.devices() spans every host's chips.  Single-host runs leave the
    env unset and skip this entirely.
    """
    import os

    addr = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if not addr:
        return False
    missing = [
        k for k in ("JAX_NUM_PROCESSES", "JAX_PROCESS_ID")
        if k not in os.environ
    ]
    if missing:
        raise SystemExit(
            "JAX_COORDINATOR_ADDRESS is set but "
            f"{' and '.join(missing)} "
            "is not; the three variables must be set together "
            "(see example/multihost/jobset.yaml)"
        )
    jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
        process_id=int(os.environ["JAX_PROCESS_ID"]),
    )
    return True


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="alexnet-jax-bench")
    p.add_argument("--batch", type=int, default=256,
                   help="per-device batch size (default 256)")
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--warmup", type=int, default=5)
    p.add_argument("--sharded", action="store_true",
                   help="train over a mesh of all visible devices")
    p.add_argument("--pool", choices=("xla", "pallas", "fused"),
                   default=None,
                   help="max-pool impl (default: $ALEXNET_POOL or xla)")
    p.add_argument("--checkpoint-dir", default="",
                   help="elastic mode: checkpoint/resume under this dir "
                        "(PVC mount); on a slice reshape the loop saves "
                        "and exits 77 for a restart under the new "
                        "identity")
    p.add_argument("--checkpoint-every", type=int, default=10,
                   help="steps between periodic checkpoints in elastic "
                        "mode (default 10; 0 = only reshape/final saves)")
    p.add_argument("--slice-state", default=None,
                   help="slice membership file the reshape watch reads "
                        "(default: the device plugin's standard path)")
    args = p.parse_args(argv)
    if args.steps < 1:
        p.error("--steps must be >= 1")

    distributed = _maybe_init_distributed()
    if distributed:
        print(
            f"joined multi-host slice: process "
            f"{jax.process_index()}/{jax.process_count()}", flush=True,
        )
    devs = jax.devices()
    print(f"devices: {len(devs)} x {devs[0].platform}", flush=True)
    if args.checkpoint_dir:
        from tpu_k8s_device_plugin.types import constants

        return run_elastic(
            args.batch, args.steps, args.checkpoint_dir,
            args.checkpoint_every,
            args.slice_state or constants.SLICE_STATE_FILE,
            sharded=args.sharded, pool=args.pool,
        )
    if args.sharded:
        ips = run_sharded(args.batch, args.steps, args.warmup,
                          pool=args.pool)
    else:
        ips = run_single(args.batch, args.steps, args.warmup,
                         pool=args.pool)
    n = len(devs) if args.sharded else 1
    print(f"total images/sec: {ips:.1f}")
    print(f"images/sec/chip:  {ips / n:.1f}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
