"""Pod-runnable AlexNet benchmark (what the example pods execute).

≈ the reference pod's ``tf_cnn_benchmarks.py --model=alexnet`` invocation
(/root/reference/example/pod/alexnet-gpu.yaml:16): runs on whatever chips
the device plugin granted (TPU_VISIBLE_CHIPS) and prints images/sec to the
pod log.  ``--sharded`` trains over a mesh of all visible devices instead
of a single one.
"""

from __future__ import annotations

import argparse
import functools
import time

import jax


def _timed_loop(step, params, opt_state, images, labels, batch, steps, warmup):
    """Shared timing harness.  Syncs via value transfer, not
    block_until_ready: the transfer has a hard data dependency on the whole
    dispatched chain, which some remote TPU transports honor more
    faithfully than buffer-ready events."""
    loss = None
    for _ in range(warmup):
        params, opt_state, loss = step(params, opt_state, images, labels)
    if loss is not None:
        float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, images, labels)
    float(loss)
    return batch * steps / (time.perf_counter() - t0)


def run_single(batch: int, steps: int, warmup: int) -> float:
    from .alexnet import create_train_state, synthetic_batch, train_step

    rng = jax.random.PRNGKey(0)
    model, state = create_train_state(rng, batch_size=batch)
    params, opt_state, tx = state["params"], state["opt_state"], state["tx"]
    images, labels = synthetic_batch(rng, batch)
    step = jax.jit(
        functools.partial(train_step, model, tx), donate_argnums=(0, 1)
    )
    return _timed_loop(
        step, params, opt_state, images, labels, batch, steps, warmup
    )


def run_sharded(batch: int, steps: int, warmup: int) -> float:
    from .alexnet import create_train_state, synthetic_batch
    from .parallel import make_mesh, make_sharded_train_step

    mesh = make_mesh()
    # keep per-device batch constant so chips stay MXU-bound as we scale
    batch *= mesh.shape["data"]
    rng = jax.random.PRNGKey(0)
    model, state = create_train_state(rng, batch_size=batch)
    step, params, opt_state, (img_sh, lbl_sh) = make_sharded_train_step(
        model, state["tx"], mesh, state["params"], state["opt_state"]
    )
    images, labels = synthetic_batch(rng, batch)
    images = jax.device_put(images, img_sh)
    labels = jax.device_put(labels, lbl_sh)
    return _timed_loop(
        step, params, opt_state, images, labels, batch, steps, warmup
    )


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="alexnet-jax-bench")
    p.add_argument("--batch", type=int, default=256,
                   help="per-device batch size (default 256)")
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--warmup", type=int, default=5)
    p.add_argument("--sharded", action="store_true",
                   help="train over a mesh of all visible devices")
    args = p.parse_args(argv)
    if args.steps < 1:
        p.error("--steps must be >= 1")

    devs = jax.devices()
    print(f"devices: {len(devs)} x {devs[0].platform}", flush=True)
    if args.sharded:
        ips = run_sharded(args.batch, args.steps, args.warmup)
    else:
        ips = run_single(args.batch, args.steps, args.warmup)
    n = len(devs) if args.sharded else 1
    print(f"total images/sec: {ips:.1f}")
    print(f"images/sec/chip:  {ips / n:.1f}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
