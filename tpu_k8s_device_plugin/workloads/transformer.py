"""Decoder-only transformer LM, TPU-first, with sequence parallelism.

The reference proves its plugin with opaque workload images (TF AlexNet,
vLLM — /root/reference/example/pod/alexnet-gpu.yaml:16,
example/vllm-serve/deployment.yaml:19-38); this build ships the workload
layer natively.  This module is the long-context half: a GPT-style LM
whose attention is pluggable between

  * local causal attention (single shard, the oracle), and
  * ring attention over a mesh ``seq`` axis (contiguous or zig-zag
    layout, from ring_attention.py) — K/V rotating on ICI while
    activations stay sequence-sharded, so per-chip memory is
    O(T / seq_parallelism).

Design choices are MXU/XLA-shaped: bf16 activations with f32 params and
softmax, static shapes, one jit of the whole train step, RoPE driven by
an explicit *positions* array (which is what makes the zig-zag permuted
layout work end-to-end: tokens, labels, and positions permute together,
and nothing else in the model cares about token order).  Sharding is the
scaling-book recipe: annotate params/inputs on a ``data × seq × model``
mesh and let XLA place the collectives.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn
import optax
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

COMPUTE_DTYPE = jnp.bfloat16

# attention callable: (q, k, v, positions) -> out, all [B, T, H, D] (+ [B, T])
AttnFn = Callable[[jax.Array, jax.Array, jax.Array, jax.Array], jax.Array]


def apply_rope(
    x: jax.Array, positions: jax.Array, theta: float = 10000.0
) -> jax.Array:
    """Rotary position embedding on [B, T, H, D] with explicit positions
    [B, T] — explicit so sequence-permuted layouts (zig-zag) stay correct.
    ``theta`` is the frequency base (10000 classic; Llama-3 uses 500000
    for longer context)."""
    d_half = x.shape[-1] // 2
    freqs = 1.0 / (theta ** (jnp.arange(d_half, dtype=jnp.float32) / d_half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, T, D/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def local_causal_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, positions: jax.Array
) -> jax.Array:
    """Whole-sequence causal attention on one shard (the oracle path).
    Causality comes from the positions array, not the storage order, so
    it is also correct on permuted layouts.  K/V may arrive grouped
    (GQA) — expanded here to the query head count."""
    k = repeat_kv(k, q.shape[2])
    v = repeat_kv(v, q.shape[2])
    scale = 1.0 / jnp.sqrt(jnp.array(q.shape[-1], jnp.float32))
    scores = jnp.einsum(
        "bqhd,bkhd->bqhk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    mask = positions[:, :, None] >= positions[:, None, :]  # [B, Tq, Tk]
    scores = jnp.where(mask[:, :, None, :], scores, -jnp.inf)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum(
        "bqhk,bkhd->bqhd", w, v.astype(jnp.float32)
    ).astype(q.dtype)


def split_qkv_heads(qkv, n_heads: int, n_kv_heads: int, head_dim: int):
    """Split a fused qkv projection [B, T, (H + 2*Hkv)*Dh] into
    q [B, T, H, Dh] and k/v [B, T, Hkv, Dh]."""
    B, T, _ = qkv.shape
    q_dim = n_heads * head_dim
    kv_dim = n_kv_heads * head_dim
    q = qkv[..., :q_dim].reshape(B, T, n_heads, head_dim)
    k = qkv[..., q_dim:q_dim + kv_dim].reshape(B, T, n_kv_heads, head_dim)
    v = qkv[..., q_dim + kv_dim:].reshape(B, T, n_kv_heads, head_dim)
    return q, k, v


def _validate_attn_ffn(n_heads: int, n_kv: int, ffn: str) -> None:
    """Trace-time config validation: a typo'd ffn string or a
    non-divisible GQA head count would otherwise surface as an opaque
    shape error (or, worse, silently build the wrong MLP)."""
    if ffn not in ("gelu", "swiglu"):
        raise ValueError(f"unknown ffn {ffn!r}: expected 'gelu' or 'swiglu'")
    if n_kv > n_heads or n_heads % n_kv:
        raise ValueError(
            f"n_kv_heads={n_kv} must divide n_heads={n_heads}"
        )


def repeat_kv(k: jax.Array, n_heads: int) -> jax.Array:
    """Broadcast grouped K/V heads [B, T, Hkv, Dh] to the full query
    head count (GQA: each KV head serves H/Hkv query heads)."""
    n_kv = k.shape[2]
    if n_kv == n_heads:
        return k
    return jnp.repeat(k, n_heads // n_kv, axis=2)


class Block(nn.Module):
    """Pre-norm transformer block: RMSNorm → attention → residual,
    RMSNorm → FFN → residual.

    Attention is multi-head or grouped-query (``n_kv_heads < n_heads``
    — the Llama-family layout: K/V project to fewer heads and each
    serves a group of query heads, shrinking the serving KV cache by
    H/Hkv).  The FFN is the dense GELU MLP, SwiGLU
    (``ffn="swiglu"`` — gate ⊙ silu, the Llama MLP), or a top-k routed
    mixture-of-experts (``n_experts > 0``, expert-parallel over the
    mesh's ``expert`` axis — see moe.py)."""

    d_model: int
    n_heads: int
    d_ff: int
    dtype: Any = COMPUTE_DTYPE
    attn_fn: AttnFn = staticmethod(local_causal_attention)
    n_experts: int = 0
    moe_k: int = 2
    moe_capacity_factor: float = 1.25
    n_kv_heads: Optional[int] = None  # None → multi-head (n_heads)
    ffn: str = "gelu"  # "gelu" | "swiglu"
    rope_theta: float = 10000.0

    @nn.compact
    def __call__(self, x: jax.Array, positions: jax.Array) -> jax.Array:
        B, T, _ = x.shape
        head_dim = self.d_model // self.n_heads
        n_kv = self.n_kv_heads or self.n_heads
        _validate_attn_ffn(self.n_heads, n_kv, self.ffn)
        h = nn.RMSNorm(dtype=self.dtype, name="attn_norm")(x)
        qkv = nn.Dense(
            (self.n_heads + 2 * n_kv) * head_dim, use_bias=False,
            dtype=self.dtype, name="qkv",
        )(h)
        q, k, v = split_qkv_heads(qkv, self.n_heads, n_kv, head_dim)
        q = apply_rope(q, positions, self.rope_theta)
        k = apply_rope(k, positions, self.rope_theta)
        # K/V go to the attention GROUPED: every attn impl expands to
        # the query head count itself — locally for the single-shard
        # paths, and AFTER the ring rotation for sequence-parallel
        # attention, so the ICI ring moves H/Hkv less data per hop
        att = self.attn_fn(q, k, v, positions)
        att = att.reshape(B, T, self.d_model)
        x = x + nn.Dense(self.d_model, use_bias=False, dtype=self.dtype,
                         name="out_proj")(att)

        h = nn.RMSNorm(dtype=self.dtype, name="mlp_norm")(x)
        if self.n_experts > 0:
            from .moe import MoEFFN

            # positions drive capacity-slot priority so overflow drops
            # the same tokens under any storage layout (zig-zag included)
            x = x + MoEFFN(
                n_experts=self.n_experts, d_model=self.d_model,
                d_ff=self.d_ff, k=self.moe_k,
                capacity_factor=self.moe_capacity_factor, dtype=self.dtype,
                name="moe",
            )(h, positions)
        elif self.ffn == "swiglu":
            gate = nn.Dense(self.d_ff, use_bias=False, dtype=self.dtype,
                            name="mlp_gate")(h)
            up = nn.Dense(self.d_ff, use_bias=False, dtype=self.dtype,
                          name="mlp_up")(h)
            x = x + nn.Dense(self.d_model, use_bias=False, dtype=self.dtype,
                             name="mlp_down")(nn.silu(gate) * up)
        else:
            h = nn.Dense(self.d_ff, use_bias=False, dtype=self.dtype,
                         name="mlp_up")(h)
            h = nn.gelu(h)
            x = x + nn.Dense(self.d_model, use_bias=False, dtype=self.dtype,
                             name="mlp_down")(h)
        return x


class TransformerLM(nn.Module):
    """Next-token LM.  ``attn_fn`` swaps local attention for ring
    attention without touching any other part of the model."""

    vocab: int
    d_model: int = 256
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 1024
    dtype: Any = COMPUTE_DTYPE
    attn_fn: AttnFn = staticmethod(local_causal_attention)
    n_experts: int = 0  # >0 swaps the MLP for a routed MoE FFN (moe.py)
    moe_k: int = 2
    moe_capacity_factor: float = 1.25
    n_kv_heads: Optional[int] = None  # < n_heads → GQA (Llama family)
    ffn: str = "gelu"  # "swiglu" for the Llama MLP
    rope_theta: float = 10000.0

    @nn.compact
    def __call__(
        self, tokens: jax.Array, positions: Optional[jax.Array] = None
    ) -> jax.Array:
        B, T = tokens.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
        x = nn.Embed(self.vocab, self.d_model, dtype=self.dtype,
                     name="embed")(tokens)
        for i in range(self.n_layers):
            x = Block(
                self.d_model, self.n_heads, self.d_ff, dtype=self.dtype,
                attn_fn=self.attn_fn, n_experts=self.n_experts,
                moe_k=self.moe_k,
                moe_capacity_factor=self.moe_capacity_factor,
                n_kv_heads=self.n_kv_heads, ffn=self.ffn,
                rope_theta=self.rope_theta,
                name=f"block_{i}",
            )(x, positions)
        x = nn.RMSNorm(dtype=self.dtype, name="final_norm")(x)
        logits = nn.Dense(self.vocab, use_bias=False, dtype=self.dtype,
                          name="lm_head")(x)
        return logits.astype(jnp.float32)


# ---------------------------------------------------------------------------
# training
# ---------------------------------------------------------------------------


def lm_loss(model: TransformerLM, params, tokens, labels, positions):
    """Mean next-token cross entropy; label -1 marks ignored slots (the
    final token of each sequence, which has no successor).  Auxiliary
    losses sown into the ``losses`` collection (the MoE load-balancing
    term, pre-scaled by its weight) are added on top."""
    logits, mut = model.apply(
        {"params": params}, tokens, positions, mutable="losses"
    )
    valid = labels >= 0
    raw = optax.softmax_cross_entropy_with_integer_labels(
        logits, jnp.maximum(labels, 0)
    )
    ce = jnp.sum(raw * valid) / jnp.maximum(jnp.sum(valid), 1)
    aux = sum(
        jnp.sum(leaf) for leaf in jax.tree_util.tree_leaves(mut)
    )
    return ce + aux


def lm_train_step(model, tx, params, opt_state, tokens, labels, positions):
    loss, grads = jax.value_and_grad(
        functools.partial(lm_loss, model)
    )(params, tokens, labels, positions)
    updates, opt_state = tx.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss


def synthetic_lm_batch(
    rng: jax.Array, batch: int, seq_len: int, vocab: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(tokens, labels, positions) in natural order; labels are tokens
    shifted left with -1 in the ignored last slot."""
    tokens = jax.random.randint(rng, (batch, seq_len), 0, vocab)
    labels = jnp.concatenate(
        [tokens[:, 1:], jnp.full((batch, 1), -1, tokens.dtype)], axis=1
    )
    positions = jnp.broadcast_to(
        jnp.arange(seq_len, dtype=jnp.int32), (batch, seq_len)
    )
    return tokens, labels, positions


# -- sharded training over a data × seq × model mesh ------------------------


def make_lm_mesh(
    devices=None, seq: int = 2, model: int = 2, expert: int = 1
) -> Mesh:
    """``data × expert × seq × model`` mesh: data parallelism outermost
    (its psum gradients tolerate the slowest links), expert next (the EP
    all-to-all rides with the batch split — tokens are sharded over
    ``(data, expert)`` jointly), sequence and tensor parallelism on the
    inner, physically-closest axes."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n % (seq * model * expert):
        raise ValueError(
            f"{n} devices not divisible by "
            f"expert*seq*model={expert * seq * model}"
        )
    grid = mesh_utils.create_device_mesh(
        (n // (expert * seq * model), expert, seq, model), devices=devices
    )
    return Mesh(grid, axis_names=("data", "expert", "seq", "model"))


def _lm_pspec(path, leaf, axes=("data", "expert", "seq", "model")) -> P:
    """Megatron-style tensor parallelism on the ``model`` axis: qkv/up
    projections column-split, out/down projections row-split, lm_head
    vocab-split; embeddings and norms replicated (vocab stays small in the
    example configs; a production config would vocab-split the embedding
    the same way as lm_head).  MoE expert stacks ([E, D, F] / [E, F, D])
    are expert-split on their leading axis and model-split on the FFN
    hidden dim — EP × TP within each expert.  *axes* is the mesh's axis
    set; any split whose axis the mesh lacks degrades to replication, so
    legacy 3-axis meshes still work with MoE params."""
    name = "/".join(
        str(getattr(p, "key", getattr(p, "name", p))) for p in path
    )
    ex = "expert" if "expert" in axes else None
    mdl = "model" if "model" in axes else None
    if leaf.ndim == 3 and "experts" in name:
        if "experts_up" in name:
            return P(ex, None, mdl)
        return P(ex, mdl, None)
    if leaf.ndim == 2 and "experts" in name:
        # int8 quant scales, per (expert, out-channel): split like the
        # out-channel of the stack they dequantize ([E,D,F] up → [E,F]
        # model-split scale; [E,F,D] down → [E,D] unsplit out dim)
        if "experts_up" in name:
            return P(ex, mdl)
        return P(ex, None)
    if leaf.ndim == 2:
        if ("qkv" in name or "mlp_up" in name or "mlp_gate" in name
                or "lm_head" in name):
            return P(None, mdl)
        if "out_proj" in name or "mlp_down" in name:
            return P(mdl, None)
    if leaf.ndim == 1 and name.endswith("scale"):
        # QuantDense per-out-channel scales: follow the kernel's output
        # dim — column-split projections carry a model-split scale, the
        # row-split ones an unsplit (replicated) scale
        if ("qkv" in name or "mlp_up" in name or "mlp_gate" in name
                or "lm_head" in name):
            return P(mdl)
    return P()


def lm_tree_shardings(mesh: Mesh, tree):
    axes = tuple(mesh.axis_names)

    def shard(path, leaf):
        spec = _lm_pspec(path, leaf, axes)
        # degrade any split the actual dim can't honor to replication
        # (always numerically valid — XLA re-broadcasts): e.g. an int4
        # group scale [D/group, F] whose group count is smaller than
        # the model axis in tiny test configs
        fixed = []
        for d, ax in enumerate(spec):
            if ax is not None:
                n = 1
                for a in (ax if isinstance(ax, tuple) else (ax,)):
                    n *= mesh.shape[a]
                if leaf.shape[d] % n:
                    ax = None
            fixed.append(ax)
        return NamedSharding(mesh, P(*fixed))

    return jax.tree_util.tree_map_with_path(shard, tree)


def make_lm_train_step(
    mesh: Mesh,
    vocab: int = 512,
    d_model: int = 256,
    n_heads: int = 4,
    n_layers: int = 2,
    d_ff: int = 1024,
    seq_axis: Optional[str] = "seq",
    attn_layout: str = "zigzag",
    learning_rate: float = 1e-2,
    rng: Optional[jax.Array] = None,
    batch: int = 4,
    seq_len: int = 64,
    n_experts: int = 0,
    moe_k: int = 2,
    moe_capacity_factor: float = 1.25,
    n_kv_heads: Optional[int] = None,
    ffn: str = "gelu",
    rope_theta: float = 10000.0,
):
    """Build a fully sharded LM train step over *mesh*.

    With *seq_axis* set, attention runs as causal ring attention over that
    mesh axis (``attn_layout``: "contiguous" or the balanced "zigzag");
    activations are [data(,expert), seq]-sharded, parameters model-split
    per ``_lm_pspec``.  With ``n_experts > 0`` the MLPs become routed
    MoE FFNs whose expert stacks shard on the mesh's ``expert`` axis
    (tokens ride ``(data, expert)`` jointly, so the dispatch/combine
    einsums lower to the EP all-to-all).  Returns (step, state, place)
    where ``place(tokens, labels, positions)`` applies the ingress layout
    (zig-zag permutation when selected) and device placement.

    The returned ``step`` **donates** its params/opt_state arguments (the
    standard training-loop contract — on TPU the old buffers are freed in
    place): always rebind to the returned values, and take any host
    snapshot of ``state["params"]`` (``jax.device_get``) *before* the
    first call.
    """
    from .ring_attention import make_ring_attention, zigzag_permute

    rng = jax.random.PRNGKey(0) if rng is None else rng
    n_seq = mesh.shape[seq_axis] if seq_axis else 1
    # tokens shard over data and (when present) the expert axis jointly —
    # EP is a second batch split outside the MoE layers
    batch_axes = (
        ("data", "expert") if "expert" in mesh.axis_names else "data"
    )

    if seq_axis:
        # heads ride the model axis too (qkv is model-split; leaving H
        # replicated would all-gather q/k/v and redo attention on every
        # model rank) — unless head count doesn't divide the axis
        n_kv_cfg = n_kv_heads or n_heads
        mdl_size = mesh.shape.get("model", 1)
        # both the query heads AND the (possibly grouped) KV heads must
        # divide the model axis for head-sharded ring attention
        head_axis = (
            "model"
            if n_heads % mdl_size == 0 and n_kv_cfg % mdl_size == 0
            else None
        )
        spec = P(batch_axes, seq_axis, head_axis, None)
        ring_fn, _ = make_ring_attention(
            mesh, seq_axis, causal=True, layout=attn_layout, spec=spec
        )

        def attn(q, k, v, positions):
            del positions  # causality comes from the ring layout
            return ring_fn(q, k, v)
    else:
        attn = local_causal_attention

    model = TransformerLM(
        vocab=vocab, d_model=d_model, n_heads=n_heads, n_layers=n_layers,
        d_ff=d_ff, attn_fn=attn, n_experts=n_experts, moe_k=moe_k,
        moe_capacity_factor=moe_capacity_factor, n_kv_heads=n_kv_heads,
        ffn=ffn, rope_theta=rope_theta,
    )
    tokens, labels, positions = synthetic_lm_batch(rng, batch, seq_len, vocab)
    params = model.init(rng, tokens, positions)["params"]
    tx = optax.adam(learning_rate)
    opt_state = tx.init(params)

    param_sh = lm_tree_shardings(mesh, params)
    opt_sh = lm_tree_shardings(mesh, opt_state)
    tok_spec = P(batch_axes, seq_axis) if seq_axis else P(batch_axes, None)
    tok_sh = NamedSharding(mesh, tok_spec)
    loss_sh = NamedSharding(mesh, P())

    params = jax.device_put(params, param_sh)
    opt_state = jax.device_put(opt_state, opt_sh)

    step = jax.jit(
        functools.partial(lm_train_step, model, tx),
        in_shardings=(param_sh, opt_sh, tok_sh, tok_sh, tok_sh),
        out_shardings=(param_sh, opt_sh, loss_sh),
        donate_argnums=(0, 1),
    )

    def place(tokens, labels, positions):
        if seq_axis and attn_layout == "zigzag":
            tokens = zigzag_permute(tokens, n_seq, axis=1)
            labels = zigzag_permute(labels, n_seq, axis=1)
            positions = zigzag_permute(positions, n_seq, axis=1)
        return tuple(
            jax.device_put(x, tok_sh) for x in (tokens, labels, positions)
        )

    state: Dict[str, Any] = {
        "model": model, "tx": tx, "params": params, "opt_state": opt_state,
        "batch": (tokens, labels, positions),
    }
    return step, state, place
