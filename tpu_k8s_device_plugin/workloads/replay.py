# tpulint: deterministic-path
"""Open-loop trace replay with per-class SLO attribution.

The counterpart of :mod:`.trafficgen`: take a ``tpu-trace/v1`` file
and replay it against a serving endpoint (one replica, or a router in
front of a fleet this module spawns itself) the way production
traffic actually arrives — **open loop**.  Requests dispatch at the
trace's timestamps whether or not earlier requests finished; a
replay that falls behind counts its late dispatches (and reports the
lag) but NEVER reschedules them, because a load generator that waits
for the system under test is measuring its own politeness.  Closed
loops self-throttle under overload and hide exactly the tail this
harness exists to expose.

What comes out is not a throughput number but an **SLO-attribution
report** (``tpu-replay-report/v1``): per-class goodput attainment
judged client-side against the same ``--slo`` grammar the server
uses, joined with the server's own ``/metrics`` and ``/statz``
goodput blocks, and — for every SLO-missed request — the stitched
``/debug/traces`` spans bucketed into where the time went:
queue-wait vs prefill vs decode vs stream-write vs router hop.  The
replay's own counters render through obs as ``tpu_replay_*``
families (``--metrics-out``), so a CI gate and a dashboard read the
same schema.

Client misbehavior (slow readers, abandoners, unary/stream mix)
comes from the trace and is executed by :mod:`.loadclient`;
``abandoned`` is a terminal outcome here, excluded from the SLO
denominator (the CLIENT left; the server did nothing wrong), while
sheds and errors count as misses.  With ``--kill-replica-at-ms`` the
harness SIGKILLs one spawned replica mid-trace and the report grows
a ``chaos`` section proving eviction, failover, and post-kill
attainment recovery — the goodput-under-chaos CI gate reads that.

Determinism marker: this module uses only monotonic clocks
(dispatch pacing) — no wall-clock reads, no RNG — so two replays of
one seeded trace differ only by scheduling noise, never by harness
randomness.  Stdlib + obs + sibling workloads modules, mypy
--strict.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .. import obs
from ..obs.slo import OTHER_LABEL, SLOPolicy
from . import loadclient
from .loadclient import StreamOutcome
from .trafficgen import TraceRequest, load_trace

log = logging.getLogger("replay")

REPORT_SCHEMA = "tpu-replay-report/v1"

# how long (trace-ms) after the kill the fleet is allowed to be in
# its failover trough before the "recovered" attainment window
# starts.  Kept shorter than the router's replica TTL so CI-scale
# traces (a few seconds of tail past the kill) still land eligible
# requests in the post-kill window.
CHAOS_SETTLE_MS = 2000.0

# server/router span names -> attribution bucket.  These are the
# names the engine journals through _mark()/Span; the report adds
# router_hop (proxy minus serve span) and unattributed (the rest of
# the client-observed latency: network, python, scrape noise).
_EVENT_BUCKETS = {
    "tpu_serve_queue_wait": "queue_wait_ms",
    "tpu_serve_admit": "prefill_ms",
    "tpu_serve_window": "decode_ms",
    "tpu_serve_stream_write": "stream_write_ms",
}
ATTRIBUTION_KEYS = ("queue_wait_ms", "prefill_ms", "decode_ms",
                    "stream_write_ms", "router_hop_ms",
                    "unattributed_ms")


@dataclass
class RequestResult:
    """One replayed request: the trace record, the wire-observed
    outcome, and the dispatcher's lateness accounting."""

    req: TraceRequest
    outcome: StreamOutcome
    lag_s: float
    late: bool
    slo_met: Optional[bool] = None  # None = not SLO-eligible


class ReplayMetrics:
    """The ``tpu_replay_*`` families (all defined HERE), rendered
    through a plain obs registry so promlint/dashboards see the same
    schema as the serving side.  Class labels are bounded to the
    declared policy names plus ``other``."""

    def __init__(self, registry: obs.Registry,
                 policies: Dict[str, SLOPolicy]) -> None:
        self.registry = registry
        self._classes = list(policies) + [OTHER_LABEL]
        self._m_requests = registry.counter(
            "tpu_replay_requests_total",
            "Replayed requests by SLO class and terminal outcome "
            "(ok/abandoned/shed/error/transport_error); class values "
            "are bounded to the declared policy set plus 'other'.",
            ("class", "outcome"))
        self._m_late = registry.counter(
            "tpu_replay_late_dispatches_total",
            "Requests dispatched later than the open-loop lateness "
            "budget allows; counted, never rescheduled.")
        self._h_lag = registry.histogram(
            "tpu_replay_dispatch_lag_seconds",
            "How far behind the trace timestamp each dispatch ran "
            "(open-loop pacing error of the harness itself).",
            buckets=obs.FAST_BUCKETS_S)
        self._h_ttft = registry.histogram(
            "tpu_replay_ttft_seconds",
            "Client-observed time to first streamed token by SLO "
            "class.", ("class",))
        self._h_total = registry.histogram(
            "tpu_replay_request_seconds",
            "Client-observed total request latency by SLO class.",
            ("class",))
        self._g_attain = registry.gauge(
            "tpu_replay_slo_attainment_ratio",
            "Fraction of SLO-eligible replayed requests that met "
            "their class SLO (the replay-side goodput headline).",
            ("class",))
        for name in self._classes:
            self._g_attain.labels(**{"class": name}).set(1.0)

    def bound(self, slo_class: str) -> str:
        return slo_class if slo_class in self._classes[:-1] \
            else OTHER_LABEL

    def observe(self, result: RequestResult) -> None:
        label = self.bound(result.req.slo_class)
        self._m_requests.labels(**{
            "class": label,
            "outcome": result.outcome.outcome}).inc()
        self._h_lag.observe(max(0.0, result.lag_s))
        if result.late:
            self._m_late.inc()
        if result.outcome.ttft_s is not None:
            self._h_ttft.labels(**{"class": label}).observe(
                result.outcome.ttft_s,
                trace_id=result.outcome.trace_id)
        self._h_total.labels(**{"class": label}).observe(
            result.outcome.total_s,
            trace_id=result.outcome.trace_id)

    def set_attainment(self, per_class: Dict[str, float]) -> None:
        for name in self._classes:
            if name in per_class:
                self._g_attain.labels(**{"class": name}).set(
                    per_class[name])


def judge(req: TraceRequest, out: StreamOutcome,
          policies: Dict[str, SLOPolicy]) -> Optional[bool]:
    """Client-side SLO verdict for one replayed request, mirroring
    the server accountant's semantics: unknown classes judge against
    the request-shape fallback, non-ok outcomes never meet an SLO —
    EXCEPT abandonment, which is the client's own doing and returns
    None (not SLO-eligible, excluded from the denominator)."""
    if out.outcome == loadclient.OUTCOME_ABANDONED:
        return None
    fallback = "interactive" if req.behavior.stream else "batch"
    policy = policies.get(req.slo_class) or policies.get(fallback) \
        or next(iter(policies.values()))
    if out.outcome != loadclient.OUTCOME_OK:
        return False
    return policy.met(out.ttft_s, out.total_s)


def replay_trace(requests: Sequence[TraceRequest], host: str,
                 port: int, *, policies: Dict[str, SLOPolicy],
                 metrics: ReplayMetrics, time_scale: float = 1.0,
                 late_ms: float = 100.0, timeout_s: float = 120.0,
                 hooks: Sequence[Tuple[float,
                                       Callable[[], None]]] = (),
                 ) -> List[RequestResult]:
    """Open-loop dispatch of *requests* against ``host:port``.  Each
    request fires at ``t_ms / time_scale`` after start on its own
    thread (an open loop must never queue behind a slow request);
    *hooks* are (real-seconds-after-start, callback) pairs — the
    chaos kill rides one.  Lag beyond *late_ms* marks the dispatch
    late (counted, never rescheduled).  Returns results in trace
    order."""
    if time_scale <= 0:
        raise ValueError("time_scale must be > 0")
    results: List[Optional[RequestResult]] = [None] * len(requests)
    lock = threading.Lock()

    # session continuation (PR 20): a revisit's prompt is the
    # conversation so far (prior visits' prompts) + the new turn, and
    # the conversation is CLOSED-loop within itself — a user cannot
    # send the follow-up before the reply arrives — while the trace
    # stays open-loop across sessions.  Both are precomputed /
    # coordinated here so replays are deterministic functions of the
    # trace, not of runtime interleaving.
    session_hist: Dict[str, List[int]] = {}
    chained: List[List[int]] = []
    for req in requests:
        if req.session:
            hist = session_hist.setdefault(req.session, [])
            chained.append(hist + req.tokens if req.cont
                           else list(req.tokens))
            hist.extend(req.tokens)
        else:
            chained.append(req.tokens)
    session_prev: Dict[str, threading.Event] = {}

    def one(i: int, req: TraceRequest, lag_s: float,
            tokens: List[int], prev_evt: Optional[threading.Event],
            done_evt: Optional[threading.Event]) -> None:
        if prev_evt is not None:
            # think time already paced the dispatch; this only guards
            # the pathological case where the previous turn is STILL
            # streaming (bounded — a wedged turn must not wedge the
            # whole conversation's accounting)
            prev_evt.wait(timeout_s)
        body: Dict[str, object] = {
            "tokens": tokens,
            "max_new_tokens": req.max_new_tokens,
            "priority": req.priority, "slo_class": req.slo_class,
            "ignore_eos": True,
        }
        if req.session:
            body["session_id"] = req.session
        if req.tenant and req.tenant != "default":
            body["tenant"] = req.tenant
        if req.behavior.stream:
            out = loadclient.stream_request(
                host, port, body, behavior=req.behavior,
                timeout_s=timeout_s)
        else:
            body["stream"] = False
            out = loadclient.unary_request(
                host, port, body, timeout_s=timeout_s)
        res = RequestResult(req=req, outcome=out, lag_s=lag_s,
                            late=lag_s * 1000.0 > late_ms,
                            slo_met=judge(req, out, policies))
        metrics.observe(res)
        with lock:
            results[i] = res
        if done_evt is not None:
            done_evt.set()

    threads: List[threading.Thread] = []
    t0 = time.monotonic()
    hook_threads: List[threading.Thread] = []
    stop = threading.Event()
    for delay_s, fn in hooks:
        def run_hook(d: float = delay_s,
                     f: Callable[[], None] = fn) -> None:
            if not stop.wait(d):
                f()
        ht = threading.Thread(target=run_hook, daemon=True)
        ht.start()
        hook_threads.append(ht)
    try:
        for i, req in enumerate(requests):
            target = t0 + req.t_ms / 1000.0 / time_scale
            now = time.monotonic()
            if target > now:
                time.sleep(target - now)
                now = time.monotonic()
            prev_evt = done_evt = None
            if req.session:
                prev_evt = session_prev.get(req.session)
                done_evt = threading.Event()
                session_prev[req.session] = done_evt
            t = threading.Thread(target=one,
                                 args=(i, req, now - target,
                                       chained[i], prev_evt,
                                       done_evt),
                                 daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=timeout_s + 30.0)
    finally:
        stop.set()
        for ht in hook_threads:
            ht.join(timeout=5.0)
    out: List[RequestResult] = []
    for i, res in enumerate(results):
        if res is None:
            # a worker thread died or overran its join budget: that
            # request's outcome is unknown — report it as a transport
            # error rather than silently shrinking the denominator
            log.warning("request %s never reported a result",
                        requests[i].rid)
            res = RequestResult(
                req=requests[i],
                outcome=StreamOutcome(
                    status=-1,
                    outcome=loadclient.OUTCOME_TRANSPORT,
                    total_s=timeout_s,
                    error="no result (worker timeout)"),
                lag_s=0.0, late=False, slo_met=False)
            metrics.observe(res)
        out.append(res)
    return out


# -- report ----------------------------------------------------------------


def _pct(xs: List[float], q: float) -> Optional[float]:
    if not xs:
        return None
    s = sorted(xs)
    return s[min(len(s) - 1, int(q * (len(s) - 1)))]


def attribute(events: List[Dict[str, object]],
              client_total_s: float) -> Dict[str, float]:
    """Bucket one request's server/router span events into where the
    time went.  ``router_hop_ms`` is the proxy span minus the serve
    span (time the router spent picking/forwarding/relaying);
    ``unattributed_ms`` is whatever remains of the client-observed
    latency (network, harness, scrape gaps) — it is REPORTED, not
    hidden, because an attribution that always sums to 100% is a
    model, not a measurement."""
    out = {k: 0.0 for k in ATTRIBUTION_KEYS}
    proxy_s = 0.0
    serve_s = 0.0
    for ev in events:
        name = ev.get("name")
        attrs = ev.get("attrs")
        if not isinstance(name, str) or not isinstance(attrs, dict):
            continue
        dur = attrs.get("duration_s")
        if not isinstance(dur, (int, float)):
            continue
        bucket = _EVENT_BUCKETS.get(name)
        if bucket is not None:
            out[bucket] += float(dur) * 1000.0
        elif name == "tpu_serve_request":
            serve_s += float(dur)
        elif name == "tpu_router_proxy":
            proxy_s += float(dur)
    if proxy_s > 0.0:
        out["router_hop_ms"] = max(0.0, proxy_s - serve_s) * 1000.0
    accounted = sum(out[k] for k in ATTRIBUTION_KEYS
                    if k != "unattributed_ms")
    out["unattributed_ms"] = max(
        0.0, client_total_s * 1000.0 - accounted)
    return {k: round(v, 3) for k, v in out.items()}


def _result_row(r: RequestResult) -> Dict[str, object]:
    o = r.outcome
    return {
        "rid": r.req.rid, "t_ms": round(r.req.t_ms, 3),
        "class": r.req.slo_class, "tenant": r.req.tenant,
        "status": o.status, "outcome": o.outcome,
        "ttft_ms": None if o.ttft_s is None
        else round(o.ttft_s * 1000.0, 3),
        "total_ms": round(o.total_s * 1000.0, 3),
        "tokens": o.tokens, "done_tokens": o.done_tokens,
        "replica": o.replica, "trace_id": o.trace_id,
        "late": r.late, "lag_ms": round(r.lag_s * 1000.0, 3),
        "slo_met": r.slo_met, "error": o.error,
    }


def build_report(results: Sequence[RequestResult],
                 policies: Dict[str, SLOPolicy], *,
                 trace_header: Dict[str, object], target: str,
                 time_scale: float, late_ms: float,
                 debug_port: Optional[int] = None,
                 debug_host: str = "127.0.0.1",
                 top_missed: int = 5) -> Dict[str, object]:
    """The ``tpu-replay-report/v1`` document: per-class attainment +
    latency tails, per-request rows, and — for SLO-missed requests —
    the span-bucketed attribution (with raw stitched events embedded
    for the slowest *top_missed*, so ``tools/obs_query.py
    --replay-report`` renders their trees offline)."""
    classes: Dict[str, Dict[str, object]] = {}
    attain: Dict[str, float] = {}
    for name, policy in policies.items():
        rs = [r for r in results if r.req.slo_class == name]
        eligible = [r for r in rs if r.slo_met is not None]
        met = [r for r in eligible if r.slo_met]
        outcomes: Dict[str, int] = {}
        for r in rs:
            outcomes[r.outcome.outcome] = outcomes.get(
                r.outcome.outcome, 0) + 1
        ttfts = [r.outcome.ttft_s * 1000.0 for r in rs
                 if r.outcome.ttft_s is not None]
        totals = [r.outcome.total_s * 1000.0 for r in rs]
        ratio = len(met) / len(eligible) if eligible else 1.0
        attain[name] = ratio
        classes[name] = {
            "policy": {"ttft_ms": policy.ttft_ms,
                       "deadline_ms": policy.deadline_ms,
                       "objective": policy.objective},
            "total": len(rs), "eligible": len(eligible),
            "met": len(met), "attainment": round(ratio, 4),
            "outcomes": outcomes,
            "ttft_ms": {"p50": _pct(ttfts, 0.5),
                        "p95": _pct(ttfts, 0.95),
                        "p99": _pct(ttfts, 0.99)},
            "total_ms": {"p50": _pct(totals, 0.5),
                         "p95": _pct(totals, 0.95),
                         "p99": _pct(totals, 0.99)},
        }
    # per-tenant attainment: the quota buckets' report surface, so a
    # multi-tenant gate can assert tenant:NAME=RATIO floors straight
    # from the document
    tenants: Dict[str, Dict[str, object]] = {}
    by_tenant: Dict[str, List[RequestResult]] = {}
    for r in results:
        by_tenant.setdefault(r.req.tenant, []).append(r)
    for tname in sorted(by_tenant):
        rs = by_tenant[tname]
        eligible = [r for r in rs if r.slo_met is not None]
        met = [r for r in eligible if r.slo_met]
        t_outcomes: Dict[str, int] = {}
        for r in rs:
            t_outcomes[r.outcome.outcome] = t_outcomes.get(
                r.outcome.outcome, 0) + 1
        tenants[tname] = {
            "total": len(rs), "eligible": len(eligible),
            "met": len(met),
            "attainment": round(
                len(met) / len(eligible), 4) if eligible else 1.0,
            "outcomes": t_outcomes,
        }
    # session warm-vs-cold split (PR 20): revisits (cont=True) should
    # warm-resume their parked KV; first visits pay the full prefill.
    # The goodput gate asserts warm p95 TTFT beats cold p95 — the
    # tiering layer's end-to-end latency evidence.
    sessions_block: Optional[Dict[str, object]] = None
    sessioned = [r for r in results if r.req.session]
    if sessioned:
        def _ttft_stats(rs: List[RequestResult]) -> Dict[str, object]:
            ttfts = [r.outcome.ttft_s * 1000.0 for r in rs
                     if r.outcome.ttft_s is not None
                     and r.outcome.outcome == loadclient.OUTCOME_OK]
            return {"total": len(rs), "measured": len(ttfts),
                    "ttft_ms": {"p50": _pct(ttfts, 0.5),
                                "p95": _pct(ttfts, 0.95)}}
        sessions_block = {
            "sessions": len({r.req.session for r in sessioned}),
            "warm": _ttft_stats(
                [r for r in sessioned if r.req.cont]),
            "cold": _ttft_stats(
                [r for r in sessioned if not r.req.cont]),
        }
    missed = sorted(
        (r for r in results if r.slo_met is False),
        key=lambda r: -r.outcome.total_s)
    missed_rows: List[Dict[str, object]] = []
    for rank, r in enumerate(missed):
        row = _result_row(r)
        events: List[Dict[str, object]] = []
        if debug_port is not None and r.outcome.trace_id:
            try:
                events = loadclient.fetch_trace_events(
                    debug_port, r.outcome.trace_id, host=debug_host)
            except (OSError, ValueError) as e:
                log.warning("no trace events for %s: %s",
                            r.req.rid, e)
        row["attribution"] = attribute(events, r.outcome.total_s)
        if rank < top_missed and events:
            # raw spans ride along for the slowest K so obs_query
            # can re-stitch them from the report file alone
            row["events"] = events
        missed_rows.append(row)
    outcome_totals: Dict[str, int] = {}
    for r in results:
        outcome_totals[r.outcome.outcome] = outcome_totals.get(
            r.outcome.outcome, 0) + 1
    return {
        "schema": REPORT_SCHEMA,
        "trace": {"seed": trace_header.get("seed"),
                  "requests": trace_header.get("requests"),
                  "config": trace_header.get("config")},
        "target": target,
        "open_loop": {"time_scale": time_scale, "late_ms": late_ms,
                      "late_dispatches": sum(
                          1 for r in results if r.late),
                      "max_lag_ms": round(max(
                          (r.lag_s for r in results),
                          default=0.0) * 1000.0, 3)},
        "classes": classes,
        "tenants": tenants,
        "sessions": sessions_block,
        "outcomes": outcome_totals,
        "abandoned": outcome_totals.get(
            loadclient.OUTCOME_ABANDONED, 0),
        "requests": [_result_row(r) for r in results],
        "slo_missed": missed_rows,
    }


def _attrs(ev: Dict[str, object]) -> Dict[str, object]:
    a = ev.get("attrs")
    return a if isinstance(a, dict) else {}


def _attainment_window(results: Sequence[RequestResult],
                       slo_class: str, lo_ms: float,
                       hi_ms: float) -> Optional[float]:
    rs = [r for r in results
          if r.req.slo_class == slo_class
          and lo_ms <= r.req.t_ms < hi_ms
          and r.slo_met is not None]
    if not rs:
        return None
    return round(sum(1 for r in rs if r.slo_met) / len(rs), 4)


# -- fleet mode ------------------------------------------------------------


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def _spawn_replica(idx: int, port: int, router_port: int,
                   args: argparse.Namespace,
                   session_dir: Optional[str] = None
                   ) -> "subprocess.Popen[bytes]":
    """One REAL replica subprocess — the CLI a pod runs — so a chaos
    kill is a kill (no graceful drain, sockets die mid-chunk)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = _repo_root() + os.pathsep \
        + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    cmd = [sys.executable, "-m",
           "tpu_k8s_device_plugin.workloads.server",
           "--config", args.config, "--n-slots", str(args.slots),
           "--max-len", str(args.max_len),
           "--max-new-tokens", str(args.max_new_tokens),
           "--window", "4", "--host", "127.0.0.1",
           "--port", str(port),
           "--register-with", f"http://127.0.0.1:{router_port}",
           "--replica-id", f"replay-{idx}",
           "--register-interval", "0.3"]
    if args.prefix_chunk > 0:
        cmd += ["--prefix-chunk", str(args.prefix_chunk)]
    if session_dir is not None:
        cmd += ["--kv-paging", "--session-tier",
                "--session-dir", session_dir,
                "--session-seed", str(args.seed)]
    for spec in args.slo or []:
        cmd += ["--slo", spec]
    return subprocess.Popen(cmd, env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)


def run_fleet(args: argparse.Namespace,
              requests: Sequence[TraceRequest],
              policies: Dict[str, SLOPolicy],
              metrics: ReplayMetrics,
              trace_header: Dict[str, object]) -> Dict[str, object]:
    """Spawn an in-process router + N real replica subprocesses,
    replay the trace through the router, optionally SIGKILL the last
    replica at ``--kill-replica-at-ms`` (trace time), and build the
    report with a journal/metric-proven ``chaos`` section."""
    from .qos import parse_tenant_quotas
    from .router import RouterServer

    rt = RouterServer(statz_interval_s=0.5, replica_ttl_s=5.0,
                      breaker_reset_s=0.5, seed=args.seed,
                      tenant_quotas=parse_tenant_quotas(
                          getattr(args, "tenant_quota", None)))
    rt.start(host="127.0.0.1", port=0)
    procs: List["subprocess.Popen[bytes]"] = []
    victim_idx = args.replicas - 1

    def fleet_healthy(body: Dict[str, object]) -> bool:
        reps = body.get("replicas")
        if not isinstance(reps, list):
            return False
        healthy = sum(1 for r in reps
                      if isinstance(r, dict) and r.get("healthy"))
        return healthy >= args.replicas

    tier_root: Optional[str] = None
    if getattr(args, "session_tier", False):
        # one crash-safe spill dir per replica: exactly what a pod's
        # emptyDir/PVC mount gives the tiering layer in production
        tier_root = tempfile.mkdtemp(prefix="replay-kvs-")
    try:
        ports = [loadclient.free_port() for _ in range(args.replicas)]
        for idx, port in enumerate(ports):
            sdir = None if tier_root is None \
                else os.path.join(tier_root, f"r{idx}")
            procs.append(_spawn_replica(idx, port, rt.port, args,
                                        session_dir=sdir))
        for port in ports:
            loadclient.wait_http_ok(port, "/healthz", 600.0)
        loadclient.wait_http_ok(rt.port, "/replicas", 60.0,
                                fleet_healthy)
        log.info("fleet up: router :%d, %d replicas", rt.port,
                 args.replicas)

        hooks: List[Tuple[float, Callable[[], None]]] = []
        if args.kill_replica_at_ms is not None:
            def kill_victim() -> None:
                log.info("chaos: SIGKILL replay-%d at trace t=%.0fms",
                         victim_idx, args.kill_replica_at_ms)
                procs[victim_idx].kill()
            hooks.append((args.kill_replica_at_ms / 1000.0
                          / args.time_scale, kill_victim))

        results = replay_trace(
            requests, "127.0.0.1", rt.port, policies=policies,
            metrics=metrics, time_scale=args.time_scale,
            late_ms=args.late_ms, timeout_s=args.timeout_s,
            hooks=hooks)

        # recovery probes: after the trace drains, the router must
        # still serve — through the survivors — before we scrape
        probes_ok = 0
        n_probes = 3
        for _ in range(n_probes):
            probe = loadclient.stream_request(
                "127.0.0.1", rt.port,
                {"tokens": list(requests[0].tokens[:8]) or [1],
                 "max_new_tokens": 4, "ignore_eos": True},
                timeout_s=60.0)
            if probe.outcome == loadclient.OUTCOME_OK:
                probes_ok += 1

        # the router proves the death two ways: the breaker opens on
        # the next request routed at the corpse, and the statz sweep
        # evicts the silent replica after its TTL.  The eviction is
        # clock-bound — on a trace whose tail is shorter than the TTL
        # it lands AFTER the last request, so wait for the journal
        # entry (bounded) before scraping the evidence.
        if args.kill_replica_at_ms is not None:
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline \
                    and not rt.recorder.events(
                        name="tpu_router_replica_evicted"):
                time.sleep(0.2)

        report = build_report(
            results, policies, trace_header=trace_header,
            target=f"router:127.0.0.1:{rt.port} "
                   f"({args.replicas} replicas)",
            time_scale=args.time_scale, late_ms=args.late_ms,
            debug_port=rt.port, top_missed=args.top_missed)

        # server-side join: the router's own goodput surfaces
        try:
            report["fleet_statz"] = loadclient.fetch_json(
                rt.port, "/fleet/statz", timeout_s=30.0)
        except (OSError, ValueError) as e:
            log.warning("fleet statz unavailable: %s", e)
            report["fleet_statz"] = None
        samples = obs.parse_exposition(rt.registry.render())
        counters = {"tpu_router_failovers_total": 0.0,
                    "tpu_router_replica_evictions_total": 0.0}
        aborts = 0.0
        for name, labels, value in samples:
            if name in counters:
                counters[name] += value
            if name == "tpu_router_requests_total" \
                    and labels.get("outcome") == "stream_abort":
                aborts += value
        report["router_metrics"] = dict(counters,
                                        stream_aborts=aborts)

        if args.kill_replica_at_ms is not None:
            kill_ms = args.kill_replica_at_ms
            victim = f"replay-{victim_idx}"
            names = [str(e.get("name", ""))
                     for e in rt.recorder.events()]
            opened = [
                e for e in rt.recorder.events(
                    name="tpu_breaker_transition")
                if str(_attrs(e).get("op", "")).endswith(victim)
                and _attrs(e).get("to") == "open"]
            evicted = [
                e for e in rt.recorder.events(
                    name="tpu_router_replica_evicted")
                if _attrs(e).get("replica") == victim]
            report["chaos"] = {
                "killed_replica": victim,
                "kill_at_trace_ms": kill_ms,
                "breaker_opened": bool(opened),
                "replica_evicted": bool(evicted),
                "stream_aborts": aborts,
                "failovers": counters["tpu_router_failovers_total"],
                "stream_abort_journaled":
                    "tpu_router_stream_abort" in names,
                "recovery_probes_ok": probes_ok,
                "recovery_probes": n_probes,
                # client-side attainment around the kill: the trough
                # and the recovery, per class — the gate's evidence
                "attainment_windows": {
                    name: {
                        "pre_kill": _attainment_window(
                            results, name, 0.0, kill_ms),
                        "kill_window": _attainment_window(
                            results, name, kill_ms,
                            kill_ms + CHAOS_SETTLE_MS),
                        "post_kill": _attainment_window(
                            results, name,
                            kill_ms + CHAOS_SETTLE_MS,
                            float("inf")),
                    } for name in policies},
            }
        return report
    finally:
        for proc in procs:
            proc.kill()
        for proc in procs:
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                log.warning("replica pid %d did not exit", proc.pid)
        rt.stop()
        if tier_root is not None:
            shutil.rmtree(tier_root, ignore_errors=True)


# -- CLI -------------------------------------------------------------------


def _parse_goodput_specs(specs: Sequence[str]
                         ) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for spec in specs:
        name, _, val = spec.partition("=")
        if not name or not val:
            raise ValueError(
                f"bad --assert-goodput {spec!r} (want CLASS=RATIO)")
        floor = float(val)
        if not 0.0 <= floor <= 1.0:
            raise ValueError(
                f"--assert-goodput {spec!r}: attainment is a ratio "
                f"in [0, 1], a floor of {floor} can never pass")
        out[name] = floor
    return out


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description="Open-loop replay of a tpu-trace/v1 file with "
                    "per-class SLO attribution")
    p.add_argument("--trace", required=True)
    p.add_argument("--target", default=None, metavar="HOST:PORT",
                   help="existing server/router endpoint; mutually "
                        "exclusive with --replicas")
    p.add_argument("--replicas", type=int, default=0,
                   help="spawn this many real replica subprocesses "
                        "behind an in-process router")
    p.add_argument("--config", default="tiny",
                   help="model config for spawned replicas")
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--max-len", type=int, default=2048)
    p.add_argument("--max-new-tokens", type=int, default=512)
    p.add_argument("--prefix-chunk", type=int, default=0,
                   help="replica APC chunk (match the trace's)")
    p.add_argument("--seed", type=int, default=0,
                   help="router seed in fleet mode")
    p.add_argument("--session-tier", action="store_true",
                   help="spawn replicas with --kv-paging "
                        "--session-tier and a per-replica spill dir "
                        "so sessioned traces warm-resume parked KV "
                        "(the report's sessions block splits warm vs "
                        "cold TTFT)")
    p.add_argument("--kill-replica-at-ms", type=float, default=None,
                   help="SIGKILL the last spawned replica at this "
                        "TRACE time (fleet mode only)")
    p.add_argument("--slo", action="append", default=None,
                   metavar="CLASS=ttft_ms[:deadline_ms]",
                   help="client-side SLO policies (same grammar as "
                        "the server; default interactive+batch)")
    p.add_argument("--time-scale", type=float, default=1.0,
                   help=">1 replays faster than recorded")
    p.add_argument("--late-ms", type=float, default=100.0)
    p.add_argument("--timeout-s", type=float, default=120.0)
    p.add_argument("--report", default=None, metavar="FILE")
    p.add_argument("--metrics-out", default=None, metavar="FILE",
                   help="write the tpu_replay_* exposition here")
    p.add_argument("--tenant-quota", action="append", default=None,
                   metavar="NAME=RATE[:BURST[:WEIGHT]]",
                   help="fleet mode: router-level per-tenant token "
                        "quota (same grammar as the router flag) so "
                        "replayed multi-tenant traffic exercises the "
                        "quota buckets")
    p.add_argument("--assert-goodput", action="append", default=None,
                   metavar="CLASS=RATIO|tenant:NAME=RATIO",
                   help="fail (exit 1) if a class's — or, with the "
                        "tenant: prefix, a tenant's — attainment is "
                        "below RATIO (repeatable)")
    p.add_argument("--assert-warm-resume", nargs="?", const="",
                   default=None, metavar="BASELINE_REPORT",
                   help="gate: revisit (warm) TTFT p95 must come in "
                        "strictly below cold re-prefill p95.  With a "
                        "BASELINE_REPORT (the same trace replayed "
                        "WITHOUT --session-tier) the cold side is "
                        "that report's revisit p95 — the same chains "
                        "re-prefilled from scratch, the honest "
                        "baseline.  Bare, the cold side is this "
                        "run's first-visit p95 (only meaningful when "
                        "chains stay near prompt length)")
    p.add_argument("--top-missed", type=int, default=5,
                   help="embed stitched spans for the slowest K "
                        "SLO-missed requests in the report")
    args = p.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    if bool(args.target) == bool(args.replicas):
        p.error("exactly one of --target / --replicas is required")
    if args.kill_replica_at_ms is not None and not args.replicas:
        p.error("--kill-replica-at-ms needs --replicas (fleet mode)")
    if args.session_tier and not args.replicas:
        p.error("--session-tier needs --replicas (fleet mode): it "
                "configures the spawned replica subprocesses")
    if args.assert_warm_resume is not None and not args.session_tier:
        p.error("--assert-warm-resume needs --session-tier (without "
                "tiering every revisit re-prefills cold)")

    header, requests = load_trace(args.trace)
    policies = obs.parse_slo_specs(args.slo) if args.slo \
        else obs.default_slo_policies()
    registry = obs.Registry()
    metrics = ReplayMetrics(registry, policies)

    if args.replicas:
        report = run_fleet(args, requests, policies, metrics, header)
    else:
        host, _, port_s = args.target.rpartition(":")
        host = host or "127.0.0.1"
        port = int(port_s)
        results = replay_trace(
            requests, host, port, policies=policies,
            metrics=metrics, time_scale=args.time_scale,
            late_ms=args.late_ms, timeout_s=args.timeout_s)
        report = build_report(
            results, policies, trace_header=header,
            target=args.target, time_scale=args.time_scale,
            late_ms=args.late_ms, debug_port=port, debug_host=host,
            top_missed=args.top_missed)
        try:
            report["statz"] = loadclient.fetch_json(
                port, "/statz", timeout_s=30.0, host=host)
        except (OSError, ValueError) as e:
            log.warning("statz unavailable on %s: %s",
                        args.target, e)
            report["statz"] = None

    classes = report["classes"]
    assert isinstance(classes, dict)
    attain = {name: info["attainment"]
              for name, info in classes.items()}
    metrics.set_attainment({k: float(v) for k, v in attain.items()})

    if args.metrics_out:
        with open(args.metrics_out, "w", encoding="utf-8") as fh:
            fh.write(registry.render())
    if args.report:
        with open(args.report, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
            fh.write("\n")
    print(json.dumps({
        "target": report["target"], "classes": classes,
        "outcomes": report["outcomes"],
        "late_dispatches": report["open_loop"],
        "chaos": report.get("chaos"),
    }, indent=2, sort_keys=True))

    tenants = report.get("tenants")
    tenants = tenants if isinstance(tenants, dict) else {}
    rc = 0
    for name, floor in _parse_goodput_specs(
            args.assert_goodput or []).items():
        if name.startswith("tenant:"):
            row = tenants.get(name.partition(":")[2])
            got = row.get("attainment") \
                if isinstance(row, dict) else None
        else:
            got = attain.get(name)
        if got is None or float(got) < floor:
            print(f"GOODPUT GATE FAIL: class {name} attainment "
                  f"{got} < {floor}", file=sys.stderr)
            rc = 1
        else:
            print(f"goodput gate ok: class {name} attainment "
                  f"{got} >= {floor}")
    if args.assert_warm_resume is not None:
        rc = max(rc, _warm_resume_gate(report,
                                       args.assert_warm_resume))
    return rc


def _revisit_p95(report: Dict[str, object],
                 bucket: str) -> Optional[float]:
    sessions = report.get("sessions")
    if not isinstance(sessions, dict):
        return None
    stats = sessions.get(bucket)
    if not isinstance(stats, dict):
        return None
    ttft = stats.get("ttft_ms")
    if not isinstance(ttft, dict):
        return None
    p95 = ttft.get("p95")
    return float(p95) if isinstance(p95, (int, float)) else None


def _warm_resume_gate(report: Dict[str, object],
                      baseline_path: str) -> int:
    """Warm revisits (tier hits) must beat cold re-prefill on TTFT
    p95.  With a baseline report the cold side is the SAME revisit
    chains replayed without tiering — the honest like-for-like;
    without one it is this run's first-visit p95."""
    w_p95 = _revisit_p95(report, "warm")
    if baseline_path:
        with open(baseline_path, encoding="utf-8") as fh:
            baseline = json.load(fh)
        c_p95 = _revisit_p95(baseline, "warm")
        cold_name = f"re-prefill p95 ({baseline_path})"
    else:
        c_p95 = _revisit_p95(report, "cold")
        cold_name = "first-visit p95"
    if w_p95 is None or c_p95 is None:
        print(f"WARM-RESUME GATE FAIL: missing revisit TTFT stats "
              f"(warm={w_p95}, cold={c_p95}) — did the trace carry "
              f"sessioned requests?", file=sys.stderr)
        return 1
    if w_p95 >= c_p95:
        print(f"WARM-RESUME GATE FAIL: warm revisit TTFT p95 "
              f"{w_p95:.1f}ms not below {cold_name} {c_p95:.1f}ms",
              file=sys.stderr)
        return 1
    print(f"warm-resume gate ok: warm revisit TTFT p95 "
          f"{w_p95:.1f}ms < {cold_name} {c_p95:.1f}ms")
    return 0


if __name__ == "__main__":
    sys.exit(main())
