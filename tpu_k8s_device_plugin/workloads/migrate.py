"""Exact wire codec for KV-checkpoint migration between replicas.

Disaggregated prefill/decode serving ships a finished prefill's engine
checkpoint (``ServingEngine.preempt``'s state dict: the raw paged KV
snapshot plus every host mirror — outputs, knobs, draw chains, grammar
state) from a prefill-class replica to a decode-class one over
``POST /migrate``.  The checkpoint round-trip must be EXACT — resume
on the far side has to be bit-identical to resume in-process, which is
what makes disagg output byte-identical to single-replica serving —
so this module is a tiny tagged binary format, not pickle (an internal
endpoint still should not execute attacker-supplied bytecode) and not
plain JSON (float round-trips and dtype fidelity are the whole point).

Layout::

    MAGIC | u64 header_len | header JSON (utf-8) | blob 0 | blob 1 ...

The header is a JSON tree in which every non-JSON value is a tagged
object: numpy/jax arrays become ``{"__nd__": i, "dtype", "shape"}``
referencing the i-th raw little-endian blob, tuples / frozensets /
bytes / non-finite floats / non-string-keyed dicts get their own tags.
Everything is deterministic and dependency-free (numpy only), so both
the jax-heavy replica and the jax-free router can move the payload
around; only the two replicas ever DECODE it.
"""

from __future__ import annotations

import base64
import json
import math
import struct
from typing import Any, Dict, List

import numpy as np

__all__ = ["dump_payload", "load_payload", "MIGRATE_CONTENT_TYPE",
           "MigrateError"]

#: the internal replica-to-replica content type the router forwards
#: opaquely (a replica answering a prefill_only request with anything
#: else is a decline, handled by normal pass-through)
MIGRATE_CONTENT_TYPE = "application/x-tpu-kv-migrate"

_MAGIC = b"TPUMIG1\n"


class MigrateError(ValueError):
    """A payload that is not a well-formed migration container."""


def _enc(obj: Any, blobs: List[bytes]) -> Any:
    """Tree -> JSON-safe tree, appending array storage to *blobs*."""
    if obj is None or isinstance(obj, (bool, int, str)):
        return obj
    if isinstance(obj, float):
        if math.isfinite(obj):
            return obj
        return {"__f__": repr(obj)}          # inf/-inf/nan, exact
    if isinstance(obj, bytes):
        return {"__b__": base64.b64encode(obj).decode("ascii")}
    if isinstance(obj, np.generic):
        # numpy scalar: a 0-d array round-trips dtype AND value
        obj = np.asarray(obj)
    if isinstance(obj, np.ndarray) or hasattr(obj, "__array__"):
        # numpy or jax array (device arrays fetch host-side here);
        # raw little-endian C-order bytes are the exactness guarantee.
        # Shape is taken BEFORE ascontiguousarray — that call promotes
        # 0-d scalars to shape (1,)
        arr = np.asarray(obj)
        shape = list(arr.shape)
        arr = np.ascontiguousarray(arr)
        if arr.dtype.byteorder == ">":
            arr = arr.astype(arr.dtype.newbyteorder("<"))
        # dtype by NAME, not .str: ml_dtypes extension types (the
        # bf16 KV pools!) stringify as opaque void ("<V2") and would
        # decode to raw bytes jit rejects; np.dtype("bfloat16")
        # resolves through the registered extension on both ends,
        # and builtin names ("float32", "int8") are endian-free —
        # the bytes above are already little-endian
        blobs.append(arr.tobytes())
        return {"__nd__": len(blobs) - 1,
                "dtype": arr.dtype.name,
                "shape": shape}
    if isinstance(obj, tuple):
        return {"__t__": [_enc(v, blobs) for v in obj]}
    if isinstance(obj, frozenset):
        # sort for determinism (members are token ids in practice)
        return {"__fs__": [_enc(v, blobs) for v in sorted(obj)]}
    if isinstance(obj, list):
        return [_enc(v, blobs) for v in obj]
    if isinstance(obj, dict):
        # tagged pair list: checkpoint dicts key on ints (layer
        # indices, copy indices) as well as strings, and JSON would
        # silently stringify them
        return {"__d__": [[_enc(k, blobs), _enc(v, blobs)]
                          for k, v in obj.items()]}
    raise MigrateError(
        f"migration payload cannot carry {type(obj).__name__}")


def _dec(node: Any, blobs: List[memoryview]) -> Any:
    if node is None or isinstance(node, (bool, int, float, str)):
        return node
    if isinstance(node, list):
        return [_dec(v, blobs) for v in node]
    if not isinstance(node, dict):
        raise MigrateError(f"bad node {type(node).__name__}")
    if "__f__" in node:
        return float(node["__f__"])
    if "__b__" in node:
        return base64.b64decode(node["__b__"])
    if "__nd__" in node:
        i = int(node["__nd__"])
        if not 0 <= i < len(blobs):
            raise MigrateError(f"blob index {i} out of range")
        arr = np.frombuffer(
            blobs[i], dtype=np.dtype(node["dtype"])
        ).reshape(node["shape"]).copy()
        return arr
    if "__t__" in node:
        return tuple(_dec(v, blobs) for v in node["__t__"])
    if "__fs__" in node:
        return frozenset(_dec(v, blobs) for v in node["__fs__"])
    if "__d__" in node:
        return {_dec(k, blobs): _dec(v, blobs)
                for k, v in node["__d__"]}
    raise MigrateError(f"unknown tag in {sorted(node)[:3]}")


def dump_payload(obj: Dict[str, Any]) -> bytes:
    """Serialize one migration payload (the /migrate wire body)."""
    blobs: List[bytes] = []
    tree = _enc(obj, blobs)
    sizes = [len(b) for b in blobs]
    header = json.dumps({"tree": tree, "blobs": sizes},
                        separators=(",", ":")).encode()
    return b"".join([_MAGIC, struct.pack("<Q", len(header)), header]
                    + blobs)


def load_payload(data: bytes) -> Dict[str, Any]:
    """Inverse of :func:`dump_payload`; raises :class:`MigrateError`
    on anything malformed (the /migrate handler answers 400)."""
    if not data.startswith(_MAGIC):
        raise MigrateError("not a migration payload (bad magic)")
    off = len(_MAGIC)
    if len(data) < off + 8:
        raise MigrateError("truncated header length")
    (hlen,) = struct.unpack_from("<Q", data, off)
    off += 8
    if len(data) < off + hlen:
        raise MigrateError("truncated header")
    try:
        header = json.loads(data[off:off + hlen])
    except ValueError as e:
        raise MigrateError(f"bad header JSON: {e}") from e
    off += hlen
    if not isinstance(header, dict) or "tree" not in header:
        raise MigrateError("header missing 'tree'")
    blobs: List[memoryview] = []
    view = memoryview(data)
    for size in header.get("blobs", []):
        size = int(size)
        if len(data) < off + size:
            raise MigrateError("truncated blob section")
        blobs.append(view[off:off + size])
        off += size
    out = _dec(header["tree"], blobs)
    if not isinstance(out, dict):
        raise MigrateError("payload root must be a dict")
    return out
